"""Invariant workloads: atomic-op ledger accounting and write-skew prevention.

The full reference-shaped AtomicOps / Serializability workloads live in
atomic_ops.py / serializability.py; these two are their lightweight,
chaos-cheap cousins kept for the randomized sweeps.

Ref: fdbserver/workloads/AtomicOps.actor.cpp (per-actor ADD streams whose
ledger and sum tables must agree) and the Serializability family — two
transactions reading overlapping state and writing based on it must never
both commit (classic write-skew).
"""

from __future__ import annotations

from ..client.types import MutationType
from ..flow.error import FdbError
from .base import TestWorkload


class AtomicLedgerWorkload(TestWorkload):
    """Each actor streams ADDs into a per-actor log key AND a shared total;
    the check phase asserts the shared total equals the sum of the logs
    (ref: AtomicOps' log/ops table comparison)."""

    name = "atomic_ledger"

    def __init__(self, actors: int = 3, ops: int = 20, prefix: bytes = b"ao/"):
        self.actors = actors
        self.ops = ops
        self.prefix = prefix

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        rng = cluster.loop.rng

        def actor(aid):
            async def go():
                for _ in range(self.ops):
                    amount = int(rng.random_int(1, 100))

                    async def op(tr, amount=amount):
                        enc = amount.to_bytes(8, "little")
                        tr.atomic_op(
                            MutationType.ADD_VALUE,
                            self.prefix + b"log/%02d" % aid,
                            enc,
                        )
                        tr.atomic_op(
                            MutationType.ADD_VALUE, self.prefix + b"total", enc
                        )

                    await db.run(op)

            return go()

        await all_of(
            [
                db.process.spawn(actor(a), f"ao_actor{a}")
                for a in range(self.actors)
            ]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def rd(tr):
            rows = await tr.get_range(
                self.prefix + b"log/", self.prefix + b"log0"
            )
            out["logs"] = sum(
                int.from_bytes(v, "little") for _k, v in rows
            )
            t = await tr.get(self.prefix + b"total")
            out["total"] = int.from_bytes(t or b"", "little")

        await db.run(rd)
        return out["total"] == out["logs"] and out["total"] > 0


class WriteSkewWorkload(TestWorkload):
    """Write-skew probes: pairs of transactions each read BOTH flag keys
    and set their own only if the other is unset; serializability admits at
    most one winner per round, and the check asserts no round ever ended
    with both flags set."""

    name = "write_skew"

    def __init__(self, rounds: int = 10, prefix: bytes = b"ser/"):
        self.rounds = rounds
        self.prefix = prefix

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        for r in range(self.rounds):
            ka = self.prefix + b"%03d/a" % r
            kb = self.prefix + b"%03d/b" % r

            def contender(mine, other):
                async def go():
                    tr = db.create_transaction()
                    try:
                        his = await tr.get(other)
                        if his is None:
                            tr.set(mine, b"1")
                        await tr.commit()
                    except FdbError as e:
                        if not e.is_retryable_in_transaction():
                            raise
                        # Lost the race: do NOT retry (the probe is
                        # one-shot; a retry would legitimately see the
                        # winner's flag and back off).

                return go()

            await all_of(
                [
                    db.process.spawn(contender(ka, kb), "ser_a"),
                    db.process.spawn(contender(kb, ka), "ser_b"),
                ]
            )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def rd(tr):
            out["rows"] = dict(
                await tr.get_range(self.prefix, self.prefix + b"\xff")
            )

        await db.run(rd)
        for r in range(self.rounds):
            a = out["rows"].get(self.prefix + b"%03d/a" % r)
            b = out["rows"].get(self.prefix + b"%03d/b" % r)
            if a is not None and b is not None:
                return False  # write skew: both contenders committed
        return True
