"""ConflictRange: conflicts happen EXACTLY when they should.

Ref: fdbserver/workloads/ConflictRange.actor.cpp — a transaction performs
a ranged read while another commits a mutation; the first must conflict
IFF the mutation intersects the range it actually observed.  Both
failure directions matter: a missed conflict is a serializability
violation, a spurious one means the resolver (the north-star engine) or
the client's conflict-range bookkeeping over-approximates — in
particular, a limit-truncated get_range must register only the extent it
returned (ref: RYW readThrough trimming on limited reads,
fdbclient/ReadYourWrites.actor.cpp).
"""

from __future__ import annotations

from ..client.types import key_after
from ..flow.error import FdbError
from .base import TestWorkload


class ConflictRangeWorkload(TestWorkload):
    name = "conflict_range"

    def __init__(self, keyspace: int = 60, iterations: int = 40,
                 prefix: bytes = b"cr/", seed_keys: int = 25):
        self.keyspace = keyspace
        self.iterations = iterations
        self.prefix = prefix
        self.seed_keys = seed_keys
        self.checked = 0
        self.conflicts = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self, db, cluster):
        rng = cluster.loop.rng

        async def fill(tr):
            for _ in range(self.seed_keys):
                i = int(rng.random_int(0, self.keyspace))
                tr.set(self._key(i), b"v%d" % i)

        await db.run(fill)

    async def start(self, db, cluster):
        rng = cluster.loop.rng
        for it in range(self.iterations):
            lo = int(rng.random_int(0, self.keyspace - 1))
            hi = int(rng.random_int(lo + 1, self.keyspace))
            limit = int(rng.random_int(1, 6))
            begin, end = self._key(lo), self._key(hi)

            reader = db.create_transaction()
            try:
                rows = await reader.get_range(begin, end, limit=limit)
            except FdbError:
                continue  # e.g. recovery window; nothing asserted
            # The extent the reader OBSERVED (and must conflict over).
            if len(rows) >= limit and rows:
                obs_end = key_after(rows[-1][0])
            else:
                obs_end = end

            # A second client commits one mutation strictly after the
            # reader's snapshot.
            mk = int(rng.random_int(0, self.keyspace))
            do_clear = rng.random_int(0, 3) == 0
            ck_end = min(self.keyspace, mk + 1 + int(rng.random_int(0, 4)))

            async def mutate(tr, mk=mk, do_clear=do_clear, ck_end=ck_end):
                if do_clear:
                    tr.clear_range(self._key(mk), self._key(ck_end))
                else:
                    tr.set(self._key(mk), b"m%d" % mk)

            await db.run(mutate)
            if do_clear:
                w_begin, w_end = self._key(mk), self._key(ck_end)
            else:
                w_begin, w_end = self._key(mk), key_after(self._key(mk))

            expect_conflict = (w_begin < obs_end) and (begin < w_end)
            reader.set(self.prefix + b"!dummy", b"%d" % it)
            try:
                await reader.commit()
                got_conflict = False
            except FdbError as e:
                if e.name == "not_committed":
                    got_conflict = True
                elif e.name in ("commit_unknown_result", "future_version",
                                "transaction_too_old"):
                    continue  # outcome unknowable; nothing asserted
                else:
                    raise
            assert got_conflict == expect_conflict, (
                f"iteration {it}: read [{begin}..{end}) limit={limit} "
                f"observed-through {obs_end}; mutation [{w_begin}..{w_end}) "
                f"=> expected conflict={expect_conflict}, got {got_conflict}"
            )
            self.checked += 1
            self.conflicts += int(got_conflict)

    async def check(self, db, cluster) -> bool:
        # Both behaviors must have been exercised, or the seed was vacuous.
        return self.checked >= self.iterations // 2 and (
            0 < self.conflicts < self.checked
        )
