"""TestWorkload base + the compound runner.

Ref: workloads.h:55 — each workload implements setup (populate), start
(run until done), check (verify invariants); tester.actor.cpp:239 runs the
spec's workloads CONCURRENTLY (chaos injectors overlap the invariant
workloads), then checks each.
"""

from __future__ import annotations

from typing import List


class TestWorkload:
    """One workload; subclasses override any subset of the phases."""

    name = "workload"

    async def setup(self, db, cluster) -> None:  # populate initial data
        return None

    async def start(self, db, cluster) -> None:  # run the workload
        return None

    async def check(self, db, cluster) -> bool:  # verify invariants
        return True


def run_workloads(
    cluster,
    workloads: List[TestWorkload],
    timeout_vt: float = 10000.0,
    quiet: bool = False,
):
    """Drive the phases like runTest (tester.actor.cpp:778): setups
    sequentially, starts concurrently (chaos overlaps load), checks
    sequentially; every check must return True.

    quiet=True waits for quiescence between start and check (ref:
    waitForQuietDatabase before the trailing consistency check,
    tester.actor.cpp:819 / QuietDatabase.actor.cpp:371) instead of relying
    on fixed virtual-time margins inside the checks."""
    from ..flow.eventloop import all_of

    db = cluster.database("tester")
    for wl in workloads:
        cluster.run_until(
            db.process.spawn(wl.setup(db, cluster), f"setup:{wl.name}"),
            timeout_vt=timeout_vt,
        )
    tasks = [
        db.process.spawn(wl.start(db, cluster), f"start:{wl.name}")
        for wl in workloads
    ]
    cluster.run_until(all_of(tasks), timeout_vt=timeout_vt)
    if quiet:
        from ..server.status import quiet_database

        cluster.run_until(
            db.process.spawn(quiet_database(db, cluster), "quiet_database"),
            timeout_vt=timeout_vt,
        )
    for wl in workloads:
        ok = cluster.run_until(
            db.process.spawn(wl.check(db, cluster), f"check:{wl.name}"),
            timeout_vt=timeout_vt,
        )
        assert ok, f"workload {wl.name} check failed"
    # Sim-end fault-site coverage (ref: the reference prints BUGGIFY
    # coverage per run): which chaos sites this seed actually exercised,
    # as registry gauges on the cluster + one trace event.
    from ..flow.buggify import publish_coverage
    from ..flow.metrics import MetricsRegistry
    from ..flow.trace import TraceEvent

    reg = MetricsRegistry("BuggifyCoverage")
    cov = publish_coverage(reg)
    cluster.buggify_coverage = reg
    TraceEvent("BuggifyCoverage").detail(
        "sites_seen", cov["sites_seen"]
    ).detail("sites_activated", cov["sites_activated"]).detail(
        "sites_fired", cov["sites_fired"]
    ).log()
