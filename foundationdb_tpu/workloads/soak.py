"""Sustained chaos-soak harness: the millions-of-users rehearsal (ISSUE 8).

Every chaos and perf claim before this was a short sim run or a
single-process bench; this module proves the system *stays up* under
sustained load while faults fire.  It drives configurable open/closed-loop
load with Zipf hot-key skew, mixed transaction shapes, and ramping arrival
rates against a rated cluster (SimCluster + Ratekeeper, or a DynamicCluster
whose controller recruits one), layers a scripted fault matrix on top —
process kills, one-directional clogs, a mid-soak device outage via
DeviceFaultInjector, recovery — and reports per-phase **goodput**
(committed transactions, not attempts; the metric PAPERS.md's
contention-management line says matters under overload), latency-chain
p99s, throttle/shed counts, the fault timeline, and the ratekeeper +
breaker transition logs.

Everything is virtual-time + DeterministicRandom: two same-seed runs
produce byte-identical reports (the replay gate tests/test_soak.py pins).
This harness is the regression gate later perf PRs (Pallas kernels,
multi-chip) reuse: `cli soak --format=json` emits a BENCH-style artifact.
"""

from __future__ import annotations

import bisect
import json
from dataclasses import dataclass, field
from typing import List, Optional

from ..flow.error import FdbError
from ..flow.knobs import g_knobs
from ..flow.latency_chain import (
    COMMIT_CHAIN,
    GRV_CHAIN,
    percentile,
    summarize_stages,
)


@dataclass
class SoakPhase:
    """One load phase.  Open loop: transactions ARRIVE at `arrival_tps`
    regardless of completions (the overload-capable mode); closed loop:
    `actors` clients each keep one transaction in flight."""

    name: str = "phase"
    duration: float = 5.0  # sim seconds
    arrival_tps: float = 50.0
    actors: int = 8
    # Shape mix (remainder = blind writes): fractions of arrivals.
    read_fraction: float = 0.25
    rmw_fraction: float = 0.5


@dataclass
class FaultEvent:
    """One scripted fault.  kinds: "kill" (process kill + revive; dynamic
    clusters only), "clog" (ONE-directional network clog — the grey
    failure where requests land but replies stall), "device_outage"
    (persistent dispatch outage on one resolver's device engine via
    DeviceFaultInjector.begin_outage/end_outage), "shard_kill" (ISSUE 15:
    the device outage scoped to ONE shard of a mesh-sharded resolver —
    only that shard's breaker opens and serves degraded off its mirror
    while the surviving shards keep the goodput floor on device;
    backend="sharded"), "shard_move" (ISSUE 18: a scripted live reshard
    — split points recomputed from occupancy quantiles and migrated
    mid-stream; composes with shard_kill to exercise the
    reshard-during-fault legality rules, deferred/degraded per seed)."""

    at: float = 0.0  # sim seconds from soak start
    kind: str = "clog"
    duration: float = 1.5  # clog/outage hold; kills recover via recruitment
    target: str = ""  # kill: role name (default storage0)
    shard: int = 0  # shard_kill: which shard's chip dies; shard_move:
    # target shard count for the scripted reshard (0 = keep current)


@dataclass
class SoakConfig:
    seed: int = 1
    cluster: str = "sim"  # sim | dynamic (kills need dynamic)
    backend: str = "jax"  # conflict backend (device faults need jax/hybrid)
    mode: str = "open"  # open | closed
    keys: int = 512
    zipf_theta: float = 0.9  # 0 = uniform
    value_bytes: int = 32
    # Distinct client Database handles the load fans over.  One handle's
    # GRV batcher coalesces concurrent read-version fetches into a single
    # in-flight request, so proxy-side admission (queue depth, shed) only
    # sees real pressure when many CLIENTS contend — the thing a
    # millions-of-users rehearsal is about.
    clients: int = 4
    phases: List[SoakPhase] = field(default_factory=list)
    faults: List[FaultEvent] = field(default_factory=list)
    max_in_flight: int = 512  # open-loop client-side cap (memory bound)
    max_attempts: int = 8  # per-transaction retry budget
    drain_timeout: float = 15.0  # sim seconds to wait for stragglers
    rk_sample_interval: float = 0.1
    n_resolvers: int = 1
    buggify: bool = False  # scripted faults only, by default
    # SLO: commit-chain p99 bound (sim seconds) and per-phase goodput
    # floor as a fraction of that phase's arrival rate (open loop) or an
    # absolute committed/s floor (closed loop).
    slo_commit_p99: float = 2.0
    goodput_floor_frac: float = 0.3
    goodput_floor_tps: float = 1.0
    # Knob overrides applied for the run (None = leave as configured).
    max_tps: Optional[float] = None
    grv_queue_max: Optional[int] = None
    degraded_tps_fraction: Optional[float] = None
    # Device key budget: a DynamicCluster's system-keyspace metadata keys
    # (\xff/keyServers/..., \xff/serverList/...) exceed the default
    # 16-byte device width, which would route every mixed batch to the
    # CPU mirror; widen so the device path actually serves the soak.
    device_key_words: Optional[int] = None
    device_key_bytes: Optional[int] = None
    # backend="sharded" (ISSUE 15): shard count for the mesh-sharded
    # resolver 0 conflict set (sim clusters only; capped to the visible
    # device count).
    sharded_shards: int = 4
    # Elastic resharding (ISSUE 18): ceiling for live shard-count
    # scaling (None = frozen at sharded_shards; >sharded_shards hands
    # the conflict set the full device list so the balancer can scale).
    sharded_max_shards: Optional[int] = None
    # Period of the soak's ShardBalancer driver (sim seconds; None/0
    # disables).  The driver feeds the balancer the ratekeeper's binding
    # signal as admission pressure and the resolver's decayed
    # witness-range sample as per-shard load.
    shard_balance_seconds: Optional[float] = None
    # Rotate the Zipf ranks through key space: hot rank r maps to key
    # index (r + hot_offset) % keys, so the hot set can be pinned to a
    # chosen shard's interior instead of always key 0 (default 0 keeps
    # every existing workload byte-identical).
    hot_offset: int = 0
    # Witness-guided retry arm (ISSUE 17): None leaves the live
    # FDB_TPU_WITNESS_RETRY flag alone; True/False overrides it for the
    # run (restored after) — the A/B seam run_contention_ab drives.
    witness_retry: Optional[bool] = None


def default_phases(peak_tps: float, total_seconds: float) -> List[SoakPhase]:
    """The ramp the ISSUE asks for: warm -> ramp -> peak -> cooldown, with
    the peak phase taking half the budget (where the fault matrix fires)."""
    return [
        SoakPhase("warm", total_seconds * 0.15, peak_tps * 0.3),
        SoakPhase("ramp", total_seconds * 0.2, peak_tps * 0.6),
        SoakPhase("peak", total_seconds * 0.5, peak_tps),
        SoakPhase("cooldown", total_seconds * 0.15, peak_tps * 0.4),
    ]


def default_faults(
    total_seconds: float, kills: bool
) -> List[FaultEvent]:
    """The scripted matrix: a process kill early in the peak phase, a
    one-directional clog mid-peak, a device outage late-peak — each with
    recovery room before the next (the test asserts the ratekeeper
    throttles DURING each window and releases after)."""
    out = []
    if kills:
        out.append(FaultEvent(at=total_seconds * 0.40, kind="kill",
                              target="tlog0",
                              duration=min(2.5, total_seconds * 0.05)))
    out.append(FaultEvent(at=total_seconds * 0.55, kind="clog",
                          duration=min(2.0, total_seconds * 0.06)))
    out.append(FaultEvent(at=total_seconds * 0.75, kind="device_outage",
                          duration=min(2.0, total_seconds * 0.06)))
    return out


def shard_outage_phases(peak_tps: float, total_seconds: float) -> List[SoakPhase]:
    """The shard-outage phase family (ISSUE 15): steady load before,
    during, and after a one-shard chip loss — the during-phase goodput
    floor is the surviving-shards claim (one sick chip out of S costs
    ~1/S of capacity, NOT the lane)."""
    return [
        SoakPhase("pre_outage", total_seconds * 0.3, peak_tps),
        SoakPhase("shard_outage", total_seconds * 0.4, peak_tps),
        SoakPhase("recovery", total_seconds * 0.3, peak_tps),
    ]


def shard_outage_config(
    minutes: float = 0.5,
    peak_tps: float = 80.0,
    seed: int = 1,
    shard: int = 1,
    n_shards: int = 4,
) -> SoakConfig:
    """A soak whose only fault is a shard_kill covering the whole
    "shard_outage" phase (sim cluster, backend="sharded")."""
    total = minutes * 60.0
    cfg = default_config(
        minutes=minutes, peak_tps=peak_tps, seed=seed,
        cluster="sim", backend="sharded", faults=False,
    )
    cfg.phases = shard_outage_phases(peak_tps, total)
    cfg.faults = [
        FaultEvent(at=total * 0.3, kind="shard_kill",
                   duration=total * 0.4, shard=shard)
    ]
    cfg.sharded_shards = n_shards
    return cfg


def hot_key_rebalance_phases(
    peak_tps: float, total_seconds: float
) -> List[SoakPhase]:
    """The hot-key rebalance phase family (ISSUE 18): RMW-heavy Zipf
    load pins one shard, a scripted shard_kill degrades it, and the
    balancer's live reshard migrates the hot range onto healthy
    devices — the "recovered" phase is where the per-shard goodput
    claim is scored."""
    hot = dict(read_fraction=0.1, rmw_fraction=0.8)
    return [
        SoakPhase("warm", total_seconds * 0.15, peak_tps * 0.5, **hot),
        SoakPhase("hot_pin", total_seconds * 0.35, peak_tps, **hot),
        SoakPhase("rebalance", total_seconds * 0.3, peak_tps, **hot),
        SoakPhase("recovered", total_seconds * 0.2, peak_tps, **hot),
    ]


def hot_key_rebalance_config(
    minutes: float = 0.5,
    peak_tps: float = 80.0,
    seed: int = 1,
    n_shards: int = 4,
    max_shards: int = 8,
    zipf_theta: float = 1.2,
    balance_seconds: Optional[float] = 1.0,
    outage: bool = True,
) -> SoakConfig:
    """A soak where a Zipf hot-key set is pinned to ONE shard's interior
    (hot_offset lands the hot ranks mid-keyspace, inside shard
    n_shards//4's range... i.e. away from the keyspace floor, which
    shard 0 owns forever), that shard's chip dies for most of the run,
    and the ShardBalancer — fed ratekeeper pressure + the witness
    contention sample — reshards/scales the mesh so the hot range
    migrates onto healthy devices.  balance_seconds=None is the
    "pinned" A/B arm: same seed, same load, same fault, no balancer."""
    total = minutes * 60.0
    cfg = default_config(
        minutes=minutes, peak_tps=peak_tps, seed=seed,
        cluster="sim", backend="sharded", faults=False,
        zipf_theta=zipf_theta,
    )
    cfg.phases = hot_key_rebalance_phases(peak_tps, total)
    cfg.sharded_shards = n_shards
    cfg.sharded_max_shards = max_shards
    cfg.shard_balance_seconds = balance_seconds
    # Hot ranks sit in the interior of shard n_shards//4 + 1's range —
    # a shard whose DEVICE keeps its index across a scale-up, so the
    # scripted outage stays scoped to it while the hot RANGE is free to
    # migrate onto healthy devices.
    cfg.hot_offset = (cfg.keys // n_shards) * (n_shards // 4) + (
        cfg.keys // (2 * n_shards)
    )
    if outage:
        hot_shard = (cfg.hot_offset * n_shards) // cfg.keys
        cfg.faults = [
            FaultEvent(at=total * 0.2, kind="shard_kill",
                       duration=total * 0.7, shard=hot_shard),
        ]
    return cfg


def hot_zipf_weights(keys: int, theta: float, offset: int) -> List[float]:
    """Per-key-index Zipf traffic weight under the hot_offset rotation
    (index i carries rank (i - offset) % keys's mass).  The scorer's
    side of _plan_txn's draw — deterministic, sums to 1."""
    cdf = zipf_cdf(keys, theta)
    mass = [cdf[0]] + [cdf[r] - cdf[r - 1] for r in range(1, keys)]
    return [mass[(i - offset) % keys] for i in range(keys)]


def contention_config(
    minutes: float = 0.25,
    peak_tps: float = 120.0,
    seed: int = 1,
    keys: int = 8,
    zipf_theta: float = 1.2,
    backend: str = "jax",
    witness_retry: Optional[bool] = None,
) -> SoakConfig:
    """High-contention Zipf soak (ISSUE 17): a tiny hot key set and an
    RMW-heavy mix drive the abort fraction past the contention-spike
    threshold, so the run exercises the whole provenance chain — device
    witnesses, the structured not_committed cause, the client retry
    hint, the contention report block, and the contention_spike flight-
    recorder capture.  No faults: contention IS the incident here."""
    total = minutes * 60.0
    hot = dict(read_fraction=0.0, rmw_fraction=1.0)
    return SoakConfig(
        seed=seed,
        cluster="sim",
        backend=backend,
        mode="open",
        keys=keys,
        zipf_theta=zipf_theta,
        phases=[
            SoakPhase("warm", total * 0.2, peak_tps * 0.5, **hot),
            SoakPhase("hot", total * 0.6, peak_tps, **hot),
            SoakPhase("cooldown", total * 0.2, peak_tps * 0.4, **hot),
        ],
        faults=[],
        # Contention arms score RELATIVE goodput (guided vs blind); a
        # same-key RMW storm legitimately aborts most attempts, so the
        # absolute floor only guards against total collapse.
        goodput_floor_frac=0.02,
        witness_retry=witness_retry,
    )


def default_config(
    minutes: float = 2.0,
    peak_tps: float = 120.0,
    seed: int = 1,
    cluster: str = "sim",
    backend: str = "jax",
    mode: str = "open",
    keys: int = 512,
    zipf_theta: float = 0.9,
    faults: bool = True,
) -> SoakConfig:
    total = minutes * 60.0
    return SoakConfig(
        seed=seed,
        cluster=cluster,
        backend=backend,
        mode=mode,
        keys=keys,
        zipf_theta=zipf_theta,
        phases=default_phases(peak_tps, total),
        faults=default_faults(total, kills=(cluster == "dynamic"))
        if faults
        else [],
        # Dynamic clusters mix system-keyspace metadata into the same
        # resolver: widen the device key budget so those batches stay
        # device-eligible (see SoakConfig.device_key_words).
        device_key_words=16 if cluster == "dynamic" else None,
        device_key_bytes=64 if cluster == "dynamic" else None,
    )


def zipf_cdf(n: int, theta: float) -> List[float]:
    """Cumulative Zipf(theta) weights over ranks 1..n (theta=0 uniform).
    O(n) once per soak; sampling is a binary search per draw."""
    total = 0.0
    cdf = []
    for k in range(1, n + 1):
        total += k ** (-theta) if theta > 0 else 1.0
        cdf.append(total)
    return [c / total for c in cdf]


def zipf_pick(rng, cdf: List[float]) -> int:
    """Rank index in [0, len(cdf)) — low indexes are the hot keys."""
    return bisect.bisect_left(cdf, rng.random01())


class _PhaseStats:
    """Mutable per-phase tallies (attributed to the phase a transaction
    STARTED in, so cross-boundary completions aren't double-counted)."""

    FIELDS = ("arrivals", "client_shed", "attempts", "committed",
              "conflicted", "too_old", "throttled", "other_errors",
              "failed", "exhausted")

    def __init__(self, name: str):
        self.name = name
        self.counts = {f: 0 for f in self.FIELDS}
        self.latencies: List[float] = []  # client-observed commit seconds
        self.t_start = 0.0
        self.t_end = 0.0
        self.ev_start = 0  # trace-collector event cursor at phase start
        self.ev_end = 0


class SoakRun:
    """One soak execution against a prepared cluster.  Use run_soak()
    unless you are composing the harness into a larger test."""

    def __init__(self, config: SoakConfig, cluster, dbs):
        self.config = config
        self.cluster = cluster
        self.dbs = list(dbs)
        self.db = self.dbs[0]  # driver actors run on the first client
        self._next_client = 0
        self.loop = cluster.loop
        # The soak's own random stream: forked from the loop rng so fault
        # scheduling never perturbs role-level sim decisions mid-run.
        self.rng = self.loop.rng.split()
        self.cdf = zipf_cdf(config.keys, config.zipf_theta)
        self.stats = [_PhaseStats(p.name) for p in config.phases]
        self.in_flight = 0
        self.fault_timeline: List[list] = []  # [t, kind, detail, t_end]
        # Sampled admission log: [t, limiting, tps] whenever the CURRENT
        # ratekeeper's binding signal changes — generation-proof (a
        # DynamicCluster recruits a fresh Ratekeeper per recovery, whose
        # own transitions log resets; this one spans the whole soak).
        self.admission_log: List[list] = []
        # Per-phase conflict-witness snapshots (ISSUE 12 satellite):
        # phase name -> {resolver: {aborts, topk}} captured at phase
        # end, so the report shows WHERE contention lived per phase
        # (the Zipf hot-key phases are the interesting rows).
        self.phase_witness: dict = {}
        # Per-phase shard-mesh cuts (ISSUE 18): phase name -> partition +
        # breaker states + per-shard shed counters at phase end — the
        # hot_key_rebalance A/B scorer's input.
        self.phase_shards: dict = {}
        self.balancer = None  # ShardBalancer when _balance_driver runs
        self._stop = False

    # -- cluster accessors ------------------------------------------------
    def current_ratekeeper(self):
        cluster = self.cluster
        if hasattr(cluster, "controllers"):
            try:
                return getattr(
                    cluster.acting_controller(), "ratekeeper", None
                )
            except RuntimeError:
                return None
        return getattr(cluster, "_soak_ratekeeper", None)

    def _resolver_conflict_sets(self):
        from ..server.status import role_objects

        out = []
        for r in role_objects(self.cluster, "resolver"):
            cs = getattr(r, "conflicts", None)
            if cs is not None and getattr(cs, "_jax", None) is not None:
                out.append((r, cs))
        return out

    def _witness_snapshot(self) -> dict:
        """resolver -> conflict_witness() at this instant (cumulative
        counters; per-phase deltas are derivable from successive phase
        rows).  Deterministic: counts + canonical-JSON top-K only."""
        from ..server.status import role_objects

        out = {}
        for r in role_objects(self.cluster, "resolver"):
            cw = getattr(r, "conflict_witness", None)
            if callable(cw):
                out[r.process.name] = cw()
        return out

    # -- transaction plans ------------------------------------------------
    def _key(self, idx: int) -> bytes:
        return b"soak/%06d" % idx

    def _plan_txn(self, rng, phase: SoakPhase):
        """Decide shape + keys AT ARRIVAL (one deterministic draw order,
        independent of task interleaving)."""
        r = rng.random01()
        if r < phase.read_fraction:
            kind = "read"
        elif r < phase.read_fraction + phase.rmw_fraction:
            kind = "rmw"
        else:
            kind = "write"
        nkeys = 1 + int(rng.random_int(0, 3))
        off, nk = self.config.hot_offset, self.config.keys
        keys = sorted(
            {(zipf_pick(rng, self.cdf) + off) % nk for _ in range(nkeys)}
        )
        return kind, keys, int(rng.random_int(0, 1 << 30))

    async def _apply(self, tr, plan):
        kind, keys, salt = plan
        pad = max(1, self.config.value_bytes)
        if kind == "read":
            for ki in keys:
                await tr.get(self._key(ki))
        elif kind == "rmw":
            for ki in keys:
                v = await tr.get(self._key(ki))
                n = int(v.split(b":")[0]) if v else 0
                tr.set(
                    self._key(ki),
                    b"%d:%s" % (n + 1, b"x" * (pad - 1)),
                )
        else:
            for ki in keys:
                tr.set(self._key(ki), b"%d:%s" % (salt, b"w" * (pad - 1)))

    def _classify(self, st: _PhaseStats, e: FdbError):
        c = st.counts
        if e.name == "not_committed":
            c["conflicted"] += 1
        elif e.name == "transaction_too_old":
            c["too_old"] += 1
        elif e.name in (
            "batch_transaction_throttled",
            "proxy_memory_limit_exceeded",
        ):
            c["throttled"] += 1
        else:
            c["other_errors"] += 1

    async def _run_txn(self, db, plan, pi: int):
        st = self.stats[pi]
        loop = self.loop
        t0 = loop.now()
        tr = db.create_transaction()
        try:
            for _attempt in range(self.config.max_attempts):
                st.counts["attempts"] += 1
                try:
                    await self._apply(tr, plan)
                    await tr.commit()
                    st.counts["committed"] += 1
                    st.latencies.append(loop.now() - t0)
                    return
                except FdbError as e:
                    self._classify(st, e)
                    try:
                        # Exponential backoff + DeterministicRandom jitter
                        # (Transaction.on_error) — exactly how throttled
                        # clients are supposed to retreat.
                        await tr.on_error(e)
                    except FdbError:
                        st.counts["failed"] += 1
                        return
            st.counts["exhausted"] += 1
        finally:
            self.in_flight -= 1

    # -- drivers ----------------------------------------------------------
    async def _load_driver(self):
        from ..flow.eventloop import all_of
        from ..flow.trace import global_collector

        loop = self.loop
        col = global_collector()
        for pi, phase in enumerate(self.config.phases):
            st = self.stats[pi]
            st.t_start = loop.now()
            st.ev_start = len(col.events)
            end = loop.now() + phase.duration
            if self.config.mode == "open":
                rate = max(phase.arrival_tps, 1e-6)
                while loop.now() < end:
                    await loop.delay(1.0 / rate)
                    st.counts["arrivals"] += 1
                    if self.in_flight >= self.config.max_in_flight:
                        # Client-side cap: an overloaded open loop bounds
                        # its own memory; the drop is COUNTED, never
                        # silent (no-silent-caps discipline).
                        st.counts["client_shed"] += 1
                        continue
                    plan = self._plan_txn(self.rng, phase)
                    db = self.dbs[self._next_client]
                    self._next_client = (
                        self._next_client + 1
                    ) % len(self.dbs)
                    self.in_flight += 1
                    db.process.spawn(
                        self._run_txn(db, plan, pi), "soak_txn"
                    )
            else:
                tasks = [
                    self.db.process.spawn(
                        self._closed_actor(
                            self.dbs[ai % len(self.dbs)], pi, phase, end
                        ),
                        f"soak_actor{ai}",
                    )
                    for ai in range(phase.actors)
                ]
                await all_of(tasks)
            st.t_end = loop.now()
            st.ev_end = len(col.events)
            self.phase_witness[st.name] = self._witness_snapshot()
            self.phase_shards[st.name] = self._shard_snapshot()
        # Drain stragglers (bounded): goodput counts completions, and a
        # hung tail must fail the SLO rather than hang the harness.
        deadline = loop.now() + self.config.drain_timeout
        while self.in_flight > 0 and loop.now() < deadline:
            await loop.delay(0.05)
        self._stop = True

    async def _closed_actor(self, db, pi: int, phase: SoakPhase, end: float):
        loop = self.loop
        rng = self.rng.split()
        while loop.now() < end:
            st = self.stats[pi]
            st.counts["arrivals"] += 1
            plan = self._plan_txn(rng, phase)
            self.in_flight += 1
            await self._run_txn(db, plan, pi)

    async def _fault_driver(self):
        loop = self.loop
        t0 = loop.now()
        for ev in sorted(self.config.faults, key=lambda e: (e.at, e.kind)):
            dt = t0 + ev.at - loop.now()
            if dt > 0:
                await loop.delay(dt)
            if ev.kind == "kill":
                await self._fault_kill(ev)
            elif ev.kind == "clog":
                await self._fault_clog(ev)
            elif ev.kind == "device_outage":
                await self._fault_device_outage(ev)
            elif ev.kind == "shard_kill":
                await self._fault_shard_kill(ev)
            elif ev.kind == "shard_move":
                await self._fault_shard_move(ev)
            else:
                raise ValueError(f"unknown fault kind {ev.kind!r}")

    async def _capture_fault_window(self, delay: float, kind: str, detail):
        """Automatic per-fault-window flight-recorder capture (ISSUE 10):
        freeze the telemetry window once the fault's hold has elapsed, so
        the artifact contains the whole degraded window plus whatever
        trigger captures (breaker open, ratekeeper throttle) fired inside
        it.  Explicit capture — bypasses the trigger cooldown by design."""
        from ..flow.flight_recorder import global_flight_recorder

        if delay > 0:
            await self.loop.delay(delay)
        global_flight_recorder().capture(
            f"fault_window:{kind}", detail=detail, now=self.loop.now()
        )

    async def _fault_kill(self, ev: FaultEvent):
        """Process kill with the machine HELD DOWN for ev.duration, then
        revive: a sustained role outage, not a blink.  The CC's recovery
        must wait for the stateful machine (it cannot recruit an empty
        replacement without losing acked data), so the commit pipeline
        stalls for the window and the OLD generation's ratekeeper — whose
        role probes now all fail — floors admission (`recovering`) until
        the recovered generation's fresh ratekeeper takes over
        (DynamicCluster only)."""
        from .chaos import revive_worker

        cluster = self.cluster
        if not hasattr(cluster, "controllers"):
            raise ValueError("kill faults need cluster='dynamic'")
        role = ev.target or "tlog0"
        t = self.loop.now()
        try:
            proc = cluster.kill_role_process(role)
        except (KeyError, RuntimeError):
            self.fault_timeline.append([t, "kill", f"{role}:unrecruited", t])
            return
        cluster.fs.crash_machine(proc.machine.machine_id)
        if ev.duration > 0:
            await self.loop.delay(ev.duration)
        revive_worker(cluster, proc)
        self.fault_timeline.append([t, "kill", role, self.loop.now()])
        await self._capture_fault_window(0.0, "kill", {"target": role})

    def _clog_endpoints(self):
        """(src, dst) machine ids for the one-directional clog: tlog ->
        storage, so log-stream pulls stall, the storage falls behind, and
        the ss_lag spring visibly binds."""
        from ..server.status import role_objects

        tlogs = role_objects(self.cluster, "tlog")
        storages = role_objects(self.cluster, "storage")
        if tlogs and storages:
            return (
                tlogs[0].process.machine.machine_id,
                storages[0].process.machine.machine_id,
            )
        machines = sorted(self.cluster.net.machines)
        return machines[0], machines[-1]

    async def _fault_clog(self, ev: FaultEvent):
        src, dst = self._clog_endpoints()
        t = self.loop.now()
        self.cluster.net.clog_pair(src, dst, ev.duration)
        self.fault_timeline.append(
            [t, "clog", f"{src}->{dst}", t + ev.duration]
        )
        # The clog holds asynchronously; capture once its window closes
        # (without stalling the fault driver's schedule).
        self.db.process.spawn_observed(
            self._capture_fault_window(
                ev.duration, "clog", {"pair": f"{src}->{dst}"}
            ),
            "soak_fault_capture",
        )

    async def _fault_device_outage(self, ev: FaultEvent):
        """Persistent dispatch outage on ONE resolver's device engine: the
        PR-3 breaker opens, verdicts fall back to the CPU mirror, the
        ratekeeper contracts (backend_degraded), then the outage lifts and
        the half-open probe recovers."""
        from ..conflict.device_faults import DeviceFaultInjector

        sets = self._resolver_conflict_sets()
        t = self.loop.now()
        if not sets:
            self.fault_timeline.append([t, "device_outage", "no-device", t])
            return
        r, cs = sets[0]
        inj = cs._jax.fault_injector
        if inj is None:
            inj = DeviceFaultInjector(rng=self.rng.split())
            cs.install_fault_injector(inj)
        inj.begin_outage("dispatch")
        await self.loop.delay(ev.duration)
        inj.end_outage("dispatch")
        self.fault_timeline.append(
            [t, "device_outage", r.process.name, self.loop.now()]
        )
        await self._capture_fault_window(
            0.0, "device_outage", {"resolver": r.process.name}
        )

    def _sharded_sets(self):
        """(resolver, mesh-sharded conflict set) pairs — resolvers whose
        raw conflict set has per-shard fault domains (ISSUE 15)."""
        from ..server.status import role_objects

        out = []
        for r in role_objects(self.cluster, "resolver"):
            cs = getattr(r, "conflicts", None)
            if cs is not None and getattr(cs, "n_shards", 0) > 1:
                out.append((r, cs))
        return out

    async def _fault_shard_kill(self, ev: FaultEvent):
        """Chip loss scoped to ONE shard of a mesh-sharded resolver
        (ISSUE 15): a persistent dispatch outage on shard ev.shard only —
        its breaker opens and its slice serves degraded off its mirror,
        the other shards keep serving on device, and when the outage
        lifts the half-open probe rehydrates only that shard."""
        from ..conflict.device_faults import DeviceFaultInjector

        sets = self._sharded_sets()
        t = self.loop.now()
        if not sets:
            self.fault_timeline.append([t, "shard_kill", "no-shards", t])
            return
        r, cs = sets[0]
        shard = ev.shard % cs.n_shards
        inj = cs.fault_injector
        if inj is None:
            inj = DeviceFaultInjector(rng=self.rng.split())
            cs.install_fault_injector(inj)
        inj.begin_outage("dispatch", shard=shard)
        await self.loop.delay(ev.duration)
        inj.end_outage("dispatch", shard=shard)
        detail = f"{r.process.name}:shard{shard}"
        self.fault_timeline.append([t, "shard_kill", detail, self.loop.now()])
        await self._capture_fault_window(
            0.0, "shard_kill",
            {"resolver": r.process.name, "shard": shard},
        )

    async def _fault_shard_move(self, ev: FaultEvent):
        """Scripted live reshard (ISSUE 18): recompute split points from
        the occupancy quantiles and migrate them mid-stream — the direct
        (balancer-less) way to land a reshard inside another fault's
        window, exercising the during-fault legality rules (an open
        breaker on a moved shard completes degraded-on-mirror; a
        scripted reshard-site fault defers the whole move)."""
        sets = self._sharded_sets()
        t = self.loop.now()
        if not sets:
            self.fault_timeline.append([t, "shard_move", "no-shards", t])
            return
        r, cs = sets[0]
        n_target = ev.shard if ev.shard > 1 else cs.n_shards
        n_target = min(n_target, cs.max_shards)
        try:
            entry = cs.reshard(
                cs.balance_split_keys(n_target), reason="fault_shard_move"
            )
            detail = f"{r.process.name}:{entry['action']}"
        except ValueError as e:
            detail = f"{r.process.name}:rejected:{e}"
        self.fault_timeline.append([t, "shard_move", detail, self.loop.now()])
        await self._capture_fault_window(
            0.0, "shard_move", {"resolver": r.process.name, "detail": detail}
        )

    async def _balance_driver(self):
        """Tick a ShardBalancer over the mesh-sharded conflict set
        (ISSUE 18).  Pressure is the ratekeeper's binding signal — 1.0
        whenever admission is limited (the scale-up driver the ISSUE
        names), else the client-side in-flight fraction; per-shard load
        is the resolver's decayed witness-range sample.  Every input is
        virtual-time deterministic, so two same-seed soaks produce
        byte-identical decision logs."""
        period = self.config.shard_balance_seconds
        if not period:
            return
        sets = self._sharded_sets()
        if not sets:
            return
        from ..server.resolver_balancer import ShardBalancer

        r, cs = sets[0]
        load_fn = getattr(r, "_shard_load_sample", None)
        self.balancer = ShardBalancer(
            cs, ratio=2.0, hysteresis=2, cooldown=2,
            min_boundaries=16, load_fn=load_fn,
        )
        while not self._stop:
            await self.loop.delay(period)
            rk = self.current_ratekeeper()
            limiting = getattr(
                getattr(rk, "rate", None), "limiting", "none"
            ) if rk else "none"
            if limiting not in (None, "", "none"):
                pressure = 1.0
            else:
                pressure = min(
                    1.0, self.in_flight / max(1, self.config.max_in_flight)
                )
            self.balancer.evaluate(pressure=pressure)

    def _shard_snapshot(self) -> dict:
        """Per-phase shard-mesh cut (ISSUE 18): partition + breaker
        states + per-shard degraded-serve (shed) counters, enough for
        the A/B scorer to attribute each phase's hot-range traffic to
        device-serving vs mirror-degraded shards."""
        out = {}
        for r, cs in self._sharded_sets():
            out[r.process.name] = {
                "shards": cs.n_shards,
                "split_keys": [k.hex() for k in cs.split_keys],
                "occupancy": cs.shard_occupancy(),
                "states": [
                    b.state for b in cs._breakers[: cs.n_shards]
                ],
                "degraded_batches": [
                    int(
                        cs.metrics.counter(
                            f"shard{s}_degraded_batches"
                        ).value
                    )
                    for s in range(cs.n_shards)
                ],
                "moves": len(cs.move_log),
            }
        return out

    async def _admission_monitor(self):
        """Sample the CURRENT ratekeeper's binding signal; log changes.
        Spans generations (see admission_log comment)."""
        loop = self.loop
        last = None
        while not self._stop:
            await loop.delay(self.config.rk_sample_interval)
            rk = self.current_ratekeeper()
            if rk is None:
                continue
            limiting = rk.rate.limiting
            if limiting != last:
                self.admission_log.append(
                    [round(loop.now(), 4), limiting, round(rk.rate.tps, 3)]
                )
                last = limiting

    async def main(self):
        from ..flow.eventloop import all_of

        mon = self.db.process.spawn(self._admission_monitor(), "soak_rkmon")
        faults = self.db.process.spawn(self._fault_driver(), "soak_faults")
        bal = self.db.process.spawn(self._balance_driver(), "soak_balance")
        await self._load_driver()
        await all_of([faults])
        await all_of([mon, bal])
        return self.report()

    # -- reporting --------------------------------------------------------
    def _contention_section(self, rec) -> dict:
        """The report's contention explorer block (ISSUE 17)."""
        from ..flow.knobs import g_env
        from ..server.status import role_objects

        resolvers = {}
        for r in role_objects(self.cluster, "resolver"):
            cw = getattr(r, "conflict_witness", None)
            if callable(cw):
                w = cw()
                resolvers[r.process.name] = {
                    "aborts": w["aborts"],
                    "topk": w["topk"],
                    **w["contention"],
                }
        return {
            "witness_retry": (
                g_env.get("FDB_TPU_WITNESS_RETRY") not in ("", "0")
            ),
            "hint_retries": sum(
                getattr(db, "witness_hint_retries", 0) for db in self.dbs
            ),
            "spike_captures": sum(
                1 for c in rec.captures if c["trigger"] == "contention_spike"
            ),
            "resolvers": resolvers,
        }

    def _resharding_section(self) -> dict:
        """The report's elastic-resharding block (ISSUE 18): the final
        partition + move log per mesh-sharded resolver, the balancer's
        full decision log, and the per-phase shard cuts.  Deterministic
        (counts, hex keys, virtual-time stamps only), so the
        byte-identical replay gate extends over it."""
        resolvers = {}
        for r, cs in self._sharded_sets():
            resolvers[r.process.name] = {
                "shards": cs.n_shards,
                "max_shards": cs.max_shards,
                "split_keys": [k.hex() for k in cs.split_keys],
                "occupancy": cs.shard_occupancy(),
                "move_log": [dict(e) for e in cs.move_log],
                "reshards": int(cs.metrics.counter("reshards").value),
                "deferred": int(
                    cs.metrics.counter("reshard_deferred").value
                ),
                "degraded": int(
                    cs.metrics.counter("reshard_degraded").value
                ),
            }
        bal = self.balancer
        return {
            "resolvers": resolvers,
            "balancer": None if bal is None else {
                "moves": bal.moves,
                "decisions": [dict(d) for d in bal.decisions],
            },
            "phase_shards": self.phase_shards,
        }

    def _spans_section(self) -> dict:
        from ..flow.spans import global_span_hub, span_latency_summary
        from ..server.status import role_objects

        hub = global_span_hub()
        overlap = 0.0
        host_fraction = 0.0
        for r in role_objects(self.cluster, "resolver"):
            m = getattr(r, "metrics", None)
            if m is not None and "pipeline_overlap_efficiency" in m.gauges:
                overlap = max(
                    overlap, m.gauges["pipeline_overlap_efficiency"].value
                )
            if m is not None and "host_fraction" in m.gauges:
                host_fraction = max(
                    host_fraction, m.gauges["host_fraction"].value
                )
        return {
            "status": hub.status_section(),
            "stage_latency": span_latency_summary(hub),
            "pipeline_overlap_efficiency": overlap,
            "host_fraction": host_fraction,
            "window": hub.window_dict(last_n=8),
        }

    def _phase_chain_p99(self, st: _PhaseStats, chain, type_):
        from ..flow.trace import global_collector

        events = global_collector().events[st.ev_start:st.ev_end]
        summary = summarize_stages(events, type_, chain)
        return summary.get("total", {}).get("p99")

    def report(self) -> dict:
        cfg = self.config
        phases = []
        worst_p99 = 0.0
        slo_ok = True
        for st, phase in zip(self.stats, cfg.phases):
            dur = max(st.t_end - st.t_start, 1e-9)
            goodput = st.counts["committed"] / dur
            chain_p99 = self._phase_chain_p99(st, COMMIT_CHAIN, "CommitDebug")
            grv_p99 = self._phase_chain_p99(st, GRV_CHAIN, "TransactionDebug")
            client_p99 = percentile(st.latencies, 0.99)
            floor = (
                phase.arrival_tps * cfg.goodput_floor_frac
                if cfg.mode == "open"
                else cfg.goodput_floor_tps
            )
            ok = goodput >= floor and (
                chain_p99 is None or chain_p99 <= cfg.slo_commit_p99
            )
            if not ok:
                # SLO breach trigger (ISSUE 10): the fourth transition-log
                # owner — a phase missing its goodput floor or p99 bound
                # freezes the window, admission log attached.
                from ..flow.flight_recorder import maybe_trigger

                maybe_trigger(
                    "slo_breach",
                    detail={"phase": st.name,
                            "goodput_tps": round(goodput, 3),
                            "goodput_floor_tps": round(floor, 3),
                            "commit_p99_chain": chain_p99,
                            "commit_p99_bound": cfg.slo_commit_p99},
                    # Thunk: copied only if the cooldown admits it.
                    transitions=lambda: [
                        list(e) for e in self.admission_log
                    ],
                    # report() evaluates every phase at ONE virtual
                    # instant; a per-phase source keeps a second
                    # breaching phase from being cooldown-swallowed by
                    # the first.
                    source=st.name,
                )
            slo_ok = slo_ok and ok
            if chain_p99 is not None:
                worst_p99 = max(worst_p99, chain_p99)
            phases.append(
                {
                    "name": st.name,
                    "duration": round(dur, 4),
                    **st.counts,
                    "goodput_tps": round(goodput, 3),
                    "goodput_floor_tps": round(floor, 3),
                    "commit_p99_chain": chain_p99,
                    "grv_p99_chain": grv_p99,
                    "commit_p99_client": client_p99,
                    "slo_ok": ok,
                    # Where contention lived this phase (ISSUE 12):
                    # aborted-txn totals + top-K contended ranges per
                    # resolver, snapshotted at phase end.
                    "conflict_witness": self.phase_witness.get(
                        st.name, {}
                    ),
                }
            )
        totals = {
            f: sum(st.counts[f] for st in self.stats)
            for f in _PhaseStats.FIELDS
        }
        wall_span = (
            self.stats[-1].t_end - self.stats[0].t_start
            if self.stats
            else 0.0
        )
        # Proxy-side shed counters (the enforcement half of throttling).
        from ..server.status import role_objects

        shed = {"grv_shed_batch": 0, "grv_shed_default": 0}
        for p in role_objects(self.cluster, "proxy"):
            stats = getattr(p, "stats", None)
            if stats is None:
                continue
            snap = stats.snapshot()
            for k in shed:
                shed[k] += snap.get(k, 0)
        rk = self.current_ratekeeper()
        from ..flow.flight_recorder import global_flight_recorder

        _rec = global_flight_recorder()
        breakers = {}
        pipeline = {}
        shards = {}
        # Shard-granular fault domains (ISSUE 15): per-shard breaker
        # transition logs (the replay gate covers them — byte-identical
        # across same-seed runs) plus the shard state summary.
        for r, cs in self._sharded_sets():
            for s in range(cs.n_shards):
                breakers[f"{r.process.name}.shard{s}"] = [
                    list(tr) for tr in cs._breakers[s].transitions
                ]
            shards[r.process.name] = {
                "total": cs.n_shards,
                "states": [b.state for b in cs._breakers],
                "degraded_shard_serves": int(
                    cs.metrics.counter("degraded_shard_serves").value
                ),
            }
        for r, cs in self._resolver_conflict_sets():
            if cs._breaker is not None:
                breakers[r.process.name] = [
                    list(tr) for tr in cs._breaker.transitions
                ]
            # Pipeline engagement per resolver (ISSUE 11): the soak's
            # goodput floors are now held WITH the double-buffered path
            # on by default — record the facts that prove it ran and how
            # it completed (bound-pushed vs idle-flushed).
            if getattr(r, "_pipeline_on", False) and cs._jax is not None:
                rsnap = r.metrics.snapshot()
                pipeline[r.process.name] = {
                    "depth": cs.pipeline_depth,
                    "dispatches": int(
                        cs._jax.metrics.counter("pipeline_dispatches").value
                    ),
                    "replayed_batches": int(
                        cs._jax.metrics.counter(
                            "pipeline_replayed_batches"
                        ).value
                    ),
                    "device_stalls": rsnap["counters"][
                        "pipeline_device_stalls"
                    ],
                    "host_stalls": rsnap["counters"]["pipeline_host_stalls"],
                }
        return {
            "config": {
                "seed": cfg.seed,
                "cluster": cfg.cluster,
                "backend": cfg.backend,
                "mode": cfg.mode,
                "keys": cfg.keys,
                "zipf_theta": cfg.zipf_theta,
                "phases": [
                    {"name": p.name, "duration": p.duration,
                     "arrival_tps": p.arrival_tps}
                    for p in cfg.phases
                ],
                "faults": [
                    {"at": f.at, "kind": f.kind, "duration": f.duration,
                     "target": f.target, "shard": f.shard}
                    for f in cfg.faults
                ],
            },
            "phases": phases,
            "totals": {
                **totals,
                "sim_seconds": round(wall_span, 4),
                "goodput_tps": round(
                    totals["committed"] / max(wall_span, 1e-9), 3
                ),
            },
            "throttle_shed": {
                **shed,
                "client_throttled": totals["throttled"],
            },
            "faults": [list(f) for f in self.fault_timeline],
            "ratekeeper": {
                "admission_log": [list(e) for e in self.admission_log],
                "transitions": (
                    [list(t) for t in rk.transitions] if rk else []
                ),
            },
            "breakers": breakers,
            "shards": shards,
            "pipeline": pipeline,
            # Contention explorer (ISSUE 17): per-resolver abort
            # timelines + spike state, the client-side witness-hint
            # retry count, and the contention_spike captures this run
            # froze.  Deterministic like everything above — the replay
            # gate extends over this block.
            "contention": self._contention_section(_rec),
            # Elastic resharding (ISSUE 18): final partition, move logs,
            # the balancer decision log, and per-phase shard cuts — the
            # hot_key_rebalance scorer and the replay gate read these.
            "resharding": self._resharding_section(),
            # Span layer (ISSUE 12): per-role ring inventory, the recent
            # window, per-stage latency percentiles off the spans, and
            # the worst pipeline overlap-efficiency gauge.  All
            # deterministic (wall fields excluded by construction), so
            # the byte-identical replay gate extends over this section.
            "spans": self._spans_section(),
            "slo": {
                "commit_p99_bound": cfg.slo_commit_p99,
                "worst_phase_commit_p99": worst_p99 or None,
                "ok": slo_ok,
            },
            # The run's flight-recorder captures (ISSUE 10): fault-window
            # artifacts + whatever triggers fired (breaker opens,
            # ratekeeper throttles, SLO breaches).  run_soak installed a
            # fresh recorder, so these are THIS run's only — and, like
            # everything above, byte-identical across same-seed runs.
            "flight_recorder": {
                "status": _rec.status_section(),
                "captures": [dict(c) for c in _rec.captures],
            },
        }


def transition_logs_json(report: dict) -> str:
    """Canonical byte form of the replay-gated logs: the admission log,
    the (current-generation) ratekeeper transitions, every breaker
    transition log, and (ISSUE 18) the balancer decision + reshard move
    logs.  Same seed => byte-identical."""
    resharding = report.get("resharding", {})
    bal = resharding.get("balancer")
    return json.dumps(
        {
            "admission": report["ratekeeper"]["admission_log"],
            "ratekeeper": report["ratekeeper"]["transitions"],
            "breakers": report["breakers"],
            "faults": report["faults"],
            "balancer": [] if bal is None else bal["decisions"],
            "moves": {
                name: blk["move_log"]
                for name, blk in sorted(
                    resharding.get("resolvers", {}).items()
                )
            },
        },
        sort_keys=True,
        separators=(",", ":"),
    )


def run_soak(config: SoakConfig) -> dict:
    """Build a rated cluster per `config`, run the soak, return the
    report.  Owns loop/collector/knob lifecycle: installs a fresh
    in-memory trace collector (latency chains + determinism isolation)
    and restores every knob it touches."""
    from ..flow.eventloop import set_event_loop
    from ..flow.flight_recorder import (
        FlightRecorder,
        global_flight_recorder,
        set_global_flight_recorder,
    )
    from ..flow.timeseries import (
        TimeSeriesHub,
        global_timeseries,
        set_global_timeseries,
    )
    from ..flow.trace import TraceCollector, set_global_collector

    srv = g_knobs.server
    saved = {
        "sample_rate": g_knobs.client.latency_sample_rate,
        "max_tps": srv.ratekeeper_max_tps,
        "grv_queue_max": srv.ratekeeper_grv_queue_max,
        "degraded_frac": srv.ratekeeper_degraded_tps_fraction,
        "key_words": srv.conflict_device_key_words,
        "key_bytes": srv.conflict_max_device_key_bytes,
    }
    from ..flow.trace import global_collector

    old_col = global_collector()
    set_global_collector(TraceCollector())
    # Fresh time-series hub + flight recorder (ISSUE 10): the soak's
    # samplers and triggers must write into rings THIS run owns — both
    # for the byte-identical replay gate and so the report's captures
    # aren't polluted by an earlier run in the same process.
    old_hub, old_rec = global_timeseries(), global_flight_recorder()
    set_global_timeseries(TimeSeriesHub())
    set_global_flight_recorder(FlightRecorder())
    # Fresh span hub (ISSUE 12): the report's spans section and the
    # captures' span windows must belong to THIS run only.
    from ..flow.spans import SpanHub, global_span_hub, set_global_span_hub

    old_spans = global_span_hub()
    set_global_span_hub(SpanHub())
    from ..flow.knobs import g_env

    wr_prev, wr_overridden = None, False
    try:
        if config.witness_retry is not None:
            # A/B seam (ISSUE 17): the flag is read live by the client's
            # on_error, so a process-env override scoped to this run is
            # exact — restored below whatever happens.
            wr_prev = g_env.override(
                "FDB_TPU_WITNESS_RETRY",
                "1" if config.witness_retry else "0",
            )
            wr_overridden = True
        # Sample every transaction: the soak's SLO gate IS the latency
        # chain, and the harness owns its own (fresh) collector.
        g_knobs.client.latency_sample_rate = 1.0
        if config.max_tps is not None:
            srv.ratekeeper_max_tps = config.max_tps
        if config.grv_queue_max is not None:
            srv.ratekeeper_grv_queue_max = config.grv_queue_max
        if config.degraded_tps_fraction is not None:
            srv.ratekeeper_degraded_tps_fraction = (
                config.degraded_tps_fraction
            )
        if config.device_key_words is not None:
            srv.conflict_device_key_words = config.device_key_words
        if config.device_key_bytes is not None:
            srv.conflict_max_device_key_bytes = config.device_key_bytes
        cluster, dbs = _build_cluster(config)
        run = SoakRun(config, cluster, dbs)
        db = dbs[0]
        total = sum(p.duration for p in config.phases)
        task = db.process.spawn(run.main(), "soak_main")
        report = cluster.run_until(
            task, timeout_vt=total * 20 + config.drain_timeout + 600.0
        )
        return report
    finally:
        if wr_overridden:
            g_env.override("FDB_TPU_WITNESS_RETRY", wr_prev)
        g_knobs.client.latency_sample_rate = saved["sample_rate"]
        srv.ratekeeper_max_tps = saved["max_tps"]
        srv.ratekeeper_grv_queue_max = saved["grv_queue_max"]
        srv.ratekeeper_degraded_tps_fraction = saved["degraded_frac"]
        srv.conflict_device_key_words = saved["key_words"]
        srv.conflict_max_device_key_bytes = saved["key_bytes"]
        set_global_collector(old_col)
        set_global_timeseries(old_hub)
        set_global_flight_recorder(old_rec)
        set_global_span_hub(old_spans)
        set_event_loop(None)


def run_contention_ab(
    minutes: float = 0.25,
    peak_tps: float = 120.0,
    seed: int = 1,
    keys: int = 8,
    zipf_theta: float = 1.2,
    backend: str = "jax",
) -> dict:
    """Witness-guided vs blind retry A/B on the high-contention Zipf arm
    (ISSUE 17's acceptance comparison).  Same seed, same load plan, same
    fault-free cluster build — the ONLY difference is the client's
    FDB_TPU_WITNESS_RETRY flag, so any goodput gap is the retry hint's.
    Scored on goodput (committed txn/s), retry counts, and commit p99;
    full per-arm reports ride along for the explorer."""
    arms = {}
    for arm, flag in (("guided", True), ("blind", False)):
        cfg = contention_config(
            minutes=minutes, peak_tps=peak_tps, seed=seed, keys=keys,
            zipf_theta=zipf_theta, backend=backend, witness_retry=flag,
        )
        arms[arm] = run_soak(cfg)

    def score(rep: dict) -> dict:
        t = rep["totals"]
        started = t["arrivals"] - t["client_shed"]
        return {
            "goodput_tps": t["goodput_tps"],
            "committed": t["committed"],
            "conflicted": t["conflicted"],
            "attempts": t["attempts"],
            "retries": t["attempts"] - started,
            "hint_retries": rep["contention"]["hint_retries"],
            "commit_p99": rep["slo"]["worst_phase_commit_p99"],
        }

    g, b = score(arms["guided"]), score(arms["blind"])
    return {
        "guided": g,
        "blind": b,
        "goodput_ratio": round(
            g["goodput_tps"] / max(b["goodput_tps"], 1e-9), 4
        ),
        "reports": arms,
    }


def _hot_device_goodput(report: dict, cfg: SoakConfig) -> dict:
    """Per-phase hot-range DEVICE goodput: the phase's committed tps
    weighted by the Zipf traffic mass whose owning shard (under that
    phase's partition cut) was serving on device, not degraded on its
    mirror.  Virtual time charges mirror serves nothing, so the
    traffic-weighted device fraction is the honest per-shard goodput
    measure the virtual-mesh A/B compares: a pinned hot range on a sick
    chip scores ~0, the same range rebalanced onto healthy chips scores
    its full committed rate."""
    weights = hot_zipf_weights(cfg.keys, cfg.zipf_theta, cfg.hot_offset)
    out = {}
    shards_by_phase = report["resharding"]["phase_shards"]
    for ph in report["phases"]:
        snap = shards_by_phase.get(ph["name"], {})
        if not snap:
            out[ph["name"]] = None
            continue
        blk = next(iter(snap.values()))
        splits = [bytes.fromhex(h) for h in blk["split_keys"]]
        states = blk["states"]
        frac = 0.0
        for i, w in enumerate(weights):
            s = bisect.bisect_right(splits, b"soak/%06d" % i)
            if states[s] == "ok":
                frac += w
        out[ph["name"]] = {
            "goodput_tps": ph["goodput_tps"],
            "device_fraction": round(frac, 4),
            "hot_device_goodput_tps": round(ph["goodput_tps"] * frac, 3),
            "degraded_batches": blk["degraded_batches"],
            "moves": blk["moves"],
        }
    return out


def run_hot_key_rebalance_ab(
    minutes: float = 0.5,
    peak_tps: float = 80.0,
    seed: int = 1,
    n_shards: int = 4,
    max_shards: int = 8,
    zipf_theta: float = 1.2,
    balance_seconds: float = 1.0,
) -> dict:
    """Balancer-on vs balancer-off A/B on the hot-key-pinned soak
    (ISSUE 18's acceptance comparison).  Same seed, same Zipf load
    pinned to one shard's interior, same scripted chip loss on that
    shard — the ONLY difference is the ShardBalancer driver, so any
    hot-range device-goodput gap is the live reshard's.  The pinned
    arm's outage-window minimum is the pre-rebalance floor; recovery is
    the balanced arm's final ("recovered") phase."""
    arms, cfgs = {}, {}
    for arm, bal in (("balanced", balance_seconds), ("pinned", None)):
        cfg = hot_key_rebalance_config(
            minutes=minutes, peak_tps=peak_tps, seed=seed,
            n_shards=n_shards, max_shards=max_shards,
            zipf_theta=zipf_theta, balance_seconds=bal,
        )
        cfgs[arm] = cfg
        arms[arm] = run_soak(cfg)
    scores = {a: _hot_device_goodput(arms[a], cfgs[a]) for a in arms}
    floor = min(
        (
            s["hot_device_goodput_tps"]
            for name, s in scores["pinned"].items()
            if s is not None and name != "warm"
        ),
        default=0.0,
    )
    recovered = scores["balanced"].get("recovered") or {}
    rec = recovered.get("hot_device_goodput_tps", 0.0)
    bal_block = arms["balanced"]["resharding"]["balancer"] or {}
    return {
        "phases": scores,
        "pre_rebalance_floor_tps": round(floor, 3),
        "recovered_hot_goodput_tps": rec,
        "recovery_ratio": round(rec / max(floor, 1e-9), 3),
        "balancer_moves": bal_block.get("moves", 0),
        "slo_ok": arms["balanced"]["slo"]["ok"]
        and arms["pinned"]["slo"]["ok"],
        "reports": arms,
    }


def _build_cluster(config: SoakConfig):
    """A rated cluster + primed client Database handles."""
    n_clients = max(1, config.clients)
    if config.cluster == "dynamic":
        assert config.backend != "sharded", (
            "backend='sharded' is a sim-cluster seam (SimCluster's "
            "conflict_set); DynamicCluster recruits resolvers by backend "
            "name only"
        )
        from ..server.dynamic_cluster import DynamicCluster

        cluster = DynamicCluster(
            seed=config.seed,
            conflict_backend=config.backend,
            buggify=config.buggify,
        )
        dbs = [cluster.database(f"soak{i}") for i in range(n_clients)]

        async def prime(tr):
            tr.set(b"soak/boot", b"1")

        cluster.run_all([(dbs[0], dbs[0].run(prime))], timeout_vt=600.0)
        return cluster, dbs
    from ..server import SimCluster
    from ..server.ratekeeper import Ratekeeper

    conflict_set = None
    backend = config.backend
    if backend == "sharded":
        # Mesh-sharded resolver 0 (ISSUE 15): a ShardedJaxConflictSet over
        # the visible devices (virtual CPU mesh in tests), split evenly
        # across the soak key space so every shard sees load.  The
        # resolver swaps it in via SimCluster's conflict_set seam.
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            # Effective only before the first backend init (tests set it
            # in conftest; the CLI lands here first) — if the backend is
            # already up with one device, the shard-count assert below
            # explains the failure.
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        from ..parallel.sharded_resolver import ShardedJaxConflictSet

        n = max(2, min(config.sharded_shards, len(jax.devices())))
        split = [
            b"soak/%06d" % (config.keys * s // n) for s in range(1, n)
        ]
        # Scaling headroom (ISSUE 18): with a max_shards ceiling the set
        # keeps the FULL device list so the balancer can scale the mesh
        # live; without one the visible devices are trimmed to the shard
        # count exactly as before.
        n_max = config.sharded_max_shards
        conflict_set = ShardedJaxConflictSet(
            split,
            key_words=8,  # 16-byte effective width covers soak/ and the
            # sim cluster's \xff/SC/ self-conflict keys; anything longer
            # rides the exact-semantics mirror pin by design
            h_cap=1 << 12,
            devices=jax.devices() if n_max else jax.devices()[:n],
            bucket_mins=(64, 128, 128),
            max_shards=n_max,
        )
        backend = "cpu"  # the other resolvers (if any) stay host-only
    cluster = SimCluster(
        seed=config.seed,
        conflict_backend=backend,
        n_resolvers=config.n_resolvers,
        buggify=config.buggify,
        conflict_set=conflict_set,
    )
    rk = Ratekeeper(
        cluster.master_proc,
        cluster.tlogs,
        cluster.storages,
        sample_interval=config.rk_sample_interval,
        resolvers=cluster.resolvers,
        proxies=cluster.proxies,
    )
    for p in cluster.proxies:
        p.ratekeeper = rk.interface()
    cluster._soak_ratekeeper = rk
    return cluster, [cluster.database(f"soak{i}") for i in range(n_clients)]
