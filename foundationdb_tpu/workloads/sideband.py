"""Sideband: external consistency through a side channel.

Ref: fdbserver/workloads/Sideband.actor.cpp — a mutator commits a key and
THEN sends the commit version to a checker through a side channel (a
PromiseStream there; a plain deque here, which is still "outside the
database").  The checker starts a transaction AFTER receiving the
message; serializability + external consistency require its read version
to reach the communicated commit version and the key to be present — a
missing key means a causality violation (a GRV served below an already-
acknowledged commit).
"""

from __future__ import annotations

from collections import deque

from .base import TestWorkload


class SidebandWorkload(TestWorkload):
    name = "sideband"

    def __init__(self, messages: int = 20, prefix: bytes = b"sideband/"):
        self.messages = messages
        self.prefix = prefix
        self.checked = 0
        self.violations = 0

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        chan: deque = deque()  # the side channel (mutator -> checker)
        done = {"sending": True}

        async def commit_marker(key: bytes) -> int:
            from ..flow.error import FdbError

            while True:
                tr = db.create_transaction()
                tr.set(key, b"present")
                try:
                    return await tr.commit()
                except FdbError as e:
                    await tr.on_error(e)

        async def mutator():
            for i in range(self.messages):
                key = self.prefix + b"%06d" % i
                version = await commit_marker(key)
                chan.append((i, version))
            done["sending"] = False

        async def checker():
            loop = cluster.loop
            remaining = self.messages
            while remaining > 0:
                if not chan:
                    await loop.delay(0.005)
                    continue
                i, commit_version = chan.popleft()
                key = self.prefix + b"%06d" % i
                # The transaction STARTS after the side message arrived:
                # its read version must cover the acked commit.
                tr = db.create_transaction()
                rv = await tr.get_read_version()
                val = await tr.get(key)
                if rv < commit_version or val != b"present":
                    self.violations += 1
                self.checked += 1
                remaining -= 1

        await all_of(
            [
                db.process.spawn(mutator(), "sideband_mut"),
                db.process.spawn(checker(), "sideband_chk"),
            ]
        )

    async def check(self, db, cluster) -> bool:
        return self.violations == 0 and self.checked == self.messages
