"""Storefront: order/inventory invariant under concurrent purchases.

Ref: fdbserver/workloads/Storefront.actor.cpp — customers buy items in
transactions that decrement per-item stock and append an order record;
the check re-derives stock from the order log and asserts no item was
oversold (stock never below zero) and accounting balances exactly.
"""

from __future__ import annotations

from .base import TestWorkload

INITIAL_STOCK = 20


class StorefrontWorkload(TestWorkload):
    name = "storefront"

    def __init__(self, items: int = 4, actors: int = 3, purchases: int = 8,
                 prefix: bytes = b"store/"):
        self.items = items
        self.actors = actors
        self.purchases = purchases
        self.prefix = prefix

    def _stock_key(self, i: int) -> bytes:
        return self.prefix + b"stock/%02d" % i

    def _order_key(self, aid: int, seq: int) -> bytes:
        return self.prefix + b"order/%02d_%04d" % (aid, seq)

    async def setup(self, db, cluster):
        async def txn(tr):
            for i in range(self.items):
                tr.set(self._stock_key(i), b"%d" % INITIAL_STOCK)

        await db.run(txn)

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        rng = cluster.loop.rng

        async def customer(aid: int):
            for seq in range(self.purchases):
                item = int(rng.random_int(0, self.items))
                qty = 1 + int(rng.random_int(0, 3))

                async def buy(tr, item=item, qty=qty, aid=aid, seq=seq):
                    ok = self._order_key(aid, seq)
                    if await tr.get(ok) is not None:
                        return  # unknown-result retry: order already landed
                    stock = int(await tr.get(self._stock_key(item)) or b"0")
                    if stock < qty:
                        tr.set(ok, b"rejected/%02d/0" % item)
                        return
                    tr.set(self._stock_key(item), b"%d" % (stock - qty))
                    tr.set(ok, b"filled/%02d/%d" % (item, qty))

                await db.run(buy)

        await all_of(
            [
                db.process.spawn(customer(a), f"store{a}")
                for a in range(self.actors)
            ]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["stock"] = await tr.get_range(
                self.prefix + b"stock/", self.prefix + b"stock0"
            )
            out["orders"] = await tr.get_range(
                self.prefix + b"order/", self.prefix + b"order0"
            )

        await db.run(read)
        if len(out["orders"]) != self.actors * self.purchases:
            return False
        sold = {i: 0 for i in range(self.items)}
        for _k, v in out["orders"]:
            state, item, qty = v.split(b"/")
            if state == b"filled":
                sold[int(item)] += int(qty)
        for k, v in out["stock"]:
            item = int(k.rsplit(b"/", 1)[-1])
            stock = int(v)
            # Serializability forbids overselling AND the ledger must
            # balance exactly.
            if stock < 0 or stock + sold[item] != INITIAL_STOCK:
                return False
        return True
