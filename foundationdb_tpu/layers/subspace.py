"""Subspace: a fixed key prefix + tuple packing underneath it.

Ref: bindings/python/fdb/subspace_impl.py — subspaces partition the key
space; sub[x] nests, pack/unpack round-trip tuples under the prefix, and
range() scans everything beneath.
"""

from __future__ import annotations

from typing import Any, Iterable, Tuple

from . import tuple as fdbtuple


class Subspace:
    def __init__(self, prefix_tuple: Iterable[Any] = (), raw_prefix: bytes = b""):
        self._prefix = raw_prefix + fdbtuple.pack(tuple(prefix_tuple))

    @property
    def raw_prefix(self) -> bytes:
        return self._prefix

    def key(self) -> bytes:
        return self._prefix

    def pack(self, t: Iterable[Any] = ()) -> bytes:
        return self._prefix + fdbtuple.pack(tuple(t))

    def unpack(self, key: bytes) -> tuple:
        if not self.contains(key):
            raise ValueError("key is not within this subspace")
        return fdbtuple.unpack(key[len(self._prefix) :])

    def contains(self, key: bytes) -> bool:
        return key.startswith(self._prefix)

    def range(self, t: Iterable[Any] = ()) -> Tuple[bytes, bytes]:
        p = self.pack(t)
        return p + b"\x00", p + b"\xff"

    def subspace(self, t: Iterable[Any]) -> "Subspace":
        return Subspace(raw_prefix=self.pack(t))

    def __getitem__(self, item) -> "Subspace":
        return self.subspace((item,))

    def __repr__(self):
        return f"Subspace(raw_prefix={self._prefix!r})"
