"""Queue / vector container recipes over the tuple layer.

Ref: layers/containers (vector.py, highcontention queue) and the
classic FDB queue recipe — the queue uses VERSIONSTAMPED keys so pushes
from any number of clients never conflict with each other (the stamp IS
the global commit order); pops read-and-clear the first item and carry
ordinary conflict semantics (two poppers racing: one retries).  The
vector is a dense index->value subspace with transactional size/swap.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ..client.types import MutationType, key_after
from .subspace import Subspace


class Queue:
    """Multi-writer FIFO: contention-free push, conflicting pop.

    Keys: sub[(stamp, )] where stamp is the 10-byte commit versionstamp —
    global arrival order with NO key reads on push (the canonical
    versionstamped-key queue recipe; ref: bindings' queue examples and
    layers/containers/highcontention's goal)."""

    def __init__(self, subspace: Subspace):
        self.sub = subspace

    def push(self, tr, value: bytes) -> None:
        # Param = [prefix][10-byte stamp placeholder][pos: 4B LE]; the
        # stamp (8B big-endian version + 2B batch index) replaces the
        # placeholder at commit, so final keys sort in commit order.
        prefix = self.sub.pack()
        key = prefix + b"\x00" * 10 + len(prefix).to_bytes(4, "little")
        tr.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY, key, value)

    async def pop(self, tr) -> Optional[bytes]:
        b, e = self.sub.range()
        rows = await tr.get_range(b, e, limit=1)
        if not rows:
            return None
        tr.clear(rows[0][0])
        return rows[0][1]

    async def peek(self, tr) -> Optional[bytes]:
        b, e = self.sub.range()
        rows = await tr.get_range(b, e, limit=1, snapshot=True)
        return rows[0][1] if rows else None

    async def empty(self, tr) -> bool:
        b, e = self.sub.range()
        return not await tr.get_range(b, e, limit=1)


class Vector:
    """Dense 0-indexed vector: sub[(i,)] = value (ref:
    layers/containers/vector.py's shape, re-derived)."""

    def __init__(self, subspace: Subspace):
        self.sub = subspace

    async def size(self, tr) -> int:
        b, e = self.sub.range()
        rows = await tr.get_range(b, e, limit=1, reverse=True)
        if not rows:
            return 0
        return int(self.sub.unpack(rows[0][0])[0]) + 1

    def set(self, tr, index: int, value: bytes) -> None:
        tr.set(self.sub.pack((index,)), value)

    async def get(self, tr, index: int) -> Optional[bytes]:
        return await tr.get(self.sub.pack((index,)))

    async def push(self, tr, value: bytes) -> int:
        n = await self.size(tr)
        tr.set(self.sub.pack((n,)), value)
        return n

    async def pop(self, tr) -> Optional[bytes]:
        n = await self.size(tr)
        if n == 0:
            return None
        k = self.sub.pack((n - 1,))
        v = await tr.get(k)
        tr.clear(k)
        return v

    async def swap(self, tr, i: int, j: int) -> None:
        ki, kj = self.sub.pack((i,)), self.sub.pack((j,))
        vi, vj = await tr.get(ki), await tr.get(kj)
        if vj is None:
            tr.clear(ki)
        else:
            tr.set(ki, vj)
        if vi is None:
            tr.clear(kj)
        else:
            tr.set(kj, vi)
