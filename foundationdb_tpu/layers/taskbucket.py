"""TaskBucket: a distributed, leased task queue stored IN the database.

Ref: fdbclient/TaskBucket.{h,actor.cpp} — tasks live in a subspace; an
executor claims one by transactionally moving it from the available space
to the timeout space with a lease deadline (in versions); finishing clears
it; an expired lease makes the task claimable again, and the finisher's
transaction conflicts with any re-claim so exactly one completion wins.
This is the execution substrate for backup/DR agents.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..flow.knobs import g_knobs
from .subspace import Subspace

AVAILABLE = 0  # (priority, uid) -> b""        priority 0 runs before 1
TIMEOUTS = 1  # (deadline_version, uid) -> priority
TASK = 2  # [uid][param] -> value


class Task:
    def __init__(self, uid: bytes, params: Dict[bytes, bytes], deadline: int):
        self.uid = uid
        self.params = params
        self.deadline = deadline

    def __repr__(self):
        return f"Task({self.uid.hex()}, {self.params.get(b'type')!r})"


class TaskBucket:
    def __init__(self, subspace: Subspace, lease_seconds: float = 5.0):
        self.ss = subspace
        self.available = subspace[AVAILABLE]
        self.timeouts = subspace[TIMEOUTS]
        self.tasks = subspace[TASK]
        self.lease_versions = int(
            lease_seconds * g_knobs.server.versions_per_second
        )

    # -- producer side --
    def add(self, tr, params: Dict[bytes, bytes], priority: int = 0) -> bytes:
        """Queue a task (inside the caller's transaction, so task creation
        is atomic with whatever work produced it — the TaskBucket
        property backup correctness leans on)."""
        rng = tr.db.process.network.loop.rng
        uid = rng.random_int(0, 1 << 62).to_bytes(8, "big")
        tr.set(self.available.pack((priority, uid)), b"")
        for k, v in params.items():
            tr.set(self.tasks[uid].pack((k,)), v)
        return uid

    # -- executor side --
    async def claim_one(self, tr) -> Optional[Task]:
        """Claim the best available task: move it to the timeout space with
        a lease deadline (ref: getOne TaskBucket.actor.cpp).  The RYW read
        of the available entry makes two claimants conflict."""
        rows = await tr.get_range(*self.available.range(), limit=1)
        if not rows:
            return await self._reclaim_expired(tr)
        key = rows[0][0]
        priority, uid = self.available.unpack(key)
        tr.clear(key)
        version = await tr.get_read_version()
        deadline = version + self.lease_versions
        tr.set(
            self.timeouts.pack((deadline, uid)), b"%d" % priority
        )
        params = await self._read_params(tr, uid)
        return Task(uid, params, deadline)

    async def _reclaim_expired(self, tr) -> Optional[Task]:
        """An expired lease returns the task to circulation (ref:
        checkTimeouts); claiming it here conflicts with the original
        executor's finish, so a *completed* task never reruns."""
        version = await tr.get_read_version()
        rows = await tr.get_range(
            self.timeouts.range()[0],
            self.timeouts.pack((version,)),
            limit=1,
        )
        if not rows:
            return None
        key, pr = rows[0]
        _old_deadline, uid = self.timeouts.unpack(key)
        tr.clear(key)
        deadline = version + self.lease_versions
        tr.set(self.timeouts.pack((deadline, uid)), pr)
        params = await self._read_params(tr, uid)
        if not params:
            return None  # finished concurrently; our claim will conflict
        return Task(uid, params, deadline)

    async def _read_params(self, tr, uid: bytes) -> Dict[bytes, bytes]:
        rows = await tr.get_range(*self.tasks[uid].range())
        return {self.tasks[uid].unpack(k)[0]: v for k, v in rows}

    def finish(self, tr, task: Task):
        """Complete: clear the task and its lease entry.  Conflicts with
        any reclaim of the same lease (both touch the timeout key)."""
        tr.clear(self.timeouts.pack((task.deadline, task.uid)))
        b, e = self.tasks[task.uid].range()
        tr.clear_range(b, e)

    def extend(self, tr, task: Task, version: int) -> int:
        """Renew the lease from `version` (ref: extendTimeout)."""
        tr.clear(self.timeouts.pack((task.deadline, task.uid)))
        task.deadline = version + self.lease_versions
        tr.set(self.timeouts.pack((task.deadline, task.uid)), b"0")
        return task.deadline

    async def is_empty(self, tr) -> bool:
        avail = await tr.get_range(*self.available.range(), limit=1)
        leased = await tr.get_range(*self.timeouts.range(), limit=1)
        return not avail and not leased


class TaskBucketExecutor:
    """Pull-execute loop: claim a task, run its handler, finish (ref: the
    backup agents' taskBucket->run loops).  `handlers` maps task type ->
    async fn(db, task) -> list of follow-on task param dicts;
    follow-ons are added in the SAME transaction that finishes the task, so
    a chain advances exactly once no matter how executors crash."""

    def __init__(self, db, bucket: TaskBucket, handlers: dict):
        self.db = db
        self.bucket = bucket
        self.handlers = handlers
        self.executed = 0

    async def run_one(self) -> bool:
        async def claim(tr):
            tr.options["access_system_keys"] = True
            return await self.bucket.claim_one(tr)

        task = await self.db.run(claim)
        if task is None:
            return False
        handler = self.handlers[task.params[b"type"].decode()]
        followons = await handler(self.db, task)

        async def fin(tr):
            tr.options["access_system_keys"] = True
            # Re-assert the lease is still ours: the timeout entry must
            # exist exactly as claimed (the read adds the conflict with any
            # reclaim).  Lease lost -> commit nothing; the work re-runs
            # under whoever retook it.
            held = await tr.get(
                self.bucket.timeouts.pack((task.deadline, task.uid))
            )
            if held is None:
                return False
            self.bucket.finish(tr, task)
            for params in followons or []:
                self.bucket.add(tr, params)
            return True

        if await self.db.run(fin):
            self.executed += 1
        return True

    async def run(self, idle_delay: float = 0.1, until_empty: bool = False):
        loop = self.db.process.network.loop
        while True:
            did = await self.run_one()
            if not did:
                if until_empty:
                    async def empty(tr):
                        tr.options["access_system_keys"] = True
                        return await self.bucket.is_empty(tr)

                    if await self.db.run(empty):
                        return
                await loop.delay(idle_delay)
