"""Client-side layers: tuple encoding, subspaces, and the directory layer.

Ref: the reference ships these in every language binding
(bindings/python/fdb/tuple.py, subspace_impl.py, directory_impl.py); they
are the idiomatic way applications structure keys on the bare KV API.
"""

from . import tuple  # noqa: A004 - mirrors fdb.tuple's name
from .directory import DirectoryLayer, DirectorySubspace, HighContentionAllocator
from .backup import BackupContainer, FileBackupAgent
from .subspace import Subspace
from .taskbucket import TaskBucket, TaskBucketExecutor
from .tuple import Versionstamp, pack, range_of, unpack

__all__ = [
    "tuple",
    "pack",
    "unpack",
    "range_of",
    "Versionstamp",
    "Subspace",
    "TaskBucket",
    "TaskBucketExecutor",
    "BackupContainer",
    "FileBackupAgent",
    "DirectoryLayer",
    "DirectorySubspace",
    "HighContentionAllocator",
]
