"""Backup and restore agents over TaskBucket.

Ref: fdbclient/FileBackupAgent.actor.cpp + BackupContainer.actor.cpp —
submitBackup queues a TaskBucket task; agent processes claim range-dump
tasks, write row pages into a backup container, and chain continuation
tasks until the manifest completes; restore replays the container in
batched transactions.

Two backup modes:
- FileBackupAgent: one-shot snapshot at a single read version (restarts
  on transaction_too_old) — the simple image copy.
- ContinuousBackupAgent: snapshot + CONTINUOUS mutation log — registers
  a consumer tag on the source logs (like the reference's `\\xff/backupLog`
  stream feeding log files), tails the merged stream into log-chunk
  files, and supports point-in-time restore at ANY version between the
  snapshot and the last logged chunk (ref: FileBackupAgent's range dumps
  + mutation logs stitched by applyMutations at restore).

The container is a directory of wire-codec page/log files on the cluster's
simulated filesystem (the BlobStore stand-in).
"""

from __future__ import annotations

from typing import List, Optional

from ..client.types import key_after
from ..flow.error import FdbError
from ..rpc.wire import decode_frame, encode_frame
from .subspace import Subspace
from .taskbucket import TaskBucket, TaskBucketExecutor

PAGE_ROWS = 1000


class BackupContainer:
    """A directory of page files + a manifest (ref: BackupContainer's
    kvranges/ + snapshot manifest layout, compacted)."""

    def __init__(self, fs, process, path: str):
        self.fs = fs
        self.process = process
        self.path = path
        self._n = 0

    async def _write_blob(self, name: str, obj) -> str:
        """Length-prefixed wire-codec blob, synced (the twin of _read_blob)."""
        f = self.fs.open(self.process, name)
        blob = encode_frame(obj)
        await f.write(0, len(blob).to_bytes(8, "big") + blob)
        await f.sync()
        return name

    async def write_page(self, index: int, begin: bytes, rows) -> str:
        return await self._write_blob(
            f"{self.path}/range-{index:06d}", (begin, rows)
        )

    async def write_manifest(
        self, version: int, pages: int, begin: bytes = b"", end: bytes = b"\xff"
    ):
        await self.write_manifest2(
            {"version": version, "pages": pages, "begin": begin, "end": end}
        )

    async def _read_blob(self, name: str):
        f = self.fs.open(self.process, name)
        size = f.size()
        if size < 8:
            return None
        img = await f.read(0, size)
        n = int.from_bytes(img[:8], "big")
        if len(img) < 8 + n:
            return None
        return decode_frame(img[8 : 8 + n])

    async def read_manifest(self) -> Optional[dict]:
        if not self.fs.exists(self.process, f"{self.path}/manifest"):
            return None
        return await self._read_blob(f"{self.path}/manifest")

    async def read_page(self, index: int):
        return await self._read_blob(f"{self.path}/range-{index:06d}")

    # -- mutation-log files (ref: the logs/ half of BackupContainer) --
    async def write_log_chunk(self, index: int, begin_ver: int,
                              end_ver: int, entries) -> str:
        """entries: [(version, [Mutation])], versions in (begin_ver,
        end_ver]."""
        return await self._write_blob(
            f"{self.path}/log-{index:06d}", (begin_ver, end_ver, entries)
        )

    async def read_log_chunk(self, index: int):
        return await self._read_blob(f"{self.path}/log-{index:06d}")

    async def delete_blob(self, name: str) -> None:
        self.fs.delete(self.process, name)

    async def delete_log_chunk(self, index: int) -> None:
        await self.delete_blob(f"{self.path}/log-{index:06d}")

    async def write_manifest2(self, manifest: dict):
        """Full-dict manifest writer (continuous backups update it after
        every durable log chunk so the container is restorable at any
        moment)."""
        await self._write_blob(f"{self.path}/manifest", manifest)


class BlobBackupContainer(BackupContainer):
    """BackupContainer over an S3-style blob store (ref: the
    blobstore:// BackupContainer flavor, BackupContainer.actor.cpp +
    fdbrpc/BlobStore.h:34).  Blobs are encoded with the versioned tagged
    wire codec — no pickle crosses the store (a corrupted or hostile
    object fails schema checks instead of executing)."""

    def __init__(self, url: str):
        from ..fileio.blobstore import BlobStoreEndpoint

        # path IS the url: backup tasks round-trip container.path through
        # the task bucket and re-open it via open_container, which must
        # re-dispatch to the blob flavor (query-string knobs are for
        # direct endpoint construction, not container URLs).
        from urllib.parse import urlparse

        if "?" in url:
            raise ValueError("container URLs carry no knob query string")
        if not urlparse(url).path.strip("/"):
            # _object_key strips the first path segment as the bucket; a
            # bucket-less URL would silently shift every object key.
            raise ValueError(
                "container URL must include a bucket: blobstore://host:port/bucket[/path]"
            )
        super().__init__(fs=None, process=None, path=url)
        self.endpoint = BlobStoreEndpoint.from_url(url)

    @staticmethod
    def _object_key(name: str) -> str:
        """blobstore://host:port/bucket/a/b -> a/b (bucket-relative)."""
        from urllib.parse import urlparse

        segs = urlparse(name).path.strip("/").split("/")
        return "/".join(segs[1:])

    async def _write_blob(self, name: str, obj) -> str:
        from ..rpc.wire import encode_frame

        self.endpoint.put_object(self._object_key(name), encode_frame(obj))
        return name

    async def _read_blob(self, name: str):
        from ..flow.error import FdbError
        from ..rpc.wire import decode_frame

        try:
            return decode_frame(
                self.endpoint.get_object(self._object_key(name))
            )
        except FdbError as e:
            if e.name == "file_not_found":
                return None
            raise

    async def read_manifest(self) -> Optional[dict]:
        return await self._read_blob(f"{self.path}/manifest")

    async def delete_blob(self, name: str) -> None:
        self.endpoint.delete_object(self._object_key(name))


def open_container(path: str, fs=None, process=None):
    """Container factory by URL scheme (ref: IBackupContainer::openContainer
    dispatching file:// vs blobstore://, BackupContainer.actor.cpp)."""
    if path.startswith("blobstore://"):
        return BlobBackupContainer(path)
    return BackupContainer(fs, process, path)


class FileBackupAgent:
    """Snapshot backup driver (ref: FileBackupAgent submitBackup :?  +
    the RangeDump task family)."""

    def __init__(
        self,
        db,
        fs,
        store_process=None,
        bucket_prefix: bytes = b"\xff\x02/backup/",
    ):
        # Task state lives in the system keyspace like the reference's
        # (ref: the backup agent's config space under \xff\x02).  The
        # container filesystem is keyed per machine, so all agents write
        # through ONE store process — the stand-in for a shared blobstore
        # endpoint (ref: BlobStoreEndpoint fdbrpc/BlobStore.actor.cpp).
        self.db = db
        self.fs = fs
        self.store_process = store_process or db.process
        self.bucket = TaskBucket(Subspace(raw_prefix=bucket_prefix))

    def container(self, path: str) -> BackupContainer:
        return open_container(path, self.fs, self.store_process)

    async def submit_backup(
        self, container: BackupContainer, begin: bytes = b"", end: bytes = b"\xff"
    ):
        """Queue the snapshot (ref: submitBackup writing the first task)."""

        async def txn(tr):
            tr.options["access_system_keys"] = True
            version = await tr.get_read_version()
            self.bucket.add(
                tr,
                {
                    b"type": b"backup_range",
                    b"path": container.path.encode(),
                    b"begin": begin,
                    b"end": end,
                    b"restart_begin": begin,
                    b"version": b"%d" % version,
                    b"page": b"0",
                },
            )

        await self.db.run(txn)

    def executor(self, db=None) -> TaskBucketExecutor:
        """A backup agent process (run several for parallelism/failover)."""
        return TaskBucketExecutor(
            db or self.db,
            self.bucket,
            {"backup_range": self._run_backup_range},
        )

    async def _run_backup_range(self, db, task) -> List[dict]:
        p = task.params
        container = self.container(p[b"path"].decode())
        begin, end = p[b"begin"], p[b"end"]
        version = int(p[b"version"])
        page = int(p[b"page"])

        async def read_page(tr):
            tr.options["access_system_keys"] = True
            tr.set_read_version(version)
            rows = await tr.get_range(
                begin, end, limit=PAGE_ROWS, snapshot=True
            )
            return rows

        try:
            rows = await db.run(read_page)
        except FdbError as e:
            if e.name != "transaction_too_old":
                raise
            # Snapshot fell out of the MVCC window: restart the whole
            # backup at a fresh version (see module docstring).
            async def fresh(tr):
                return await tr.get_read_version()

            new_version = await db.run(fresh)
            return [
                {
                    b"type": b"backup_range",
                    b"path": p[b"path"],
                    b"begin": p[b"restart_begin"],
                    b"end": end,
                    b"restart_begin": p[b"restart_begin"],
                    b"version": b"%d" % new_version,
                    b"page": b"0",
                }
            ]
        await container.write_page(page, begin, rows)
        if len(rows) >= PAGE_ROWS:
            return [
                {
                    b"type": b"backup_range",
                    b"path": p[b"path"],
                    b"begin": key_after(rows[-1][0]),
                    b"end": end,
                    b"restart_begin": p[b"restart_begin"],
                    b"version": p[b"version"],
                    b"page": b"%d" % (page + 1),
                }
            ]
        await container.write_manifest(
            version, page + 1, p[b"restart_begin"], end
        )
        return []

    async def restore(self, container: BackupContainer, batch_rows: int = 500):
        """Clear the target range and replay the container (ref:
        FileBackupAgent restore tasks, compacted to a client-side loop)."""
        manifest = await container.read_manifest()
        if manifest is None:
            raise FdbError("file_not_found")
        return await apply_snapshot_image(
            self.db, container, manifest, batch_rows
        )


async def apply_snapshot_image(
    db, container: BackupContainer, manifest: dict, batch_rows: int = 500,
    lock_aware: bool = False,
) -> int:
    """Clear the target range and replay the snapshot pages — the shared
    first half of both restore paths (ref: restore clearing restoreRange
    before applying the range files)."""

    def _opts(tr):
        if lock_aware:
            tr.options["lock_aware"] = True

    async def clear_txn(tr):
        _opts(tr)
        tr.clear_range(manifest.get("begin", b""), manifest.get("end", b"\xff"))

    await db.run(clear_txn)
    rows_restored = 0
    for i in range(manifest["pages"]):
        pg = await container.read_page(i)
        if pg is None:
            raise FdbError("file_corrupt")
        _begin, rows = pg
        for off in range(0, max(len(rows), 1), batch_rows):
            chunk = rows[off : off + batch_rows]

            async def txn(tr, chunk=chunk):
                _opts(tr)
                for k, v in chunk:
                    tr.set(k, v)

            if chunk:
                await db.run(txn)
                rows_restored += len(chunk)
    return rows_restored


class ContinuousBackupAgent:
    """Snapshot + continuous mutation log -> point-in-time restore.

    Ref: the FileBackupAgent's full shape (FileBackupAgent.actor.cpp):
    range dumps at a snapshot version PLUS log files carrying every later
    mutation (the reference taps `\xff/backupLog` written by the proxies;
    the rebuild registers a consumer tag and tails the tag-partitioned
    logs through a MergePeekCursor — same stream, pull instead of tap).
    Restore at version V: apply the snapshot image, then every logged
    mutation in (snapshot_version, V], in version order, one transaction
    per version batch (applyMutations' discipline)."""

    def __init__(self, db, fs, src_tlogs, container: BackupContainer,
                 tag: str = "_backup"):
        self.db = db
        self.fs = fs
        self.tlogs = list(src_tlogs)
        self.container = container
        self.tag = tag
        self.snapshot_version = 0
        self.logged_through = 0
        self._chunks = 0  # log chunk files written
        self._cursor = None
        self.stopped = False

    async def _pop_all(self, version: int):
        from ..server.interfaces import TLogPopRequest

        for tl in self.tlogs:
            await tl.pop.get_reply(
                self.db.process, TLogPopRequest(version=version, tag=self.tag)
            )

    async def start(self, begin: bytes = b"", end: bytes = b"\xff") -> int:
        """Register the log floor, then write the snapshot pages at one
        version; the mutation log tails from that version."""
        await self._pop_all(0)
        while True:
            tr = self.db.create_transaction()
            version = await tr.get_read_version()
            try:
                pages = 0
                lo = begin
                while True:
                    rows = await tr.get_range(
                        lo, end, limit=PAGE_ROWS, snapshot=True
                    )
                    await self.container.write_page(pages, lo, rows)
                    pages += 1
                    if len(rows) < PAGE_ROWS:
                        break
                    lo = key_after(rows[-1][0])
                break
            except FdbError as e:
                if e.name != "transaction_too_old":
                    raise
        self.snapshot_version = version
        self.logged_through = version
        await self._write_manifest(begin, end, pages)
        await self._pop_all(version)
        return version

    async def _write_manifest(self, begin: bytes, end: bytes, pages: int):
        self._pages = pages
        self._begin, self._end = begin, end
        prev = await self.container.read_manifest() or {}
        await self.container.write_manifest2(
            {
                "version": self.snapshot_version,
                "pages": pages,
                "begin": begin,
                "end": end,
                "log_chunks": self._chunks,
                "first_log_chunk": prev.get("first_log_chunk", 0),
                "logged_through": self.logged_through,
            }
        )

    async def resnapshot(self) -> int:
        """Fresh snapshot image at a new version (ref: fdbbackup's
        periodic snapshots — what makes `expire` safe: log chunks wholly
        below the NEWEST snapshot are redundant for every restorable
        target and only then may be deleted)."""
        while True:
            tr = self.db.create_transaction()
            version = await tr.get_read_version()
            try:
                pages = 0
                lo = self._begin
                while True:
                    rows = await tr.get_range(
                        lo, self._end, limit=PAGE_ROWS, snapshot=True
                    )
                    await self.container.write_page(pages, lo, rows)
                    pages += 1
                    if len(rows) < PAGE_ROWS:
                        break
                    lo = key_after(rows[-1][0])
                break
            except FdbError as e:
                if e.name != "transaction_too_old":
                    raise
        self.snapshot_version = version
        await self._write_manifest(self._begin, self._end, pages)
        return version

    async def expire(self) -> int:
        """Re-snapshot, then drop every log chunk made redundant by it
        (ref: fdbbackup expire).  Returns chunks deleted."""
        v = await self.resnapshot()
        # The tail must cover the new snapshot before old chunks go: a
        # chunk straddling v still carries needed versions and is kept by
        # expire_container's end_ver check anyway.
        return await expire_container(self.container, v)

    async def tail_once(self) -> int:
        """Pull the merged stream past logged_through into one durable log
        chunk; returns versions captured."""
        from ..rpc.peek_cursor import MergePeekCursor

        if self._cursor is not None and self._cursor.begin != self.logged_through:
            self._cursor = None
        if self._cursor is None:
            self._cursor = MergePeekCursor(
                self.db.process,
                self.tlogs,
                tags=None,  # the full stream: no tag discovery needed
                begin=self.logged_through,
                limit_versions=128,
            )
        entries, horizon = await self._cursor.next_batch()
        flat = [
            (v, self._cursor.flatten(bundle))
            for v, bundle in entries
            if v > self.logged_through
        ]
        if not flat and horizon <= self.logged_through:
            return 0
        if flat:
            await self.container.write_log_chunk(
                self._chunks, self.logged_through, horizon, flat
            )
            self._chunks += 1
        self.logged_through = max(self.logged_through, horizon)
        await self._write_manifest(self._begin, self._end, self._pages)
        await self._pop_all(self.logged_through)
        return len(flat)

    async def run(self, poll: float = 0.05):
        loop = self.db.process.network.loop
        while not self.stopped:
            n = await self.tail_once()
            if n == 0:
                await loop.delay(poll)

    async def atomic_restore(self, target_version: int = None,
                             batch_rows: int = 500) -> int:
        """Restore that is ATOMIC to every observer (ref: the
        BackupAgent atomicRestore the AtomicRestore workload drives):
        lock the database, run the multi-transaction restore lock-aware,
        unlock.  Non-lock-aware readers and writers fail database_locked
        for the duration, so no transaction can ever observe (or
        interleave with) a half-restored range; from the outside the
        restore happens at one point between the lock and the unlock."""
        from ..client.management import lock_database, unlock_database

        uid = await lock_database(self.db)
        try:
            v = await self.restore(
                target_version, batch_rows, lock_aware=True
            )
        finally:
            await unlock_database(self.db, uid)
        return v

    async def restore(self, target_version: int = None,
                      batch_rows: int = 500, lock_aware: bool = False) -> int:
        """Point-in-time restore: snapshot image + logged mutations
        through `target_version` (default: everything logged).  Returns
        the restore version actually applied."""
        from ..client.types import ATOMIC_TYPES, MutationType

        manifest = await self.container.read_manifest()
        if manifest is None:
            raise FdbError("file_not_found")
        snap_v = manifest["version"]
        logged = manifest.get("logged_through", snap_v)
        target = logged if target_version is None else target_version
        if not (snap_v <= target <= logged):
            raise FdbError("restore_invalid_version")
        begin, end = manifest.get("begin", b""), manifest.get("end", b"\xff")
        uend = min(end, b"\xff")  # user-keyspace bound
        await apply_snapshot_image(
            self.db, self.container, manifest, batch_rows,
            lock_aware=lock_aware,
        )

        def in_scope(m):
            if m.type == MutationType.CLEAR_RANGE:
                # A clear whose RANGE overlaps the backup bounds applies
                # (clamped both sides) even when its start key is below
                # `begin` — dropping it would resurrect deleted keys.
                return m.param1 < uend and m.param2 > begin
            return begin <= m.param1 < uend

        # Mutation-log replay in version order through the target
        # (chunks below first_log_chunk were expired — redundant for any
        # target the snapshot-version check above admits).
        for ci in range(manifest.get("first_log_chunk", 0),
                        manifest.get("log_chunks", 0)):
            chunk = await self.container.read_log_chunk(ci)
            if chunk is None:
                raise FdbError("file_corrupt")
            _bv, _ev, entries = chunk
            for version, mutations in entries:
                if version <= snap_v or version > target:
                    continue
                user = [m for m in mutations if in_scope(m)]
                if not user:
                    continue

                async def apply(tr, user=user):
                    if lock_aware:
                        tr.options["lock_aware"] = True
                    for m in user:
                        if m.type == MutationType.SET_VALUE:
                            tr.set(m.param1, m.param2)
                        elif m.type == MutationType.CLEAR_RANGE:
                            tr.clear_range(
                                max(m.param1, begin), min(m.param2, uend)
                            )
                        elif m.type in ATOMIC_TYPES:
                            tr.atomic_op(m.type, m.param1, m.param2)

                await self.db.run(apply)
        return target


async def describe_container(container: BackupContainer) -> dict:
    """Ref: fdbbackup `describe` — summarize restorability: the snapshot
    version, the continuous-log tail, and the restorable window."""
    manifest = await container.read_manifest()
    if manifest is None:
        return {"restorable": False}
    out = dict(manifest)
    out["restorable"] = True
    out["restorable_from"] = manifest["version"]
    out["restorable_to"] = manifest.get("logged_through", manifest["version"])
    # First retained chunk bounds the point-in-time floor after expiry.
    first = manifest.get("first_log_chunk", 0)
    chunks = manifest.get("log_chunks", 0)
    if chunks > first:
        head = await container.read_log_chunk(first)
        if head is not None:
            out["oldest_logged_version"] = head[0]
    return out


async def expire_container(container: BackupContainer,
                           before_version: int) -> int:
    """Ref: fdbbackup `expire --expire-before-version` — delete log chunks
    ENTIRELY below `before_version` (the snapshot image stays: it is the
    restore base).  Restore targets at or above the first retained
    chunk's begin remain valid; returns the number of chunks deleted."""
    manifest = await container.read_manifest()
    if manifest is None:
        return 0
    first = manifest.get("first_log_chunk", 0)
    chunks = manifest.get("log_chunks", 0)
    deleted = 0
    i = first
    while i < chunks:
        chunk = await container.read_log_chunk(i)
        if chunk is None:
            break
        _b, end_ver, _entries = chunk
        if end_ver > before_version:
            break  # this chunk still carries live versions
        await container.delete_log_chunk(i)
        deleted += 1
        i += 1
    if deleted:
        manifest["first_log_chunk"] = i
        await container.write_manifest2(manifest)
    return deleted
