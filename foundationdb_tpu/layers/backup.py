"""Backup and restore agents over TaskBucket.

Ref: fdbclient/FileBackupAgent.actor.cpp + BackupContainer.actor.cpp —
submitBackup queues a TaskBucket task; agent processes claim range-dump
tasks, write row pages into a backup container, and chain continuation
tasks until the manifest completes; restore replays the container in
batched transactions.

Rebuild scope (documented deviations): the snapshot is taken at ONE read
version carried through every page task, so the restored image is a true
point-in-time snapshot; if the version falls out of the MVCC window
mid-backup (transaction_too_old), the backup RESTARTS at a fresh version
instead of stitching a mutation log over fuzzy range reads (the
reference's mutation-log machinery arrives with DR).  The container is a
directory of pickled page files on the cluster's simulated filesystem.
"""

from __future__ import annotations

import pickle
from typing import List, Optional

from ..client.types import key_after
from ..flow.error import FdbError
from .subspace import Subspace
from .taskbucket import TaskBucket, TaskBucketExecutor

PAGE_ROWS = 1000


class BackupContainer:
    """A directory of page files + a manifest (ref: BackupContainer's
    kvranges/ + snapshot manifest layout, compacted)."""

    def __init__(self, fs, process, path: str):
        self.fs = fs
        self.process = process
        self.path = path
        self._n = 0

    async def write_page(self, index: int, begin: bytes, rows) -> str:
        name = f"{self.path}/range-{index:06d}"
        f = self.fs.open(self.process, name)
        blob = pickle.dumps((begin, rows), protocol=4)
        await f.write(0, len(blob).to_bytes(8, "big") + blob)
        await f.sync()
        return name

    async def write_manifest(
        self, version: int, pages: int, begin: bytes = b"", end: bytes = b"\xff"
    ):
        f = self.fs.open(self.process, f"{self.path}/manifest")
        blob = pickle.dumps(
            {"version": version, "pages": pages, "begin": begin, "end": end},
            protocol=4,
        )
        await f.write(0, len(blob).to_bytes(8, "big") + blob)
        await f.sync()

    async def _read_blob(self, name: str):
        f = self.fs.open(self.process, name)
        size = f.size()
        if size < 8:
            return None
        img = await f.read(0, size)
        n = int.from_bytes(img[:8], "big")
        if len(img) < 8 + n:
            return None
        return pickle.loads(img[8 : 8 + n])

    async def read_manifest(self) -> Optional[dict]:
        if not self.fs.exists(self.process, f"{self.path}/manifest"):
            return None
        return await self._read_blob(f"{self.path}/manifest")

    async def read_page(self, index: int):
        return await self._read_blob(f"{self.path}/range-{index:06d}")


class FileBackupAgent:
    """Snapshot backup driver (ref: FileBackupAgent submitBackup :?  +
    the RangeDump task family)."""

    def __init__(
        self,
        db,
        fs,
        store_process=None,
        bucket_prefix: bytes = b"\xff\x02/backup/",
    ):
        # Task state lives in the system keyspace like the reference's
        # (ref: the backup agent's config space under \xff\x02).  The
        # container filesystem is keyed per machine, so all agents write
        # through ONE store process — the stand-in for a shared blobstore
        # endpoint (ref: BlobStoreEndpoint fdbrpc/BlobStore.actor.cpp).
        self.db = db
        self.fs = fs
        self.store_process = store_process or db.process
        self.bucket = TaskBucket(Subspace(raw_prefix=bucket_prefix))

    def container(self, path: str) -> BackupContainer:
        return BackupContainer(self.fs, self.store_process, path)

    async def submit_backup(
        self, container: BackupContainer, begin: bytes = b"", end: bytes = b"\xff"
    ):
        """Queue the snapshot (ref: submitBackup writing the first task)."""

        async def txn(tr):
            tr.options["access_system_keys"] = True
            version = await tr.get_read_version()
            self.bucket.add(
                tr,
                {
                    b"type": b"backup_range",
                    b"path": container.path.encode(),
                    b"begin": begin,
                    b"end": end,
                    b"restart_begin": begin,
                    b"version": b"%d" % version,
                    b"page": b"0",
                },
            )

        await self.db.run(txn)

    def executor(self, db=None) -> TaskBucketExecutor:
        """A backup agent process (run several for parallelism/failover)."""
        return TaskBucketExecutor(
            db or self.db,
            self.bucket,
            {"backup_range": self._run_backup_range},
        )

    async def _run_backup_range(self, db, task) -> List[dict]:
        p = task.params
        container = self.container(p[b"path"].decode())
        begin, end = p[b"begin"], p[b"end"]
        version = int(p[b"version"])
        page = int(p[b"page"])

        async def read_page(tr):
            tr.options["access_system_keys"] = True
            tr.set_read_version(version)
            rows = await tr.get_range(
                begin, end, limit=PAGE_ROWS, snapshot=True
            )
            return rows

        try:
            rows = await db.run(read_page)
        except FdbError as e:
            if e.name != "transaction_too_old":
                raise
            # Snapshot fell out of the MVCC window: restart the whole
            # backup at a fresh version (see module docstring).
            async def fresh(tr):
                return await tr.get_read_version()

            new_version = await db.run(fresh)
            return [
                {
                    b"type": b"backup_range",
                    b"path": p[b"path"],
                    b"begin": p[b"restart_begin"],
                    b"end": end,
                    b"restart_begin": p[b"restart_begin"],
                    b"version": b"%d" % new_version,
                    b"page": b"0",
                }
            ]
        await container.write_page(page, begin, rows)
        if len(rows) >= PAGE_ROWS:
            return [
                {
                    b"type": b"backup_range",
                    b"path": p[b"path"],
                    b"begin": key_after(rows[-1][0]),
                    b"end": end,
                    b"restart_begin": p[b"restart_begin"],
                    b"version": p[b"version"],
                    b"page": b"%d" % (page + 1),
                }
            ]
        await container.write_manifest(
            version, page + 1, p[b"restart_begin"], end
        )
        return []

    async def restore(self, container: BackupContainer, batch_rows: int = 500):
        """Clear the target range and replay the container (ref:
        FileBackupAgent restore tasks, compacted to a client-side loop)."""
        manifest = await container.read_manifest()
        if manifest is None:
            raise FdbError("file_not_found")
        # Clear the target range first so the result IS the snapshot image,
        # not a merge with whatever was written since (ref: restore clearing
        # restoreRange before applying).
        async def clear_txn(tr):
            tr.clear_range(
                manifest.get("begin", b""), manifest.get("end", b"\xff")
            )

        await self.db.run(clear_txn)
        rows_restored = 0
        for i in range(manifest["pages"]):
            pg = await container.read_page(i)
            if pg is None:
                raise FdbError("file_corrupt")
            _begin, rows = pg
            for off in range(0, max(len(rows), 1), batch_rows):
                chunk = rows[off : off + batch_rows]

                async def txn(tr, chunk=chunk):
                    for k, v in chunk:
                        tr.set(k, v)

                if chunk:
                    await self.db.run(txn)
                    rows_restored += len(chunk)
        return rows_restored
