"""PubSub layer: feeds, inboxes, subscriptions — messages as KV rows.

Ref: layers/pubsub/pubsub.py (the reference's sample python layer) and
fdbserver/pubsub.actor.cpp (its vestigial in-server twin).  Re-derived
pull-model design: a post writes ONE row into the feed's subspace at a
versionstamped sequence (no fan-out write amplification); an inbox read
merges, per subscribed feed, everything past the inbox's per-feed
watermark, then advances the watermarks — the reference's "dirty feed"
copy, folded into the read transaction.

Layout (under one Subspace):
  ('f', feed, <stamp>) = message          -- the feed's append log
  ('s', inbox, feed) = b''                -- subscription edge
  ('w', inbox, feed) = last-seen key      -- inbox watermark per feed
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..client.types import MutationType, key_after
from .subspace import Subspace


class PubSub:
    def __init__(self, db, subspace: Optional[Subspace] = None):
        self.db = db
        self.sub = subspace or Subspace(("pubsub",))

    # -- management --
    async def create_feed(self, name: str) -> None:
        async def txn(tr):
            tr.set(self.sub.pack(("meta", "feed", name)), b"")

        await self.db.run(txn)

    async def create_inbox(self, name: str) -> None:
        async def txn(tr):
            tr.set(self.sub.pack(("meta", "inbox", name)), b"")

        await self.db.run(txn)

    async def subscribe(self, inbox: str, feed: str) -> None:
        async def txn(tr):
            if await tr.get(self.sub.pack(("meta", "feed", feed))) is None:
                raise ValueError(f"no such feed {feed!r}")
            tr.set(self.sub.pack(("s", inbox, feed)), b"")

        await self.db.run(txn)

    # -- posting --
    async def post(self, feed: str, contents: bytes) -> None:
        async def txn(tr):
            prefix = self.sub.pack(("f", feed))
            key = prefix + b"\x00" * 10 + len(prefix).to_bytes(4, "little")
            tr.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY, key, contents)

        await self.db.run(txn)

    # -- reading --
    async def get_feed_messages(
        self, feed: str, limit: int = 64
    ) -> List[bytes]:
        async def txn(tr):
            b, e = self.sub.range(("f", feed))
            return [v for _k, v in await tr.get_range(b, e, limit=limit)]

        return await self.db.run(txn)

    async def get_inbox_messages(
        self, inbox: str, limit: int = 64
    ) -> List[Tuple[str, bytes]]:
        """Unseen messages across every subscribed feed, in per-feed
        order, advancing the inbox watermarks (at-most-once per inbox)."""

        async def txn(tr):
            sb, se = self.sub.range(("s", inbox))
            feeds = [
                self.sub.unpack(k)[2] for k, _v in await tr.get_range(sb, se)
            ]
            out: List[Tuple[str, bytes]] = []
            for feed in feeds:
                wkey = self.sub.pack(("w", inbox, feed))
                water = await tr.get(wkey)
                fb, fe = self.sub.range(("f", feed))
                lo = key_after(water) if water else fb
                rows = await tr.get_range(lo, fe, limit=limit - len(out))
                for k, v in rows:
                    out.append((feed, v))
                if rows:
                    tr.set(wkey, rows[-1][0])
                if len(out) >= limit:
                    break
            return out

        return await self.db.run(txn)

    async def list_feeds(self) -> List[str]:
        async def txn(tr):
            b, e = self.sub.range(("meta", "feed"))
            return [self.sub.unpack(k)[2] for k, _v in await tr.get_range(b, e)]

        return await self.db.run(txn)
