"""Tuple layer: order-preserving encoding of typed tuples into keys.

Ref: bindings/python/fdb/tuple.py and the tuple-layer spec
(design/tuple.md in later reference versions; the 6.0 Python binding
implements the same codes).  The defining property: unpack(pack(t)) == t
and pack(t1) < pack(t2) iff t1 sorts before t2 element-wise — so tuples
index correctly as keys.

Type codes (the spec's):
  0x00 null            0x01 bytes          0x02 unicode
  0x05 nested tuple    0x0c-0x1c ints      0x20 float  0x21 double
  0x26 false 0x27 true 0x30 uuid           0x33 versionstamp

This is a from-scratch implementation of the documented format (value
layouts reconstructed from the spec, not the binding's code).
"""

from __future__ import annotations

import struct
import uuid as _uuid
from typing import Any, Iterable, Tuple

NULL = 0x00
BYTES = 0x01
STRING = 0x02
NESTED = 0x05
INT_ZERO = 0x14  # 0x14-n .. 0x14+n for n-byte negative/positive ints
FLOAT = 0x20
DOUBLE = 0x21
FALSE = 0x26
TRUE = 0x27
UUID = 0x30
VERSIONSTAMP = 0x33


class Versionstamp:
    """An 80-bit commit version + 16-bit batch order + 16-bit user order
    (ref: fdb.tuple.Versionstamp)."""

    __slots__ = ("tr_version", "user_version")

    def __init__(self, tr_version: bytes = b"\xff" * 10, user_version: int = 0):
        assert len(tr_version) == 10
        self.tr_version = tr_version
        self.user_version = user_version

    def is_complete(self) -> bool:
        return self.tr_version != b"\xff" * 10

    def to_bytes(self) -> bytes:
        return self.tr_version + struct.pack(">H", self.user_version)

    def __eq__(self, other):
        return (
            isinstance(other, Versionstamp)
            and self.tr_version == other.tr_version
            and self.user_version == other.user_version
        )

    def __hash__(self):
        return hash((self.tr_version, self.user_version))

    def __repr__(self):
        return f"Versionstamp({self.tr_version!r}, {self.user_version})"


def _encode_bytes_escaped(out: bytearray, b: bytes):
    out.extend(b.replace(b"\x00", b"\x00\xff"))
    out.append(0x00)


def _float_tr(b: bytes) -> bytes:
    """Order-preserving IEEE transform: negative numbers flip every bit,
    non-negative flip only the sign bit (spec's float encoding)."""
    if b[0] & 0x80:
        return bytes(x ^ 0xFF for x in b)
    return bytes([b[0] ^ 0x80]) + b[1:]


def _float_untr(b: bytes) -> bytes:
    if b[0] & 0x80:  # transformed non-negative
        return bytes([b[0] ^ 0x80]) + b[1:]
    return bytes(x ^ 0xFF for x in b)


def _encode_one(out: bytearray, v: Any, nested: bool):
    if v is None:
        out.append(NULL)
        if nested:
            # Inside a nested tuple, null escapes so the terminator stays
            # unambiguous (spec: 0x00 0xff).
            out.append(0xFF)
    elif v is True:
        out.append(TRUE)
    elif v is False:
        out.append(FALSE)
    elif isinstance(v, bytes):
        out.append(BYTES)
        _encode_bytes_escaped(out, v)
    elif isinstance(v, str):
        out.append(STRING)
        _encode_bytes_escaped(out, v.encode("utf-8"))
    elif isinstance(v, int):
        if v == 0:
            out.append(INT_ZERO)
        elif v > 0:
            n = (v.bit_length() + 7) // 8
            if n > 8:
                raise ValueError("int too large for tuple encoding")
            out.append(INT_ZERO + n)
            out.extend(v.to_bytes(n, "big"))
        else:
            n = ((-v).bit_length() + 7) // 8
            if n > 8:
                raise ValueError("int too large for tuple encoding")
            out.append(INT_ZERO - n)
            # Offset encoding: v + (2^(8n) - 1), big-endian — preserves
            # order among negatives and below all positives.
            out.extend((v + (1 << (8 * n)) - 1).to_bytes(n, "big"))
    elif isinstance(v, float):
        out.append(DOUBLE)
        out.extend(_float_tr(struct.pack(">d", v)))
    elif isinstance(v, _uuid.UUID):
        out.append(UUID)
        out.extend(v.bytes)
    elif isinstance(v, Versionstamp):
        out.append(VERSIONSTAMP)
        out.extend(v.to_bytes())
    elif isinstance(v, (tuple, list)):
        out.append(NESTED)
        for x in v:
            _encode_one(out, x, nested=True)
        out.append(0x00)
    else:
        raise TypeError(f"unpackable tuple element: {type(v)}")


def pack(t: Iterable[Any]) -> bytes:
    out = bytearray()
    for v in t:
        _encode_one(out, v, nested=False)
    return bytes(out)


def _decode_escaped(b: bytes, pos: int) -> Tuple[bytes, int]:
    out = bytearray()
    while True:
        i = b.index(b"\x00", pos)
        out.extend(b[pos:i])
        if i + 1 < len(b) and b[i + 1] == 0xFF:
            out.append(0x00)
            pos = i + 2
        else:
            return bytes(out), i + 1


def _decode_one(b: bytes, pos: int, nested: bool) -> Tuple[Any, int]:
    code = b[pos]
    pos += 1
    if code == NULL:
        if nested:
            assert b[pos] == 0xFF
            return None, pos + 1
        return None, pos
    if code == TRUE:
        return True, pos
    if code == FALSE:
        return False, pos
    if code == BYTES:
        return _decode_escaped(b, pos)
    if code == STRING:
        s, pos = _decode_escaped(b, pos)
        return s.decode("utf-8"), pos
    if INT_ZERO - 8 <= code <= INT_ZERO + 8:
        n = code - INT_ZERO
        if n == 0:
            return 0, pos
        if n > 0:
            return int.from_bytes(b[pos : pos + n], "big"), pos + n
        n = -n
        return (
            int.from_bytes(b[pos : pos + n], "big") - (1 << (8 * n)) + 1,
            pos + n,
        )
    if code == DOUBLE:
        return struct.unpack(">d", _float_untr(b[pos : pos + 8]))[0], pos + 8
    if code == FLOAT:
        return struct.unpack(">f", _float_untr(b[pos : pos + 4]))[0], pos + 4
    if code == UUID:
        return _uuid.UUID(bytes=b[pos : pos + 16]), pos + 16
    if code == VERSIONSTAMP:
        vs = Versionstamp(
            b[pos : pos + 10], struct.unpack(">H", b[pos + 10 : pos + 12])[0]
        )
        return vs, pos + 12
    if code == NESTED:
        items = []
        while True:
            if b[pos] == 0x00 and not (
                pos + 1 < len(b) and b[pos + 1] == 0xFF
            ):
                return tuple(items), pos + 1
            v, pos = _decode_one(b, pos, nested=True)
            items.append(v)
    raise ValueError(f"unknown tuple type code {code:#x} at {pos - 1}")


def unpack(b: bytes) -> tuple:
    items = []
    pos = 0
    while pos < len(b):
        v, pos = _decode_one(b, pos, nested=False)
        items.append(v)
    return tuple(items)


def range_of(t: Iterable[Any]) -> Tuple[bytes, bytes]:
    """(begin, end) covering every key that extends tuple t (ref:
    fdb.tuple.range)."""
    p = pack(t)
    return p + b"\x00", p + b"\xff"
