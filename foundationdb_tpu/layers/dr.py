"""Disaster recovery: continuous replication into a SECOND cluster.

Ref: fdbclient/DatabaseBackupAgent.actor.cpp — DR copies a source cluster
into a destination cluster by shipping the mutation stream; the
destination applies each source version atomically, so it is at every
moment a consistent (possibly older) snapshot of the source.  The agent
here plays the LogRouter/backup-worker part directly: it registers a
consumer tag on the source's logs (holding their discard floor, like a
storage), takes an initial range snapshot, then tails the log and applies
each version's user-keyspace mutations to the destination in one
transaction.

Multi-log sources ride a MergePeekCursor over the tag-partitioned log
set (ref: the merged peek cursors DatabaseBackupAgent's log workers use);
single-log sources are just the 1-wide case.
"""

from __future__ import annotations

from typing import List, Optional

from ..client.types import MutationType, key_after
from ..flow.error import FdbError
from ..server.interfaces import TLogPopRequest

DR_TAG = "_dr"
SNAPSHOT_PAGE = 1000
# Destination-side progress marker: every apply transaction reads it and
# writes the new version, making replay idempotent under blind retries
# after commit_unknown_result AND resumable across agent restarts (ref:
# the apply-version bookkeeping DatabaseBackupAgent keeps in the
# destination).
DR_APPLIED_KEY = b"\xff/dr/applied"
# b"syncing" while the initial snapshot is (re)building the destination —
# consumers must treat the data as invalid until it returns to b"tailing"
# (ref: the destination lock DatabaseBackupAgent holds during the initial
# range copy).
DR_STATE_KEY = b"\xff/dr/state"


class DRAgent:
    def __init__(self, src_db, dst_db, src_tlogs: List, tag: str = DR_TAG):
        self.src_db = src_db
        self.dst_db = dst_db
        self.tlogs = list(src_tlogs)
        self.tag = tag
        self.applied = 0  # source version the destination reflects
        self._storage_tags: List[str] = []
        self._cursor = None  # MergePeekCursor, (re)built on tag changes
        self.stopped = False

    async def start(self, skip_snapshot_from: Optional[int] = None) -> int:
        """Register the consumer floor, then copy the initial snapshot.
        Registration happens FIRST so nothing the snapshot misses can be
        discarded before tailing begins (ref: the backup range lock before
        the initial snapshot).

        skip_snapshot_from=V skips the copy entirely: the caller certifies
        the destination ALREADY equals the source as of source-version V
        (the atomic-switchover contract — both sides locked and drained;
        ref: atomicSwitchover avoiding a recopy)."""
        proc = self.src_db.process
        await self._pop_all(0)
        await self._refresh_tags()
        if skip_snapshot_from is not None:
            self.applied = skip_snapshot_from
            await self._mark_applied(skip_snapshot_from, state=b"tailing")
            await self._pop_all(skip_snapshot_from)
            return skip_snapshot_from
        # Resume: a previous incarnation that finished its snapshot left
        # applied/state markers, and its pop floor is PERSISTED on the
        # source logs, so the stream since then is still retained — tail
        # from the marker instead of re-copying everything.
        resume = await self._read_progress()
        if resume is not None:
            self.applied = resume
            await self._pop_all(resume)
            return resume
        # Snapshot at one source read version (pages share it; a too-old
        # snapshot restarts fresh, same discipline as the file backup).
        while True:
            tr = self.src_db.create_transaction()
            tr.options["lock_aware"] = True
            version = await tr.get_read_version()
            try:
                await self._copy_snapshot(tr, version)
                break
            except FdbError as e:
                if e.name != "transaction_too_old":
                    raise
        self.applied = version
        await self._mark_applied(version, state=b"tailing")
        await self._pop_all(version)
        return version

    async def _pop_all(self, version: int, unregister: bool = False):
        proc = self.src_db.process
        for tl in self.tlogs:
            await tl.pop.get_reply(
                proc,
                TLogPopRequest(
                    version=version, tag=self.tag, unregister=unregister
                ),
            )

    async def _read_progress(self) -> Optional[int]:
        async def txn(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            state = await tr.get(DR_STATE_KEY)
            raw = await tr.get(DR_APPLIED_KEY)
            if state == b"tailing" and raw is not None:
                return int(raw)
            return None

        return await self.dst_db.run(txn)

    async def _mark_applied(self, version: int, state: bytes = None):
        async def txn(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            tr.set(DR_APPLIED_KEY, b"%d" % version)
            if state is not None:
                tr.set(DR_STATE_KEY, state)

        await self.dst_db.run(txn)

    async def _refresh_tags(self):
        """Discover the source's per-storage tags from \xff/serverList/
        (sharded sources tag user mutations per storage, not with the
        default tag)."""
        from ..server import system_keys as sk

        async def txn(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            rows = await tr.get_range(
                sk.SERVER_LIST_PREFIX, sk.SERVER_LIST_END
            )
            return [sk.server_list_id(k) for k, _v in rows]

        fresh = await self.src_db.run(txn)
        if set(fresh) - set(self._storage_tags):
            self._cursor = None  # widened tag set: rebuild from `applied`
        self._storage_tags = sorted(set(self._storage_tags) | set(fresh))

    async def _copy_snapshot(self, tr, version: int):
        # Mark the destination INVALID for the whole multi-transaction
        # copy (cleared back to "tailing" only when it completes), then
        # wipe so the result IS the snapshot.
        async def wipe(d):
            d.options["access_system_keys"] = True
            d.set(DR_STATE_KEY, b"syncing")
            d.clear_range(b"", b"\xff")

        await self.dst_db.run(wipe)
        lo = b""
        while True:
            rows = await tr.get_range(
                lo, b"\xff", limit=SNAPSHOT_PAGE, snapshot=True
            )

            async def put(d, rows=rows):
                for k, v in rows:
                    d.set(k, v)

            if rows:
                await self.dst_db.run(put)
            if len(rows) < SNAPSHOT_PAGE:
                return
            lo = key_after(rows[-1][0])

    def _get_cursor(self):
        """The merge cursor over every source log for the current tag set;
        rebuilt (from `applied`) whenever the tag set widens — or whenever
        the cursor ran ahead of `applied` (a tail_once that raised or was
        cancelled mid-batch): reusing it would silently skip the versions
        in (applied, cursor.begin]."""
        from ..rpc.peek_cursor import MergePeekCursor

        if self._cursor is not None and self._cursor.begin != self.applied:
            self._cursor = None
        if self._cursor is None:
            self._cursor = MergePeekCursor(
                self.src_db.process,
                self.tlogs,
                tags=self._tags(),
                begin=self.applied,
                limit_versions=64,
            )
        return self._cursor

    async def tail_once(self) -> int:
        """Pull the merged source stream past `applied` and apply each
        version's user-keyspace mutations to the destination in ONE
        transaction (the prefix-consistency guarantee).  Returns versions
        applied."""
        before = self.applied
        cursor = self._get_cursor()
        entries, horizon = await cursor.next_batch()
        n = 0
        new_tag = False
        for version, bundle in entries:
            if version <= self.applied:
                continue
            mutations = cursor.flatten(bundle)
            from ..client.types import ATOMIC_TYPES
            from ..server import system_keys as sk

            # In-stream tag discovery: a storage registration rides the
            # broadcast tag, and any mutation tagged ONLY with the new
            # storage can exist at later versions only (routing to it
            # requires keyServers commits after the registration) — so
            # adding the tag before peeking past this version closes the
            # new-storage race without polling.
            for m in mutations:
                if (
                    m.type == MutationType.SET_VALUE
                    and m.param1.startswith(sk.SERVER_LIST_PREFIX)
                ):
                    sid = sk.server_list_id(m.param1)
                    if sid not in self._storage_tags:
                        self._storage_tags.append(sid)
                        new_tag = True
            user = [m for m in mutations if m.param1 < b"\xff"]

            async def apply(d, user=user, version=version):
                # Idempotence fence: a blind retry after a lost commit
                # reply (commit_unknown_result) re-reads the progress
                # marker and no-ops if this version already applied.
                d.options["access_system_keys"] = True
                d.options["lock_aware"] = True
                raw = await d.get(DR_APPLIED_KEY)
                if raw is not None and int(raw) >= version:
                    return
                for m in user:
                    if m.type == MutationType.SET_VALUE:
                        d.set(m.param1, m.param2)
                    elif m.type == MutationType.CLEAR_RANGE:
                        d.clear_range(m.param1, min(m.param2, b"\xff"))
                    elif m.type in ATOMIC_TYPES:
                        # Replaying the op against the (identical) prefix
                        # state yields the identical result (ref: mutation
                        # log application in applyMutations).
                        d.atomic_op(m.type, m.param1, m.param2)
                d.set(DR_APPLIED_KEY, b"%d" % version)

            if user:
                await self.dst_db.run(apply)
            self.applied = version  # fdblint: ignore[RACE004]: applied is owned by the single tail loop; start() writes it only before spawning the loop (phase-ordered, never concurrent)
            n += 1
            if new_tag:
                # Later versions in THIS batch may be missing the new
                # tag's bundles: rebuild the cursor from `applied` with
                # the widened tag set.
                self._cursor = None
                break
        # The merged horizon is known-complete — safe to adopt even
        # mid-backlog: versions below it carrying none of our tags would
        # otherwise wedge the window forever.
        if not new_tag and horizon > self.applied:
            self.applied = horizon
        if self.applied > before:
            await self._pop_all(self.applied)
        return n

    def _tags(self) -> List[str]:
        """Every tag carrying user mutations: the defaults plus the
        storage tags discovered from the source's serverList.  On a single
        log, the union of all tags is the full stream."""
        from ..server.interfaces import TAG_ALL, TAG_DEFAULT

        return [TAG_DEFAULT, TAG_ALL] + list(self._storage_tags)

    async def run(self, poll: float = 0.02, tag_refresh: float = 1.0):
        loop = self.src_db.process.network.loop
        last_refresh = -1e18
        self._running = True
        try:
            while not self.stopped:
                if loop.now() - last_refresh > tag_refresh:
                    await self._refresh_tags()
                    last_refresh = loop.now()
                n = await self.tail_once()
                if n == 0:
                    await loop.delay(poll)
        finally:
            self._running = False

    async def abort(self) -> None:
        """fdbdr abort (ref: DatabaseBackupAgent::abortBackup; the
        BackupToDBAbort workload asserts this contract): stop tailing,
        release the source-side consumer floor (unregister — the logs
        must not retain forever for a dead DR), and mark the destination
        state aborted.  The destination KEEPS its data — a consistent
        prefix of the source (every apply was one whole source version
        batch) — and is immediately usable for ordinary writes."""
        loop = self.src_db.process.network.loop
        self.stopped = True
        # Wait out an in-flight tail_once in the run() loop (same
        # discipline as switchover): aborting mid-apply is fine, aborting
        # mid-bookkeeping would race the state marker write below.
        while getattr(self, "_running", False):
            await loop.delay(0.01)
        await self._pop_all(self.applied, unregister=True)
        await self._mark_applied(self.applied, state=b"aborted")

    async def switchover(self, reverse_tlogs: List) -> "DRAgent":
        """fdbdr switch (ref: DatabaseBackupAgent::atomicSwitchover):

          1. lock the SOURCE (no new primary writes),
          2. lock the DESTINATION (freeze it while direction flips),
          3. drain the remaining stream — the two databases are now equal,
          4. start the REVERSE agent with NO recopy (skip_snapshot_from at
             the frozen destination's version),
          5. unlock the destination: it is the new primary; the old
             primary STAYS locked as the replica (the reference keeps DR
             destinations locked; every agent transaction is lock-aware).

        Returns the running-direction-reversed agent; this agent stops."""
        from ..client.management import lock_database, unlock_database

        loop = self.src_db.process.network.loop
        self.stopped = True
        # WAIT for the spawned run() loop to actually exit: a tail_once
        # in flight there shares this cursor — racing it could adopt a
        # horizon past a version whose mutations the other coroutine is
        # still holding, silently dropping them right before we certify
        # equality.
        while getattr(self, "_running", False):
            await loop.delay(0.01)

        src_uid = await lock_database(self.src_db)
        self.switch_lock_uid = src_uid
        dst_uid = None
        try:
            tr = self.src_db.create_transaction()
            tr.options["lock_aware"] = True
            final_v = await tr.get_read_version()
            dst_uid = await lock_database(self.dst_db)
            while self.applied < final_v:
                n = await self.tail_once()
                if n == 0:
                    await loop.delay(0.02)

            rev = DRAgent(
                self.dst_db, self.src_db, reverse_tlogs,
                tag=self.tag + "_rev",
            )
            tr2 = self.dst_db.create_transaction()
            tr2.options["lock_aware"] = True
            dest_v = await tr2.get_read_version()
            await rev.start(skip_snapshot_from=dest_v)
        except BaseException:
            # Unwind: the primary must not stay locked behind a failed
            # switch (the caller may restart run() and retry later).
            try:
                if dst_uid is not None:
                    await unlock_database(self.dst_db, dst_uid)
            finally:
                await unlock_database(self.src_db, src_uid)
                self.stopped = False
            raise
        # Release the forward consumer tag: its pop floor is frozen at the
        # drained version and would otherwise retain every post-switch
        # mutation on the old primary's logs forever.
        await self._pop_all(self.applied, unregister=True)
        await unlock_database(self.dst_db, dst_uid)
        return rev

    def set_storage_tags(self, tags: List[str]):
        """Manual override for tests; run() refreshes from serverList."""
        self._storage_tags = list(tags)
