"""Directory layer: a filesystem-like namespace mapping paths to short
allocated key prefixes.

Ref: bindings/python/fdb/directory_impl.py — DirectoryLayer keeps a node
tree under `\xfe` (each node records its children and layer tag), and
allocates content prefixes with the HighContentionAllocator so many
clients can create directories concurrently without conflicting.  This is
a from-scratch implementation of the same semantics (same node-tree idea
and the documented HCA windowing algorithm; the on-disk layout is NOT
byte-compatible with the reference bindings and says so here).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..client.types import MutationType
from ..flow.error import FdbError
from . import tuple as fdbtuple
from .subspace import Subspace


class HighContentionAllocator:
    """Integer id allocator safe under high concurrency (ref:
    HighContentionAllocator in directory_impl.py).  Counters track how full
    the current window is; candidates are probed randomly within the
    window with snapshot reads so concurrent allocators rarely conflict."""

    def __init__(self, subspace: Subspace):
        self.counters = subspace[0]
        self.recent = subspace[1]

    @staticmethod
    def _window_size(start: int) -> int:
        if start < 255:
            return 64
        if start < 65535:
            return 1024
        return 8192

    async def allocate(self, tr) -> int:
        rng = tr.db.process.network.loop.rng
        while True:
            # Current window start = the last counters key.
            rows = await tr.get_range(
                *self.counters.range(), limit=1, reverse=True, snapshot=True
            )
            start = (
                self.counters.unpack(rows[0][0])[0] if rows else 0
            )
            window_advanced = False
            while True:
                if window_advanced:
                    tr.clear_range(
                        self.counters.range()[0], self.counters.pack((start,))
                    )
                    tr.clear_range(
                        self.recent.range()[0], self.recent.pack((start,))
                    )
                tr.atomic_op(
                    MutationType.ADD_VALUE,
                    self.counters.pack((start,)),
                    (1).to_bytes(8, "little"),
                )
                raw = await tr.get(self.counters.pack((start,)), snapshot=True)
                count = int.from_bytes(raw or b"", "little")
                window = self._window_size(start)
                if count * 2 < window:
                    break
                start += window
                window_advanced = True
            while True:
                candidate = start + int(rng.random_int(0, window))
                latest = await tr.get_range(
                    *self.counters.range(), limit=1, reverse=True, snapshot=True
                )
                latest_start = (
                    self.counters.unpack(latest[0][0])[0] if latest else 0
                )
                if latest_start > start:
                    break  # window moved under us; restart
                # NON-snapshot read: two allocators probing the same
                # candidate must conflict at commit (write-write alone
                # would not), so exactly one wins and the loser retries
                # with a new random candidate (ref: the plain
                # tr[recent[candidate]] read in 6.0's allocate).
                taken = await tr.get(self.recent.pack((candidate,)))
                if taken is None:
                    tr.set(self.recent.pack((candidate,)), b"")
                    return candidate


class DirectorySubspace(Subspace):
    """The handle create_or_open returns: a Subspace over the directory's
    allocated prefix plus its path/layer metadata."""

    def __init__(self, path: Tuple[str, ...], prefix: bytes, layer: bytes,
                 directory: "DirectoryLayer"):
        super().__init__(raw_prefix=prefix)
        self.path = path
        self.layer = layer
        self._directory = directory

    def __repr__(self):
        return f"DirectorySubspace(path={self.path}, prefix={self.raw_prefix!r})"


class DirectoryLayer:
    def __init__(self, node_prefix: bytes = b"\xfe", content_prefix: bytes = b""):
        self._node_root = Subspace(raw_prefix=node_prefix)
        self._content_prefix = content_prefix
        self._allocator = HighContentionAllocator(
            self._node_root[b"hca"]
        )

    # -- node helpers: a directory's node is keyed by its prefix --
    def _node(self, prefix: bytes) -> Subspace:
        return self._node_root[prefix]

    def _child_key(self, node: Subspace, name: str) -> bytes:
        return node[0].pack((name,))

    async def _find(self, tr, path: Tuple[str, ...]):
        """(node, prefix) for path, or (None, None)."""
        prefix = b""  # the root directory's conventional prefix
        node = self._node(prefix)
        for name in path:
            child = await tr.get(self._child_key(node, name))
            if child is None:
                return None, None
            prefix = child
            node = self._node(prefix)
        return node, prefix

    async def create_or_open(self, tr, path, layer: bytes = b""):
        return await self._create_or_open(tr, tuple(path), layer, True, True)

    async def create(self, tr, path, layer: bytes = b""):
        return await self._create_or_open(tr, tuple(path), layer, True, False)

    async def open(self, tr, path, layer: bytes = b""):
        return await self._create_or_open(tr, tuple(path), layer, False, True)

    async def _create_or_open(
        self, tr, path: Tuple[str, ...], layer: bytes,
        allow_create: bool, allow_open: bool,
    ):
        if not path:
            raise ValueError("the root directory cannot be opened")
        node, prefix = await self._find(tr, path)
        if node is not None:
            if not allow_open:
                raise FdbError("directory_already_exists")
            existing = await tr.get(node.pack((b"layer",))) or b""
            if layer and existing != layer:
                raise FdbError("directory_incompatible_layer")
            return DirectorySubspace(path, prefix, existing, self)
        if not allow_create:
            raise FdbError("directory_does_not_exist")
        # Create missing parents, then this directory.
        parent_node = self._node(b"")
        for name in path[:-1]:
            child = await tr.get(self._child_key(parent_node, name))
            if child is None:
                sub = await self._create_one(tr, parent_node, name, b"")
                child = sub
            parent_node = self._node(child)
        sub_prefix = await self._create_one(
            tr, parent_node, path[-1], layer
        )
        return DirectorySubspace(path, sub_prefix, layer, self)

    async def _create_one(self, tr, parent_node: Subspace, name: str,
                          layer: bytes) -> bytes:
        vid = await self._allocator.allocate(tr)
        prefix = self._content_prefix + fdbtuple.pack((vid,))
        # The allocated prefix must be virgin (ref: the prefix-free check).
        existing = await tr.get_range(
            prefix, prefix + b"\xff", limit=1, snapshot=True
        )
        if existing:
            raise FdbError("directory_prefix_not_empty")
        tr.set(self._child_key(parent_node, name), prefix)
        node = self._node(prefix)
        tr.set(node.pack((b"layer",)), layer)
        return prefix

    async def exists(self, tr, path) -> bool:
        node, _ = await self._find(tr, tuple(path))
        return node is not None

    async def list(self, tr, path=()) -> List[str]:
        node, _ = await self._find(tr, tuple(path))
        if node is None:
            raise FdbError("directory_does_not_exist")
        rows = await tr.get_range(*node[0].range())
        return [node[0].unpack(k)[0] for k, _v in rows]

    async def move(self, tr, old_path, new_path):
        old_path, new_path = tuple(old_path), tuple(new_path)
        if new_path[: len(old_path)] == old_path:
            raise FdbError("directory_moved_under_itself")
        node, prefix = await self._find(tr, old_path)
        if node is None:
            raise FdbError("directory_does_not_exist")
        if (await self._find(tr, new_path))[0] is not None:
            raise FdbError("directory_already_exists")
        parent_node, _ = await self._find(tr, new_path[:-1])
        if parent_node is None:
            raise FdbError("directory_does_not_exist")
        old_parent, _ = await self._find(tr, old_path[:-1])
        tr.clear(self._child_key(old_parent, old_path[-1]))
        tr.set(self._child_key(parent_node, new_path[-1]), prefix)
        layer = await tr.get(node.pack((b"layer",))) or b""
        return DirectorySubspace(new_path, prefix, layer, self)

    async def remove(self, tr, path) -> bool:
        """Delete the directory, its subdirectories, and ALL content."""
        path = tuple(path)
        if not path:
            raise ValueError("the root directory cannot be removed")
        node, prefix = await self._find(tr, path)
        if node is None:
            return False
        await self._remove_recursive(tr, node, prefix)
        parent_node, _ = await self._find(tr, path[:-1])
        tr.clear(self._child_key(parent_node, path[-1]))
        return True

    async def _remove_recursive(self, tr, node: Subspace, prefix: bytes):
        rows = await tr.get_range(*node[0].range())
        for _k, child_prefix in rows:
            await self._remove_recursive(
                tr, self._node(child_prefix), child_prefix
            )
        # Content + node metadata.
        tr.clear_range(prefix, prefix + b"\xff")
        b, e = self._node(prefix).range()
        tr.clear_range(b, e)
