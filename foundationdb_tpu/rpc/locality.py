"""Locality data + replication policy algebra.

Ref: fdbrpc/Locality.h:117 (LocalityData: processId/zoneId/machineId/dcId
key-value sets) and fdbrpc/ReplicationPolicy.h — the policy combinators
`PolicyOne` (:33, any one replica), `PolicyAcross` (:99, k replicas across
distinct values of an attribute, each satisfying a sub-policy), and
`PolicyAnd` (:119, all sub-policies at once).  `select_replicas` picks a
satisfying subset from candidates; `validate` checks one.  Team building
(DD) and tlog recruitment use these to spread replicas across failure
domains.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence


@dataclass(frozen=True)
class LocalityData:
    """Ref: LocalityData fdbrpc/Locality.h:117 — the standard keys."""

    process_id: str = ""
    zone_id: str = ""
    machine_id: str = ""
    dc_id: str = ""

    def get(self, attr: str) -> str:
        return {
            "processid": self.process_id,
            "zoneid": self.zone_id,
            "machineid": self.machine_id,
            "dcid": self.dc_id,
        }[attr.lower()]


class ReplicationPolicy:
    def validate(self, localities: Sequence[LocalityData]) -> bool:
        raise NotImplementedError

    def select_replicas(
        self, candidates: Dict[object, LocalityData]
    ) -> Optional[List[object]]:
        """A minimal-ish satisfying subset of candidate ids, or None.
        Deterministic: candidates are considered in sorted-id order (the
        reference randomizes; determinism keeps simulation reproducible)."""
        raise NotImplementedError


class PolicyOne(ReplicationPolicy):
    """Any single replica (ref: PolicyOne :33)."""

    def validate(self, localities):
        return len(localities) >= 1

    def select_replicas(self, candidates):
        for key in sorted(candidates, key=str):
            return [key]
        return None

    def __repr__(self):
        return "One()"


class PolicyAcross(ReplicationPolicy):
    """`count` replicas with distinct values of `attr`, each group
    satisfying `sub` (ref: PolicyAcross :99 — e.g.
    Across(2, "zoneid", One()) = two replicas in two distinct zones)."""

    def __init__(self, count: int, attr: str, sub: ReplicationPolicy = None):
        self.count = count
        self.attr = attr
        self.sub = sub or PolicyOne()

    def validate(self, localities):
        groups: Dict[str, list] = {}
        for loc in localities:
            groups.setdefault(loc.get(self.attr), []).append(loc)
        ok = sum(1 for g in groups.values() if self.sub.validate(g))
        return ok >= self.count

    def select_replicas(self, candidates):
        groups: Dict[str, Dict[object, LocalityData]] = {}
        for key in sorted(candidates, key=str):
            loc = candidates[key]
            groups.setdefault(loc.get(self.attr), {})[key] = loc
        chosen: List[object] = []
        used = 0
        for val in sorted(groups):
            if used >= self.count:
                break
            sel = self.sub.select_replicas(groups[val])
            if sel is not None:
                chosen.extend(sel)
                used += 1
        return chosen if used >= self.count else None

    def __repr__(self):
        return f"Across({self.count}, {self.attr}, {self.sub!r})"


class PolicyAnd(ReplicationPolicy):
    """All sub-policies simultaneously (ref: PolicyAnd :119).  Selection is
    greedy: the union of each sub-policy's picks, re-validated."""

    def __init__(self, subs: List[ReplicationPolicy]):
        self.subs = list(subs)

    def validate(self, localities):
        return all(p.validate(localities) for p in self.subs)

    def select_replicas(self, candidates):
        chosen: Dict[object, LocalityData] = {}
        for p in self.subs:
            sel = p.select_replicas(candidates)
            if sel is None:
                return None
            for k in sel:
                chosen[k] = candidates[k]
        locs = list(chosen.values())
        if not self.validate(locs):
            return None
        return sorted(chosen, key=str)

    def __repr__(self):
        return f"And({self.subs!r})"
