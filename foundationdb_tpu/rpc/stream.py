"""Typed request/reply endpoints over the network fabric.

Ref: fdbrpc/fdbrpc.h — RequestStream :212 (server side: a stream of
requests), ReplyPromise :94 (a promise whose fulfillment travels back over
the network as a serialized SAV), getReply :235 (send + wait).  The rebuild
keeps the shape: a server pops (request, reply) pairs; a client's get_reply
returns a future that errors with broken_promise if the server dies
(ref: NetSAV broken on connection failure).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Any, Optional

from ..flow.error import FdbError
from ..flow.eventloop import TaskPriority
from ..flow.future import Future, Promise, PromiseStream
from .network import Endpoint, SimNetwork, SimProcess


def BrokenPromise() -> FdbError:
    return FdbError("broken_promise")


def well_known_token(name: str) -> int:
    """Stable token derived from the stream name, so a client-side ref keeps
    working across the server process's reboot (ref: well-known endpoint
    tokens, e.g. the coordinators' WLTOKEN_* constants)."""
    return (1 << 40) | (zlib.crc32(name.encode()) & 0xFFFFFFFF)


@dataclass
class _Envelope:
    request: Any
    reply_to: Optional[Endpoint]


class Reply:
    """Server-side handle for answering one request; send() travels back to
    the caller's one-shot reply endpoint (ref: ReplyPromise fdbrpc.h:94).

    Dropping a Reply unanswered sends broken_promise to the requester —
    exactly the reference's NetSAV/ReplyPromise destructor semantics: a
    server actor that dies (e.g. its role was replaced by a new generation
    on the same live process) breaks the caller's promise instead of
    leaving it hanging forever (ref: ReplyPromise ~destructor sendError,
    fdbrpc.h:94-120)."""

    __slots__ = ("_net", "_src", "_reply_to", "_sent")

    def __init__(self, net: SimNetwork, src: SimProcess, reply_to: Optional[Endpoint]):
        self._net = net
        self._src = src
        self._reply_to = reply_to
        self._sent = False

    def send(self, value=None):
        self._send((False, value))

    def send_error(self, name: str, detail=None):
        # Structured cause (ISSUE 17): only a detail-bearing error widens
        # the wire to (True, (name, detail)) — bare errors keep the
        # original (True, name) shape, so every existing path and replay
        # stays byte-identical.
        self._send((True, (name, detail) if detail is not None else name))

    def _send(self, wire):
        if self._sent or self._reply_to is None:
            return
        self._sent = True
        self._net.send_from(
            self._src, self._reply_to, wire, priority=TaskPriority.DefaultPromiseEndpoint
        )

    def __del__(self):
        if not self._sent and self._reply_to is not None:
            try:
                self._send((True, "broken_promise"))
            except Exception:  # noqa: BLE001 - interpreter teardown  # fdblint: ignore[ERR001]: __del__ during interpreter teardown — the network may be half-collected, nothing can surface it
                pass


class RequestStream:
    """Server side: a well-known endpoint producing (request, Reply) pairs."""

    def __init__(
        self,
        process: SimProcess,
        name: str,
        token: Optional[int] = None,
        well_known: bool = False,
    ):
        self.process = process
        self.name = name
        replace = False
        if token is None and well_known:
            token = well_known_token(name)
            # Well-known streams are per-role singletons: a new generation's
            # role instance on the same process replaces the old receiver
            # (the reference's equivalent: a rebooted role re-registers its
            # well-known endpoints).
            replace = True
        self._stream = PromiseStream()
        self.endpoint = process.make_endpoint(
            self._deliver, token=token, replace=replace
        )

    def _deliver(self, env: _Envelope):
        reply = Reply(self.process.network, self.process, env.reply_to)
        if getattr(self, "_closed", None) is not None:
            # A retired role's endpoint: refuse instead of queueing into a
            # stream nobody will ever pop (the caller re-resolves topology).
            reply.send_error(self._closed)
            return
        self._stream.send((env.request, reply))

    def close(self, error_name: str = "broken_promise"):
        """Tear down the serving side: every PARKED request's reply breaks
        and every future delivery is refused — the reference's
        NetNotifiedQueue destruction breaking outstanding getReplys when a
        role actor dies (fdbrpc.h:192).  Without this, a request parked on
        a stale generation's role (alive process, role retired) hangs its
        caller forever."""
        self._closed = error_name
        q = self._stream.future_stream._queue
        pending, q[:] = list(q), []
        if pending:
            from ..flow.testprobe import test_probe

            test_probe("request_stream_closed_parked")
        for _req, rep in pending:
            rep.send_error(error_name)
        # The CONSUMER side must break too: a serve actor parked in
        # `await stream.pop()` when its generation retires would otherwise
        # stay parked forever — nothing can ever push (deliveries are
        # refused above), so the task and everything it closes over leak
        # silently until process death (the fdblint PRM001 orphaned-wait
        # class, observed dynamically by sim_validation's
        # expect_no_orphaned_waits).  Erroring the stream wakes it with
        # broken_promise and it exits with its generation.
        self._stream.send_error(FdbError(error_name))

    def pop(self) -> Future:
        """Future of the next (request, Reply)."""
        return self._stream.pop()

    def is_ready(self) -> bool:
        """A request is already queued (pop() would complete immediately) —
        lets servers drain a burst into one batch (ref: the queued-request
        draining in transactionStarter, MasterProxyServer.actor.cpp:948)."""
        return self._stream.is_ready()

    def ref(self) -> "RequestStreamRef":
        return RequestStreamRef(self.endpoint, self.name)


@dataclass(frozen=True)
class RequestStreamRef:
    """Client-side handle; what interface structs carry (ref: the
    RequestStream<T> members of e.g. MasterProxyInterface.h)."""

    endpoint: Endpoint
    name: str = ""

    def get_reply(self, src: SimProcess, request) -> Future:
        """Send and await the reply (ref: getReply fdbrpc.h:235).

        The future errors with broken_promise if the destination process
        dies before answering (detected via the fabric's death notification,
        standing in for a closed connection).
        """
        net = src.network
        out = Promise(priority=TaskPriority.DefaultPromiseEndpoint)
        if net.is_unreachable(self.endpoint.address):
            # Target known-down (the simulator can peek at remote liveness;
            # a real network only learns from a failed connect): fail after
            # a connection-attempt latency (ref: failed connect ->
            # broken_promise on the reply).
            net.loop._schedule(
                TaskPriority.DefaultPromiseEndpoint,
                lambda: out.send_error(BrokenPromise()),
                at=net.loop.now() + net._latency(),
            )
            return out.future
        reply_ep_holder = {}

        def on_reply(wire):
            src.drop_endpoint(reply_ep_holder["ep"])
            pending = src._pending_on.get(self.endpoint.address)
            if pending is not None:
                pending.pop((out, reply_ep_holder["ep"]), None)
            if out.is_set():
                return
            is_err, value = wire
            if is_err:
                if isinstance(value, tuple):  # (name, detail) — ISSUE 17
                    out.send_error(FdbError(value[0], detail=value[1]))
                else:
                    out.send_error(FdbError(value))
            else:
                out.send(value)

        reply_ep = src.make_endpoint(on_reply)
        reply_ep_holder["ep"] = reply_ep
        # Insertion-ordered dict-as-set, NOT a set: on process death these
        # promises are broken by iterating this container, and a set of
        # id-hashed tuples iterates in allocation-dependent order — which
        # made whole-cluster kills nondeterministic across interpreter runs
        # (found by the same-seed byte-identity check).
        src._pending_on.setdefault(self.endpoint.address, {})[
            (out, reply_ep)
        ] = None
        net.send_from(src, self.endpoint, _Envelope(request, reply_ep))
        return out.future

    def send(self, src: SimProcess, request):
        """One-way send, no reply expected (ref: RequestStream::send)."""
        src.network.send_from(src, self.endpoint, _Envelope(request, None))


async def retry_get_reply(
    ref: RequestStreamRef, src: SimProcess, request, *, delay: float = 0.1
):
    """getReply with broken_promise retry after a backoff — the minimal
    stand-in for the reference's loadBalance single-target path
    (fdbrpc/LoadBalance.actor.h:159) until replica sets exist."""
    loop = src.network.loop
    while True:
        try:
            return await ref.get_reply(src, request)
        except FdbError as e:
            if e.name != "broken_promise":
                raise
            await loop.delay(delay)


def spawn_owned(role, coro, name: str):
    """Spawn a per-request handler task OWNED by `role`: recorded in
    role._owned (pruned of finished tasks) so worker._teardown_role can
    cancel it with the role.  Handlers can park indefinitely (prevVersion
    ordering waits, log pushes into a chain hole) and must die with their
    generation, breaking the replies they hold.  Observed (spawn_observed
    semantics): ownership covers cancellation, not error observation — a
    handler dying on an FdbError mid-request must trace, not vanish."""
    t = role.process.spawn_observed(coro, name)
    role._owned = [x for x in getattr(role, "_owned", []) if not x.is_ready()]
    role._owned.append(t)
    return t
