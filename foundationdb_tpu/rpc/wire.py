"""Versioned tagged binary wire serialization for the real transport.

Ref: flow/serialize.h:80-188 (Serializer templates with
`currentProtocolVersion` gating every struct) and the ConnectPacket
versioning in fdbrpc/FlowTransport.actor.cpp:189-210.  The reference never
puts an executable-on-decode format on the wire; the rebuild's early
`pickle((token, payload))` frames were a remote-code-execution primitive
for anyone who could reach the port (mTLS mitigated, not excused).  This
module replaces them.

Properties:
  - Tagged binary values: None/bool/int/float/bytes/str/list/tuple/dict
    plus a STRUCT tag for the registered dataclasses that define the RPC
    protocol (interfaces.py et al) and an ENUM tag for IntEnums.
  - One format-version byte per frame; mismatches error loudly.
  - Struct schema evolution: fields are written positionally in declared
    order with an explicit count.  A decoder seeing FEWER fields than it
    knows fills the rest from dataclass defaults (old peer, new field); a
    decoder seeing MORE fields than it knows rejects the frame (new peer
    talking to old code — reject-unknown, loudly, like the reference's
    protocol-version gate).
  - Decoding constructs data only — no code execution, no attribute
    lookup driven by wire bytes beyond the fixed registry.  Every length
    is bounds-checked against the frame; depth is capped.  Malformed
    input raises WireDecodeError, never anything else.

The struct registry is keyed by crc32(class name) & 0xFFFF, derived — not
assigned — so both peers compute identical ids from identical protocol
definitions; a name collision fails registration loudly at import time.
The single-process simulator keeps its deep-copy pickling (trusted, never
leaves the process); this codec is the boundary format.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from enum import IntEnum
from typing import Any, Callable, Dict, List, Tuple

WIRE_VERSION = 1
MAX_DEPTH = 64
MAX_VARINT_BYTES = 16  # > 2**112: nothing on this wire is that large

T_NONE = 0
T_TRUE = 1
T_FALSE = 2
T_INT = 3
T_FLOAT = 4
T_BYTES = 5
T_STR = 6
T_LIST = 7
T_TUPLE = 8
T_DICT = 9
T_STRUCT = 10
T_ENUM = 11

_F64 = struct.Struct(">d")
_U16 = struct.Struct(">H")


class WireDecodeError(Exception):
    """Malformed or unknown wire bytes.  The ONLY error decode raises."""


class WireEncodeError(Exception):
    """Value outside the protocol vocabulary (e.g. an unregistered class)."""


# --- registry -------------------------------------------------------------

_struct_ids: Dict[type, int] = {}
_structs_by_id: Dict[int, Tuple[type, tuple]] = {}  # id -> (cls, fields)
_enum_ids: Dict[type, int] = {}
_enums_by_id: Dict[int, type] = {}
_built = False


def _class_id(name: str) -> int:
    return zlib.crc32(name.encode()) & 0xFFFF


def register_struct(cls: type) -> type:
    """Admit a dataclass to the wire vocabulary."""
    assert dataclasses.is_dataclass(cls), cls
    cid = _class_id(cls.__name__)
    prev = _structs_by_id.get(cid)
    if prev is not None and prev[0] is not cls:
        raise AssertionError(
            f"wire id collision: {cls.__name__} vs {prev[0].__name__}"
        )
    _struct_ids[cls] = cid
    _structs_by_id[cid] = (cls, tuple(dataclasses.fields(cls)))
    return cls


def register_enum(cls: type) -> type:
    assert issubclass(cls, IntEnum), cls
    cid = _class_id(cls.__name__)
    prev = _enums_by_id.get(cid)
    if prev is not None and prev is not cls:
        raise AssertionError(
            f"wire enum id collision: {cls.__name__} vs {prev.__name__}"
        )
    _enum_ids[cls] = cid
    _enums_by_id[cid] = cls
    return cls


def _build_registry():
    """Collect the protocol vocabulary: every dataclass/IntEnum in the
    modules that define what crosses the real transport.  Lazy (first
    encode/decode) to avoid import cycles with the server modules."""
    global _built
    if _built:
        return
    from ..client import types as client_types
    from ..conflict import types as conflict_types
    from ..server import (
        cluster_controller,
        coordination,
        failure_monitor,
        ratekeeper,
        resolver,
    )
    from ..server import interfaces as server_interfaces
    from ..server import worker as server_worker
    from . import locality as rpc_locality
    from . import network as rpc_network
    from . import stream as rpc_stream

    modules = (
        server_interfaces,
        client_types,
        conflict_types,
        rpc_stream,
        rpc_network,
        server_worker,
        coordination,
        cluster_controller,
        failure_monitor,
        ratekeeper,
        resolver,
        rpc_locality,
    )
    for mod in modules:
        for obj in vars(mod).values():
            if isinstance(obj, type) and obj.__module__ == mod.__name__:
                if dataclasses.is_dataclass(obj):
                    register_struct(obj)
                elif issubclass(obj, IntEnum):
                    register_enum(obj)
    # Marked ONLY after full success: a failed first build (import cycle,
    # broken module) must surface its real error on every call, not decay
    # into "unregistered struct" against a half-empty registry.
    _built = True


# --- encoding -------------------------------------------------------------


def _enc_varint(out: List[bytes], n: int):
    """Unsigned LEB128."""
    if n < 0:
        raise WireEncodeError("negative varint")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(bytes((b | 0x80,)))
        else:
            out.append(bytes((b,)))
            return


def _zigzag(n: int) -> int:
    return (n << 1) if n >= 0 else ((-n) << 1) - 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _encode(out: List[bytes], v: Any, depth: int):
    if depth > MAX_DEPTH:
        raise WireEncodeError("nesting too deep")
    if v is None:
        out.append(bytes((T_NONE,)))
    elif v is True:
        out.append(bytes((T_TRUE,)))
    elif v is False:
        out.append(bytes((T_FALSE,)))
    elif isinstance(v, IntEnum):
        cid = _enum_ids.get(type(v))
        if cid is None:
            raise WireEncodeError(f"unregistered enum {type(v).__name__}")
        out.append(bytes((T_ENUM,)))
        out.append(_U16.pack(cid))
        _enc_varint(out, _zigzag(int(v)))
    elif isinstance(v, int):
        out.append(bytes((T_INT,)))
        _enc_varint(out, _zigzag(v))
    elif isinstance(v, float):
        out.append(bytes((T_FLOAT,)))
        out.append(_F64.pack(v))
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out.append(bytes((T_BYTES,)))
        _enc_varint(out, len(b))
        out.append(b)
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(bytes((T_STR,)))
        _enc_varint(out, len(b))
        out.append(b)
    elif isinstance(v, list):
        out.append(bytes((T_LIST,)))
        _enc_varint(out, len(v))
        for item in v:
            _encode(out, item, depth + 1)
    elif isinstance(v, tuple):
        out.append(bytes((T_TUPLE,)))
        _enc_varint(out, len(v))
        for item in v:
            _encode(out, item, depth + 1)
    elif isinstance(v, dict):
        out.append(bytes((T_DICT,)))
        _enc_varint(out, len(v))
        for k, val in v.items():
            _encode(out, k, depth + 1)
            _encode(out, val, depth + 1)
    elif dataclasses.is_dataclass(v) and not isinstance(v, type):
        cid = _struct_ids.get(type(v))
        if cid is None:
            raise WireEncodeError(f"unregistered struct {type(v).__name__}")
        _cls, flds = _structs_by_id[cid]
        out.append(bytes((T_STRUCT,)))
        out.append(_U16.pack(cid))
        _enc_varint(out, len(flds))
        for f in flds:
            _encode(out, getattr(v, f.name), depth + 1)
    else:
        raise WireEncodeError(
            f"type {type(v).__name__} is not in the wire vocabulary"
        )


def encode_frame_py(value: Any) -> bytes:
    """Pure-Python encode (the reference implementation; also the
    fallback for values outside the C fast path's 64-bit int range)."""
    _build_registry()
    out: List[bytes] = [bytes((WIRE_VERSION,))]
    _encode(out, value, 0)
    return b"".join(out)


# --- optional C accelerator ----------------------------------------------
#
# cpp/wirecodec.c implements the SAME format; differential-fuzzed against
# the Python reference (tests/test_wire.py).  Loaded lazily with the
# registry; registry growth (late register_struct) re-configures it.

from ..flow.knobs import g_env

_c_mod = None
_c_stamp = -1
# Process configuration, read once: set FDB_TPU_WIRE_PY=1 to force the
# pure-Python codec (A/B baselines, debugging).
_C_DISABLED = bool(g_env.get("FDB_TPU_WIRE_PY"))


class _CFallbackSignal(Exception):
    """Raised by the C codec for frames it cannot represent."""


def _c_codec():
    global _c_mod, _c_stamp, _C_DISABLED
    if _C_DISABLED:
        return None
    stamp = len(_structs_by_id) + len(_enums_by_id)
    if _c_mod is not None and stamp == _c_stamp:
        return _c_mod
    if _c_mod is None:
        from .wire_native import load

        _c_mod = load()
        if _c_mod is None:
            _C_DISABLED = True  # build failed; never retry this process
            return None
    import dataclasses as _dc
    from enum import IntEnum as _IE

    struct_by_id = {}
    for cid, (cls, flds) in _structs_by_id.items():
        names = tuple(f.name for f in flds)
        min_req = 0
        for i, f in enumerate(flds):
            if (
                f.default is _dc.MISSING
                and f.default_factory is _dc.MISSING
            ):
                min_req = i + 1
        struct_by_id[cid] = (cls, names, min_req)
    struct_ids = {
        cls: (cid, struct_by_id[cid][1]) for cls, cid in _struct_ids.items()
    }
    _c_mod.configure(
        struct_by_id,
        dict(_enums_by_id),
        struct_ids,
        dict(_enum_ids),
        WireEncodeError,
        WireDecodeError,
        _CFallbackSignal,
        _IE,
        _dc.is_dataclass,
    )
    _c_stamp = stamp
    return _c_mod


def encode_frame(value: Any) -> bytes:
    """value -> one wire frame body (caller adds the length prefix)."""
    _build_registry()
    c = _c_codec()
    if c is not None:
        try:
            return c.encode(value)
        except _CFallbackSignal:
            pass
    return encode_frame_py(value)


# --- decoding -------------------------------------------------------------


class _Reader:
    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0
        self.end = len(buf)

    def take(self, n: int) -> bytes:
        if n < 0 or self.end - self.pos < n:
            raise WireDecodeError("truncated frame")
        b = self.buf[self.pos : self.pos + n]
        self.pos += n
        return b

    def byte(self) -> int:
        if self.pos >= self.end:
            raise WireDecodeError("truncated frame")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        shift = 0
        n = 0
        for i in range(MAX_VARINT_BYTES):
            b = self.byte()
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7
        raise WireDecodeError("varint too long")


def _decode(r: _Reader, depth: int) -> Any:
    if depth > MAX_DEPTH:
        raise WireDecodeError("nesting too deep")
    tag = r.byte()
    if tag == T_NONE:
        return None
    if tag == T_TRUE:
        return True
    if tag == T_FALSE:
        return False
    if tag == T_INT:
        return _unzigzag(r.varint())
    if tag == T_FLOAT:
        return _F64.unpack(r.take(8))[0]
    if tag == T_BYTES:
        return r.take(r.varint())
    if tag == T_STR:
        try:
            return r.take(r.varint()).decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireDecodeError(f"bad utf-8: {e}") from None
    if tag in (T_LIST, T_TUPLE):
        n = r.varint()
        if n > r.end - r.pos:  # each element needs >= 1 byte
            raise WireDecodeError("length exceeds frame")
        items = [_decode(r, depth + 1) for _ in range(n)]
        return items if tag == T_LIST else tuple(items)
    if tag == T_DICT:
        n = r.varint()
        if n * 2 > r.end - r.pos:
            raise WireDecodeError("length exceeds frame")
        out = {}
        for _ in range(n):
            k = _decode(r, depth + 1)
            try:
                out[k] = _decode(r, depth + 1)
            except TypeError as e:  # unhashable key
                raise WireDecodeError(f"bad dict key: {e}") from None
        return out
    if tag == T_ENUM:
        cid = _U16.unpack(r.take(2))[0]
        cls = _enums_by_id.get(cid)
        if cls is None:
            raise WireDecodeError(f"unknown enum id {cid:#06x}")
        try:
            return cls(_unzigzag(r.varint()))
        except ValueError as e:
            raise WireDecodeError(str(e)) from None
    if tag == T_STRUCT:
        cid = _U16.unpack(r.take(2))[0]
        entry = _structs_by_id.get(cid)
        if entry is None:
            raise WireDecodeError(f"unknown struct id {cid:#06x}")
        cls, flds = entry
        n = r.varint()
        if n > len(flds):
            raise WireDecodeError(
                f"{cls.__name__}: peer sent {n} fields, we know {len(flds)}"
            )
        kwargs = {}
        for i in range(n):
            kwargs[flds[i].name] = _decode(r, depth + 1)
        # Old peer, new local field: defaults fill the tail.
        for f in flds[n:]:
            if (
                f.default is dataclasses.MISSING
                and f.default_factory is dataclasses.MISSING
            ):
                raise WireDecodeError(
                    f"{cls.__name__}.{f.name}: missing with no default"
                )
        try:
            return cls(**kwargs)
        except (TypeError, ValueError) as e:
            raise WireDecodeError(f"{cls.__name__}: {e}") from None
    raise WireDecodeError(f"unknown tag {tag}")


def decode_frame_py(frame: bytes) -> Any:
    """Pure-Python decode (reference implementation / C fallback)."""
    _build_registry()
    r = _Reader(frame)
    ver = r.byte()
    if ver != WIRE_VERSION:
        raise WireDecodeError(f"wire version {ver} != {WIRE_VERSION}")
    v = _decode(r, 0)
    if r.pos != r.end:
        raise WireDecodeError(f"{r.end - r.pos} trailing bytes")
    return v


def decode_frame(frame: bytes) -> Any:
    """One frame body -> value.  Raises WireDecodeError and nothing else."""
    _build_registry()
    c = _c_codec()
    if c is not None:
        try:
            return c.decode(frame)
        except _CFallbackSignal:
            pass
    return decode_frame_py(frame)
