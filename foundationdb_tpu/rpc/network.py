"""Deterministic in-process network fabric (the Sim2 rebuild).

Ref: fdbrpc/sim2.actor.cpp — ProcessInfo/MachineInfo (simulator.h:47,112),
kill APIs (:148-153), clogging (:263-264), Sim2Conn latency model (:180).
Everything runs on one flow EventLoop; "processes" are actor groups, a
"send" is a scheduled delivery after a random latency drawn from the loop's
DeterministicRandom, so whole-cluster runs are bit-reproducible per seed.

Design notes vs the reference:
  - No byte serialization in simulation: payloads are deep-copied at send
    time, which provides the same isolation property (no shared mutable
    state across the process boundary) the reference gets from serializing.
    A real DCN transport behind the same send() contract does serialize.
  - Kills are modeled at delivery: messages to a dead process vanish; reply
    promises held against it break (ref: connectionKeeper noticing a closed
    connection -> broken_promise on outstanding NetSAVs,
    FlowTransport.actor.cpp:355).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..flow.asyncvar import AsyncVar
from ..flow.error import ActorCancelled, FdbError
from ..flow.eventloop import EventLoop, Task, TaskPriority
from ..flow.trace import TraceEvent


def _trace_task_death(f):
    """Completion observer attached by spawn_observed: errors other than
    cancellation become a trace event instead of vanishing with the
    dropped Task."""
    err = f.error()
    if err is None or isinstance(err, ActorCancelled):
        return
    TraceEvent("SpawnedTaskDied", severity=20).detail(
        "task", getattr(f, "name", "?")
    ).detail("error", repr(err)).log()


@dataclass(frozen=True)
class Endpoint:
    """Addressable receiver: (process address, token). Ref: fdbrpc endpoint
    tokens — a UID keying the receiver map on the destination."""

    address: str
    token: int


class SimMachine:
    """A machine groups processes and shares a failure domain (ref:
    MachineInfo simulator.h:112; machineId in LocalityData)."""

    def __init__(self, network: "SimNetwork", machine_id: str, dc_id: str = "dc0"):
        self.network = network
        self.machine_id = machine_id
        self.dc_id = dc_id
        self.processes: List[SimProcess] = []

    def kill(self):
        for p in list(self.processes):
            p.kill()


class SimProcess:
    """An actor group with an address; the unit of kill/reboot (ref:
    ProcessInfo simulator.h:47)."""

    def __init__(self, network: "SimNetwork", name: str, machine: SimMachine):
        self.network = network
        self.name = name
        self.machine = machine
        self.address = f"{machine.machine_id}:{len(machine.processes)}"
        machine.processes.append(self)
        self.alive = True
        self.excluded = False
        self._endpoints: Dict[int, Callable] = {}
        self._next_token = 1
        self._tasks: List[Task] = []
        # Futures (reply promises) this process is waiting on, keyed by the
        # remote address expected to answer; broken on that process's death.
        self._pending_on: Dict[str, dict] = {}  # addr -> ordered {(<Promise>,<Endpoint>): None}
        network._register(self)

    # -- actor management --
    def spawn(self, coro, name: str = "") -> Task:
        assert self.alive, f"spawn on dead process {self.name}"
        t = self.network.loop.spawn(coro, name=f"{self.name}/{name}")
        self._tasks.append(t)
        self._tasks = [x for x in self._tasks if not x.is_ready()]
        return t

    def spawn_observed(self, coro, name: str = "") -> Task:
        """spawn + death observation, for fire-and-forget actors whose Task
        nobody holds (serve loops, tickers, per-request handlers): an
        FdbError killing such a task otherwise vanishes — the loop only
        surfaces non-FdbError crashes, so a role quietly stops serving
        (the grey-failure wedge fdblint TSK001 polices).  Only
        CANCELLATION is quiet; every other death — broken_promise from a
        closed generation's stream included — emits SpawnedTaskDied by
        design, because "which generation's actor died when" is exactly
        what a recovery post-mortem needs."""
        t = self.spawn(coro, name)
        t.add_callback(_trace_task_death)
        return t

    # -- endpoints --
    def make_endpoint(
        self,
        receiver: Callable,
        token: Optional[int] = None,
        replace: bool = False,
    ) -> Endpoint:
        if token is None:
            token = self._next_token
            self._next_token += 1
        assert replace or token not in self._endpoints, f"token {token} in use"
        self._endpoints[token] = receiver
        return Endpoint(self.address, token)

    def drop_endpoint(self, ep: Endpoint):
        self._endpoints.pop(ep.token, None)

    # -- lifecycle --
    def kill(self):
        """Kill: cancel actors, drop endpoints, break promises held against
        this process (ref: ISimulator::killProcess simulator.h:148)."""
        if not self.alive:
            return
        self.alive = False
        TraceEvent("ProcessKilled").detail("name", self.name).log()
        self._endpoints.clear()
        tasks, self._tasks = self._tasks, []
        for t in tasks:
            if not t.is_ready():
                t.cancel()
        self.network._on_process_death(self)

    def reboot(self):
        """Return to life with a fresh endpoint table; role actors must be
        respawned by the caller (the worker rebooter's job, ref:
        simulatedFDBDRebooter SimulatedCluster.actor.cpp:197)."""
        assert not self.alive
        self.alive = True
        self._endpoints.clear()
        self._pending_on.clear()
        self.network.mark_up(self.address)


class SimNetwork:
    """The fabric: routing, latency, clogs, partitions, kill notification."""

    def __init__(self, loop: EventLoop, *, deep_copy: bool = True):
        self.loop = loop
        self.deep_copy = deep_copy
        self.machines: Dict[str, SimMachine] = {}
        self._procs: Dict[str, SimProcess] = {}
        # (src_ip, dst_ip) -> virtual time until which sends are held
        self._clogged: Dict[Tuple[str, str], float] = {}
        self.failure: Dict[str, AsyncVar] = {}  # address -> AsyncVar[bool up]
        self.messages_sent = 0

    # -- topology --
    def machine(self, machine_id: str, dc_id: str = "dc0") -> SimMachine:
        m = self.machines.get(machine_id)
        if m is None:
            m = SimMachine(self, machine_id, dc_id)
            self.machines[machine_id] = m
        return m

    def process(self, name: str, machine_id: Optional[str] = None) -> SimProcess:
        m = self.machine(machine_id or name)
        return SimProcess(self, name, m)

    def _register(self, p: SimProcess):
        self._procs[p.address] = p
        self.failure.setdefault(p.address, AsyncVar(True))

    def get_process(self, address: str) -> Optional[SimProcess]:
        return self._procs.get(address)

    def is_unreachable(self, address: str) -> bool:
        """True when a send could never be answered: the process is known
        dead (simulation omniscience; the real fabric returns False and
        relies on connection failure)."""
        p = self._procs.get(address)
        return p is None or not p.alive

    # -- latency / fault models --
    def _latency(self) -> float:
        # ref Sim2Conn: a fraction of a millisecond, randomized per packet
        return 0.0001 + 0.0004 * self.loop.rng.random01()

    def clog_pair(self, ip_a: str, ip_b: str, seconds: float):
        """Hold traffic ONE way, ip_a -> ip_b (ref: ISimulator::clogPair
        simulator.h:264 clogs a single direction — asymmetric grey
        failures, where requests arrive but replies stall, are exactly
        the cases symmetric partitions can't reproduce).  Use
        partition_pair for a full bidirectional cut."""
        until = self.loop.now() + seconds
        pair = (ip_a, ip_b)
        self._clogged[pair] = max(self._clogged.get(pair, 0.0), until)

    def partition_pair(self, ip_a: str, ip_b: str, seconds: float):
        """Hold traffic BOTH ways between two machines (two directional
        clogs; the reference composes clogPair both ways for the same
        effect)."""
        self.clog_pair(ip_a, ip_b, seconds)
        self.clog_pair(ip_b, ip_a, seconds)

    def unclog_pair(self, ip_a: str, ip_b: str):
        """Release one pair early, both directions (ref:
        ISimulator::unclogPair)."""
        self._clogged.pop((ip_a, ip_b), None)
        self._clogged.pop((ip_b, ip_a), None)

    def unclog_all(self):
        self._clogged.clear()

    def _clog_release(self, src_ip: str, dst_ip: str) -> float:
        return self._clogged.get((src_ip, dst_ip), 0.0)

    # -- sending --
    def send(self, dst: Endpoint, payload, priority: int = TaskPriority.DefaultEndpoint):
        """Fire-and-forget message to an endpoint; vanishes if the target is
        dead or the endpoint is gone at delivery time (like an unreliable
        packet; reliability is built above via reply promises + retries)."""
        self.messages_sent += 1
        msg = copy.deepcopy(payload) if self.deep_copy else payload
        deliver_at = self.loop.now() + self._latency()
        self._schedule_delivery(dst, msg, deliver_at, priority)

    def send_from(
        self,
        src: SimProcess,
        dst: Endpoint,
        payload,
        priority: int = TaskPriority.DefaultEndpoint,
    ):
        if not src.alive:
            return
        self.messages_sent += 1
        msg = copy.deepcopy(payload) if self.deep_copy else payload
        src_ip = src.machine.machine_id
        dst_ip = dst.address.split(":")[0]
        release = self._clog_release(src_ip, dst_ip)
        deliver_at = max(self.loop.now(), release) + self._latency()
        self._schedule_delivery(dst, msg, deliver_at, priority)

    def _schedule_delivery(self, dst: Endpoint, msg, at: float, priority: int):
        def deliver():
            p = self._procs.get(dst.address)
            if p is None or not p.alive:
                return
            receiver = p._endpoints.get(dst.token)
            if receiver is None:
                # Live process, no such endpoint (e.g. the role died with a
                # reboot in between): answer a request's reply promise with
                # broken_promise, as the reference does for a request to an
                # unknown endpoint token (FlowTransport deliver :430).
                reply_to = getattr(msg, "reply_to", None)
                if reply_to is not None and hasattr(msg, "request"):
                    self._schedule_delivery(
                        reply_to,
                        (True, "broken_promise"),
                        self.loop.now() + self._latency(),
                        priority,
                    )
                return
            receiver(msg)

        self.loop._schedule(priority, deliver, at=at)

    # -- death notification --
    def _on_process_death(self, dead: SimProcess):
        self.failure[dead.address].set(False)
        for p in self._procs.values():
            pending = p._pending_on.pop(dead.address, None)
            if not pending:
                continue
            for promise, reply_ep in pending:
                p.drop_endpoint(reply_ep)  # one-shot endpoint, never answered
                if not promise.is_set():
                    # Deliver after a latency, as a closing connection would.
                    self.loop._schedule(
                        TaskPriority.DefaultEndpoint,
                        lambda pr=promise: (
                            None
                            if pr.is_set()
                            else pr.send_error(FdbError("broken_promise"))
                        ),
                        at=self.loop.now() + self._latency(),
                    )

    def mark_up(self, address: str):
        self.failure[address].set(True)
