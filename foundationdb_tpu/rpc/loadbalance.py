"""Model-based request routing with hedged second requests.

Ref: fdbrpc/LoadBalance.actor.h:159 `loadBalance` — order an interface's
replicas by the per-endpoint latency model (QueueModel,
fdbrpc/QueueModel.h), send to the best, and if the reply is slow issue a
backup request to the second-best (`secondRequest` :168); first reply
wins.  Failed endpoints accrue a penalty so traffic shifts away from
them.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..flow.error import FdbError
from ..flow.eventloop import first_of


class QueueModel:
    """Per-endpoint smoothed latency + failure penalty (ref: QueueModel /
    the smoothed outstanding/latency bookkeeping in LoadBalance)."""

    ALPHA = 0.2
    # Strong enough that a few failures outweigh any latency advantage
    # (the reference gets this from the failure monitor marking the
    # endpoint down); successes decay it back quickly.
    FAIL_PENALTY = 8.0

    def __init__(self):
        self._latency: dict = {}
        self._penalty: dict = {}

    def expected(self, key) -> float:
        return self._latency.get(key, 0.001) * self._penalty.get(key, 1.0)

    def update(self, key, latency: float, failed: bool):
        if failed:
            self._penalty[key] = min(
                float(1 << 20), self._penalty.get(key, 1.0) * self.FAIL_PENALTY
            )
            return
        self._penalty[key] = max(1.0, self._penalty.get(key, 1.0) * 0.25)
        prev = self._latency.get(key, latency)
        self._latency[key] = prev + self.ALPHA * (latency - prev)

    def order(self, keys: List) -> List:
        """Replicas by expected latency, stable on ties (deterministic)."""
        return sorted(keys, key=lambda k: (self.expected(k), str(k)))


async def load_balance(
    process,
    model: Optional[QueueModel],
    alternatives: List,
    send: Callable,
    *,
    key_of: Callable = None,
    hedge_after: float = 0.01,
    reroute_errors=("broken_promise", "future_version"),
    failed: Callable = None,
):
    """Send via the model's best replica; hedge to the runner-up if the
    first reply is slower than `hedge_after` (ref: loadBalance's
    secondRequest path).  `send(alt)` returns the reply future;
    `reroute_errors` advance to the next alternative, anything else
    re-raises to the caller (e.g. wrong_shard_server -> cache invalidation
    upstream).  Raises the last error when every alternative failed."""
    loop = process.network.loop
    key_of = key_of or (lambda a: id(a))
    # Known-failed replicas sort LAST, not out: stale failure info must
    # never make data unreachable (ref: loadBalance consulting
    # IFailureMonitor before picking alternatives).
    dead = failed or (lambda a: False)
    order = (
        sorted(
            alternatives,
            key=lambda a: (
                bool(dead(a)),
                model.expected(key_of(a)),
                str(key_of(a)),
            ),
        )
        if model
        else sorted(alternatives, key=lambda a: bool(dead(a)))
    )
    last_err = FdbError("all_alternatives_failed")
    i = 0
    while i < len(order):
        alt = order[i]
        t0 = loop.now()
        fut = process.spawn(_guarded(send, alt), "lb_req")
        use_hedge = i + 1 < len(order)
        if use_hedge:
            timer = loop.delay(hedge_after)
            idx, _ = await first_of(fut, timer)
            if idx == 0:
                loop.cancel_timer(timer)
                ok, val = fut.get()
                if ok:
                    if model:
                        model.update(key_of(alt), loop.now() - t0, False)
                    return val
                if model:
                    model.update(key_of(alt), loop.now() - t0, True)
                if val.name not in reroute_errors:
                    raise val
                last_err = val
                i += 1
                continue
            # Slow: hedge to the runner-up; first reply wins (duplicate
            # delivery is safe — reads are idempotent).
            alt2 = order[i + 1]
            t1 = loop.now()
            fut2 = process.spawn(_guarded(send, alt2), "lb_hedge")
            idx2, _ = await first_of(fut, fut2)
            win, lose = (fut, fut2) if idx2 == 0 else (fut2, fut)
            wkey, lkey = (
                (key_of(alt), key_of(alt2))
                if idx2 == 0
                else (key_of(alt2), key_of(alt))
            )
            wt = t0 if idx2 == 0 else t1
            ok, val = win.get()
            if model:
                model.update(wkey, loop.now() - wt, not ok)
            if ok:
                return val
            if val.name not in reroute_errors:
                raise val
            # Winner failed; fall back to the loser's eventual answer.
            lt = t1 if idx2 == 0 else t0  # the loser's own start time
            ok2, val2 = await lose
            if model:
                model.update(lkey, loop.now() - lt, not ok2)
            if ok2:
                return val2
            if val2.name not in reroute_errors:
                raise val2
            last_err = val2
            i += 2
        else:
            ok, val = await fut
            if model:
                model.update(key_of(alt), loop.now() - t0, not ok)
            if ok:
                return val
            if val.name not in reroute_errors:
                raise val
            last_err = val
            i += 1
    raise last_err


async def _guarded(send, alt):
    try:
        return True, await send(alt)
    except FdbError as e:
        return False, e
