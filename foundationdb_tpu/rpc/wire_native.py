"""Build + load the C wire-codec accelerator (cpp/wirecodec.c).

Ref: the format itself is rpc/wire.py's (the flow/serialize.h analog);
this module only builds/loads the byte-identical C implementation —
same on-demand compile pattern as the native kv engine
(fileio/kvstore_native.py).  The extension is
OPTIONAL: any build or import failure leaves the pure-Python codec in
charge (correctness never depends on the accelerator).  For values the
C fast path cannot represent (ints beyond 64 bits), the extension
raises the fallback signal wire.py hands it at configure()
(wire._CFallbackSignal), and the frame is retried in pure Python.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO, "cpp", "wirecodec.c")
_LIB = os.path.join(_REPO, "cpp", "_fdb_wirecodec.so")


def load():
    """The configured-but-unregistered extension module, or None."""
    try:
        if (
            not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        ):
            inc = sysconfig.get_paths()["include"]
            # Build to a private temp path and rename into place:
            # concurrent processes racing an in-place gcc write could
            # dlopen a half-written .so (and cache the corruption via its
            # fresh mtime).  rename() is atomic on the same filesystem.
            tmp = f"{_LIB}.tmp.{os.getpid()}"
            subprocess.run(
                [
                    "gcc", "-O2", "-shared", "-fPIC",
                    f"-I{inc}", "-o", tmp, _SRC,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, _LIB)
        spec = importlib.util.spec_from_file_location(
            "_fdb_wirecodec", _LIB
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:  # fdblint: ignore[ERR001]: optional native codec — None selects the pure-python wire format, the handled path
        return None
