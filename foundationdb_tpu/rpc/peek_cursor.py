"""Merge peek cursors over the tag-partitioned log set.

Ref: fdbserver/LogSystemPeekCursor.actor.cpp — ServerPeekCursor reads one
tag from one log with failover; MergedPeekCursor combines the cursors of
every log holding the tag set, emitting versions in order only once every
contributing log has reported past them (the known-complete horizon).
Consumers: log routers pulling a full stream, DR agents tailing
multi-log sources, and any reader whose tags span several logs.

The rebuild merges RAW TAGGED bundles (version -> {tag: [(seq, m)]}),
deduping replicated bundles by tag, so the output can be re-served
per-tag (a router) or flattened to commit order (DR).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..flow.error import FdbError
from ..server.interfaces import TLogPeekRequest


class MergePeekCursor:
    """Pull-merge over `logs` for `tags` (None = every tag).

    next_batch() returns (entries, end_version):
      entries: [(version, {tag: [(seq, mutation)]})] ascending, complete
               through end_version;
      end_version: the merged known-complete horizon (min over logs) —
               versions <= it carrying none of the tags simply don't
               appear.  A member whose floor is above the merge begin (a
               FRESH replacement log) serves from its floor
               (allow_below_begin) — the range below it comes from the
               replicas that still hold it, instead of the whole merge
               wedging on peek_below_begin forever.  A log that DIES makes
               the cursor raise; the caller re-resolves topology (ref:
               the cursor invalidation on epoch end)."""

    def __init__(
        self,
        process,
        logs: List,
        tags: Optional[List[str]] = None,
        begin: int = 0,
        limit_versions: int = 256,
    ):
        self.process = process
        self.logs = list(logs)
        self.tags = None if tags is None else list(tags)
        self.begin = begin  # all versions <= begin already consumed
        self.limit = limit_versions
        # Per-log buffered entries + per-log scanned horizon.
        self._buf: List[Dict[int, dict]] = [{} for _ in self.logs]
        self._horizon: List[int] = [begin for _ in self.logs]
        # Start of each log's CURRENT contiguous coverage segment (the
        # segment ends at _horizon[i]).  Each pull resumes from
        # _horizon[i], so segments normally chain; a pull whose
        # served_from jumps ABOVE the prior horizon (the log's floor
        # popped past what it had scanned) leaves a hole, and the segment
        # start resets to that served_from.  None until the first answer.
        self._covered_from: List[Optional[int]] = [None for _ in self.logs]
        self.known_committed = 0

    async def next_batch(self) -> Tuple[list, int]:
        from ..flow.eventloop import wait_for_all

        async def pull(i: int):
            log = self.logs[i]
            rep = await log.peek.get_reply(
                self.process,
                TLogPeekRequest(
                    # Each log resumes from ITS OWN scanned horizon — a fast
                    # log's buffered entries above the merge horizon are not
                    # re-transferred while a slow log catches up.
                    begin_version=max(self.begin, self._horizon[i]),
                    tags=self.tags,
                    limit_versions=self.limit,
                    raw_tagged=True,
                    allow_below_begin=True,
                ),
            )
            for version, bundle in rep.entries:
                if version > self.begin:
                    self._buf[i][version] = bundle
            if (
                self._covered_from[i] is None
                or rep.served_from > self._horizon[i]
            ):
                self._covered_from[i] = rep.served_from
            self._horizon[i] = max(self._horizon[i], rep.end_version)
            self.known_committed = max(
                self.known_committed, rep.known_committed
            )

        await wait_for_all(
            [self.process.spawn(pull(i), f"merge_pull{i}") for i in range(len(self.logs))]
        )
        if self.logs and not self._coverage_ok():
            from ..flow.testprobe import test_probe

            test_probe("merge_cursor_uncovered")
            # Some tag's ENTIRE replica slot has coverage starting above
            # the merge begin: a range at/above begin is held by nobody
            # who could have that tag's data — advancing would silently
            # skip mutations.  Raise like the single-log peek_below_begin
            # so the caller re-resolves topology (a replica elsewhere, or
            # a restore point) instead of emitting a gapped stream.
            # Long-lived consumers (backup/DR) prevent this case outright
            # by registering pop floors on every log; it remains reachable
            # when a recovery replaces logs (fresh begin_version) while a
            # cursor still needs the older range.
            raise FdbError("peek_below_begin")
        horizon = min(self._horizon)
        merged: Dict[int, Dict[str, list]] = {}
        for buf in self._buf:
            for version in [v for v in buf if v <= horizon]:
                bundle = buf.pop(version)
                out = merged.setdefault(version, {})
                for tag, items in bundle.items():
                    out.setdefault(tag, items)  # replica bundles identical
        entries = [(v, merged[v]) for v in sorted(merged)]
        if horizon > self.begin:
            self.begin = horizon
        return entries, self.begin

    def _coverage_ok(self) -> bool:
        """Is every tag's range from self.begin held by at least one
        member that could hold that tag?

        Coverage is TAG-AWARE: non-broadcast tags live on only `rf`
        consecutive ring members (log_system.tlogs_for_tag), so one log
        covering begin for unrelated tags must not mask a hole in another
        tag's whole replica slot.  With explicit tags the slots are
        computed exactly; with tags=None (full stream) the tag universe
        is unknown, so EVERY rf-window of the ring must contain a
        covering member (any tag lives in some window).  Conservative
        where the member list's ring order or satellite count is unknown
        — a spurious raise is loud, a missed gap is silent loss."""
        from ..flow.knobs import g_knobs
        from ..server.log_system import tlogs_for_tag

        covers = [
            c is not None and c <= self.begin for c in self._covered_from
        ]
        if all(covers):
            return True
        n = len(self.logs)
        if self.tags is None:
            rf = min(g_knobs.server.log_replication_factor, n)
            windows = [
                [(s + r) % n for r in range(rf)] for s in range(n)
            ]
        else:
            windows = [tlogs_for_tag(t, n) for t in self.tags]
        return all(any(covers[i] for i in w) for w in windows)

    @staticmethod
    def flatten(bundle: Dict[str, list]) -> list:
        """One version's {tag: [(seq, m)]} -> commit-ordered [mutations]
        (dedupe across tags by seq, like a single log's merged peek)."""
        by_seq: Dict[int, object] = {}
        for items in bundle.values():
            for seq, m in items:
                by_seq[seq] = m
        return [m for _s, m in sorted(by_seq.items())]
