"""Merge peek cursors over the tag-partitioned log set.

Ref: fdbserver/LogSystemPeekCursor.actor.cpp — ServerPeekCursor reads one
tag from one log with failover; MergedPeekCursor combines the cursors of
every log holding the tag set, emitting versions in order only once every
contributing log has reported past them (the known-complete horizon).
Consumers: log routers pulling a full stream, DR agents tailing
multi-log sources, and any reader whose tags span several logs.

The rebuild merges RAW TAGGED bundles (version -> {tag: [(seq, m)]}),
deduping replicated bundles by tag, so the output can be re-served
per-tag (a router) or flattened to commit order (DR).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..flow.error import FdbError
from ..server.interfaces import TLogPeekRequest


class MergePeekCursor:
    """Pull-merge over `logs` for `tags` (None = every tag).

    next_batch() returns (entries, end_version):
      entries: [(version, {tag: [(seq, mutation)]})] ascending, complete
               through end_version;
      end_version: the merged known-complete horizon (min over logs) —
               versions <= it carrying none of the tags simply don't
               appear.  A log that answers peek_below_begin or dies makes
               the cursor raise; the caller re-resolves topology (ref:
               the cursor invalidation on epoch end)."""

    def __init__(
        self,
        process,
        logs: List,
        tags: Optional[List[str]] = None,
        begin: int = 0,
        limit_versions: int = 256,
    ):
        self.process = process
        self.logs = list(logs)
        self.tags = None if tags is None else list(tags)
        self.begin = begin  # all versions <= begin already consumed
        self.limit = limit_versions
        # Per-log buffered entries + per-log scanned horizon.
        self._buf: List[Dict[int, dict]] = [{} for _ in self.logs]
        self._horizon: List[int] = [begin for _ in self.logs]
        self.known_committed = 0

    async def next_batch(self) -> Tuple[list, int]:
        from ..flow.eventloop import wait_for_all

        async def pull(i: int):
            log = self.logs[i]
            rep = await log.peek.get_reply(
                self.process,
                TLogPeekRequest(
                    # Each log resumes from ITS OWN scanned horizon — a fast
                    # log's buffered entries above the merge horizon are not
                    # re-transferred while a slow log catches up.
                    begin_version=max(self.begin, self._horizon[i]),
                    tags=self.tags,
                    limit_versions=self.limit,
                    raw_tagged=True,
                ),
            )
            for version, bundle in rep.entries:
                if version > self.begin:
                    self._buf[i][version] = bundle
            self._horizon[i] = max(self._horizon[i], rep.end_version)
            self.known_committed = max(
                self.known_committed, rep.known_committed
            )

        await wait_for_all(
            [self.process.spawn(pull(i), f"merge_pull{i}") for i in range(len(self.logs))]
        )
        horizon = min(self._horizon)
        merged: Dict[int, Dict[str, list]] = {}
        for buf in self._buf:
            for version in [v for v in buf if v <= horizon]:
                bundle = buf.pop(version)
                out = merged.setdefault(version, {})
                for tag, items in bundle.items():
                    out.setdefault(tag, items)  # replica bundles identical
        entries = [(v, merged[v]) for v in sorted(merged)]
        if horizon > self.begin:
            self.begin = horizon
        return entries, self.begin

    @staticmethod
    def flatten(bundle: Dict[str, list]) -> list:
        """One version's {tag: [(seq, m)]} -> commit-ordered [mutations]
        (dedupe across tags by seq, like a single log's merged peek)."""
        by_seq: Dict[int, object] = {}
        for items in bundle.values():
            for seq, m in items:
                by_seq[seq] = m
        return [m for _s, m in sorted(by_seq.items())]
