"""RPC + virtualized network: the rebuild of the reference's fdbrpc/ layer.

The reference runs identical role code over two interchangeable networks —
real TCP (FlowTransport over Net2) and the deterministic simulator (Sim2) —
selected at startup (fdbserver.actor.cpp:1468-1473).  This package keeps
that architecture: `SimNetwork` is the deterministic in-process fabric with
latency, clogging, partitions and kills (ref: fdbrpc/sim2.actor.cpp,
ISimulator fdbrpc/simulator.h:35); typed request/reply endpoints
(`RequestStream`, ref: fdbrpc/fdbrpc.h:212) ride on top and never know which
fabric they are on.  A DCN/TCP transport for real deployment plugs in behind
the same Endpoint/send contract.
"""

from .network import SimNetwork, SimProcess, SimMachine, Endpoint
from .stream import RequestStream, RequestStreamRef, BrokenPromise

__all__ = [
    "SimNetwork",
    "SimProcess",
    "SimMachine",
    "Endpoint",
    "RequestStream",
    "RequestStreamRef",
    "BrokenPromise",
]
