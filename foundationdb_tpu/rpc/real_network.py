"""Real TCP network fabric behind the same send/endpoint contract as the
simulator — the Net2/FlowTransport rebuild.

Ref: flow/Net2.actor.cpp:117 (the real INetwork: reactor + timers + task
priorities) and fdbrpc/FlowTransport.actor.cpp:160 (TransportData: peer
connection map, connectionKeeper :355 reconnect/backoff, connectionReader
:213 framing, deliver :430 token dispatch).  The single most load-bearing
property of the reference — the SAME role actors run on either fabric,
selected at startup (fdbserver.actor.cpp:1468-1473) — is preserved: roles
receive a `RealProcess` instead of a `SimProcess` and never know the
difference.

Design:
  - One flow EventLoop per OS process, driven by `run_realtime`: due timers
    run as virtual-time events anchored to time.monotonic(); when idle, the
    loop blocks in selectors.select() until the next timer or socket IO.
    This is Net2's reactor loop (boost.asio there, selectors here).
  - Wire format: 4-byte big-endian length + the versioned tagged binary
    codec in rpc/wire.py encoding (token, payload).  Requests are
    `_Envelope(request, reply_to)` like the simulator; replies are
    (is_err, value) tuples to the one-shot reply endpoint.  Decoding
    constructs data only (registered protocol structs) — a malformed or
    unknown frame closes the connection loudly, never executes (ref: the
    versioned struct serialization in flow/serialize.h:80).
  - Connection lifecycle: lazy connect on first send, write-queue until
    established, reconnect-on-next-send after failure.  A closed/failed
    connection breaks every reply promise pending on that peer
    (ref: connectionKeeper noticing a closed connection -> broken_promise
    on outstanding NetSAVs, FlowTransport.actor.cpp:355).
"""

from __future__ import annotations

import selectors
import socket
import ssl
import struct
import time
from typing import Callable, Dict, List, Optional

from ..flow.error import FdbError
from ..flow.eventloop import EventLoop, Task, TaskPriority
from ..flow.trace import TraceEvent
from .wire import WireDecodeError, decode_frame, encode_frame

_LEN = struct.Struct(">I")
MAX_FRAME = 64 << 20
# Wire protocol version, exchanged in the hello frame (ref: the
# ProtocolVersion constant in ConnectPacket — bump on incompatible wire
# changes; mismatched peers are rejected at connect, loudly).  B072 is the
# tagged-binary codec (rpc/wire.py) replacing pickle frames.
PROTOCOL_VERSION = b"FDBTPU-0x0FDB00B072000001"


class RealMachine:
    """Failure-domain stand-in so role code touching process.machine works."""

    def __init__(self, machine_id: str):
        self.machine_id = machine_id
        self.dc_id = "dc0"
        self.processes: List["RealProcess"] = []


class RealProcess:
    """The local OS process as an actor group; mirrors SimProcess's surface
    (spawn / make_endpoint / drop_endpoint / address / alive)."""

    def __init__(self, network: "RealNetwork", name: str):
        self.network = network
        self.name = name
        self.machine = RealMachine(network.host)
        self.address = network.address  # host:port of our listener
        self.machine.processes.append(self)
        self.alive = True
        self.excluded = False
        self._endpoints: Dict[int, Callable] = {}
        self._tasks: List[Task] = []
        self._pending_on: Dict[str, dict] = {}  # addr -> ordered {(<Promise>,<Endpoint>): None}
        network._register(self)

    def spawn(self, coro, name: str = "") -> Task:
        assert self.alive, f"spawn on dead process {self.name}"
        t = self.network.loop.spawn(coro, name=f"{self.name}/{name}")
        self._tasks.append(t)
        self._tasks = [x for x in self._tasks if not x.is_ready()]
        return t

    def spawn_observed(self, coro, name: str = "") -> Task:
        """SimProcess.spawn_observed's surface on the real transport: the
        role code is identical on either network (the load-bearing Sim2/
        Net2 design), so fire-and-forget actor deaths trace here too."""
        from .network import _trace_task_death

        t = self.spawn(coro, name)
        t.add_callback(_trace_task_death)
        return t

    def make_endpoint(
        self,
        receiver: Callable,
        token: Optional[int] = None,
        replace: bool = False,
    ):
        from .network import Endpoint

        if token is None:
            # Network-global counter: remote frames carry only the token,
            # so dynamic tokens must be unique across every co-located
            # process sharing this listener.
            token = self.network._token_counter
            self.network._token_counter += 1
        assert replace or token not in self._endpoints, f"token {token} in use"
        self._endpoints[token] = receiver
        return Endpoint(self.address, token)

    def drop_endpoint(self, ep):
        self._endpoints.pop(ep.token, None)


class TLSConfig:
    """Mutual-TLS material (ref: FDBLibTLS — both sides present a cert
    signed by the shared CA; identity is the chain, not the hostname,
    matching the plugin's verify-peers model)."""

    def __init__(self, cert_file: str, key_file: str, ca_file: str):
        self.cert_file = cert_file
        self.key_file = key_file
        self.ca_file = ca_file


class _Conn:
    """One TCP connection with framing and a write queue.  With TLS
    configured, the connection speaks ciphertext on the socket and
    plaintext frames internally via an SSLObject over memory BIOs (the
    non-blocking form that composes with the selector reactor)."""

    def __init__(self, net: "RealNetwork", sock: socket.socket, peer: Optional[str]):
        self.net = net
        self.sock = sock
        self.peer = peer  # host:port listener address of the remote, if known
        self.inbuf = b""
        self.outbuf = b""  # raw bytes for the socket (ciphertext under TLS)
        self.connected = peer is None  # accepted conns are connected already
        self.closed = False
        # Superseded by a simultaneous-connect replacement: closing it must
        # NOT break the peer's pending replies (they ride the replacement).
        self.superseded = False
        self.created = time.monotonic()
        self.last_activity = time.monotonic()
        # -- TLS state (None when the network runs plaintext) --
        self.ssl = None
        self._in_bio = None
        self._out_bio = None
        self._hs_done = False
        self._plain_out = b""  # frames queued before the handshake finished

    def start_tls(self, server_side: bool):
        self._in_bio = ssl.MemoryBIO()
        self._out_bio = ssl.MemoryBIO()
        ctx = (
            self.net._tls_server_ctx if server_side else self.net._tls_client_ctx
        )
        self.ssl = ctx.wrap_bio(
            self._in_bio, self._out_bio, server_side=server_side
        )
        self._pump_handshake()

    def _pump_handshake(self):
        try:
            self.ssl.do_handshake()
            self._hs_done = True
        except (ssl.SSLWantReadError, ssl.SSLWantWriteError):
            pass
        except ssl.SSLError as e:
            TraceEvent("TLSHandshakeFailed", severity=30).detail(
                "peer", self.peer or "<accepting>"
            ).detail("error", str(e)[:200]).log()
            # Flush the TLS alert OpenSSL produced and push it out before
            # closing, so the rejected peer sees WHY (a handshake_failure
            # alert) instead of a bare EOF it would retry forever.  Loop on
            # partial sends (non-blocking socket); best-effort — a full
            # send buffer drops the remainder rather than blocking.
            self._flush_bio()
            while self.outbuf:
                try:
                    n = self.sock.send(self.outbuf)
                except (BlockingIOError, OSError):
                    break
                if n <= 0:
                    break
                self.outbuf = self.outbuf[n:]
            self.close()
            return
        self._flush_bio()
        if self._hs_done and self._plain_out:
            plain, self._plain_out = self._plain_out, b""
            self._ssl_send(plain)

    def _flush_bio(self):
        raw = self._out_bio.read()
        if raw:
            self.outbuf += raw
            self.net._want_write(self)

    def _ssl_send(self, plain: bytes):
        self.ssl.write(plain)
        self._flush_bio()

    def feed_raw(self, data: bytes):
        """Socket bytes in -> plaintext appended to inbuf."""
        if self.ssl is None:
            self.inbuf += data
            return
        self._in_bio.write(data)
        if not self._hs_done:
            self._pump_handshake()
            if self.closed or not self._hs_done:
                return
        while True:
            try:
                chunk = self.ssl.read(1 << 16)
            except (ssl.SSLWantReadError, ssl.SSLWantWriteError):
                break
            except ssl.SSLError:
                self.close()
                return
            if not chunk:
                break
            self.inbuf += chunk
        self._flush_bio()

    def enqueue(self, frame: bytes):
        wire = _LEN.pack(len(frame)) + frame
        if self.ssl is None:
            self.outbuf += wire
        elif self._hs_done:
            self._ssl_send(wire)
        else:
            self._plain_out += wire  # released when the handshake completes
        self.net._want_write(self)

    def close(self):
        if self.closed:
            return
        self.closed = True
        try:
            self.net.selector.unregister(self.sock)
        except Exception:  # noqa: BLE001
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self.peer is not None:
            self.net._on_conn_closed(self)


class RealNetwork:
    """The real fabric: listener + peer connections + local delivery."""

    def __init__(
        self,
        loop: EventLoop,
        host: str = "127.0.0.1",
        port: int = 0,
        tls: Optional[TLSConfig] = None,
        protocol_version: Optional[bytes] = None,
    ):
        self.loop = loop
        # Overridable per network: the MultiVersion client probes a
        # cluster with several codec generations (client/multi_version.py);
        # everything else speaks the current one.
        self.protocol_version = protocol_version or PROTOCOL_VERSION
        self.selector = selectors.DefaultSelector()
        self.host = host
        self.tls = tls
        if tls is not None:
            self._tls_server_ctx = self._make_tls_ctx(tls, server_side=True)
            self._tls_client_ctx = self._make_tls_ctx(tls, server_side=False)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        self.address = f"{host}:{self.port}"
        self.selector.register(
            self._listener, selectors.EVENT_READ, self._on_accept
        )
        self._proc_list: List[RealProcess] = []
        self._conns: Dict[str, _Conn] = {}  # peer address -> conn
        self._last_close_established: Dict[str, bool] = {}
        self.messages_sent = 0
        self._token_counter = 1
        self._stopped = False
        self.connect_timeout = 5.0
        # A peer with traffic owed to us (unsent frames or replies we are
        # waiting on) that stays silent this long is declared failed (ref:
        # the ping keepalive + failure detection on connectionKeeper).
        self.idle_timeout = 15.0
        self._arm_watchdog()

    @staticmethod
    def _make_tls_ctx(tls: TLSConfig, server_side: bool):
        """Mutual TLS both directions (ref: FDBLibTLS verify-peers): each
        side must present a cert chained to the shared CA; hostname checks
        are off — the CA, not DNS, is the trust root inside a cluster."""
        ctx = ssl.SSLContext(
            ssl.PROTOCOL_TLS_SERVER if server_side else ssl.PROTOCOL_TLS_CLIENT
        )
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_cert_chain(tls.cert_file, tls.key_file)
        ctx.load_verify_locations(tls.ca_file)
        return ctx

    def _arm_watchdog(self):
        if self._stopped:
            return
        self._watchdog()
        self.loop._schedule(
            TaskPriority.DefaultDelay,
            self._arm_watchdog,
            at=self.loop.now() + 1.0,
        )

    def _watchdog(self):
        """Bound every hang: close connections that never finished
        connecting, and connections owing us traffic that went silent —
        closing breaks the pending reply promises so callers retry instead
        of hanging forever (ref: connection monitoring/ping,
        FlowTransport.actor.cpp connectionMonitor)."""
        now = time.monotonic()
        for conn in list(self._conns.values()):
            if conn.closed:
                continue
            if not conn.connected and now - conn.created > self.connect_timeout:
                conn.close()
                continue
            # _plain_out counts as owed traffic: frames parked behind a TLS
            # handshake that never completes must trigger the idle close
            # (and thus reconnect), exactly like unsent plaintext would.
            owed = (
                bool(conn.outbuf)
                or bool(conn._plain_out)
                or any(
                    conn.peer in p._pending_on and p._pending_on[conn.peer]
                    for p in self._proc_list
                )
            )
            if owed and now - conn.last_activity > self.idle_timeout:
                conn.close()

    # -- topology (compat surface) --
    # NOTE: every co-located RealProcess shares this network's listener
    # address (they are role groups inside one OS process, like roles in
    # one fdbserver); _procs is a list, and token dispatch is global.
    def _register(self, p: RealProcess):
        self._proc_list.append(p)

    def process(self, name: str, machine_id: Optional[str] = None) -> RealProcess:
        return RealProcess(self, name)

    def get_process(self, address: str) -> Optional[RealProcess]:
        if address == self.address and self._proc_list:
            return self._proc_list[0]
        return None

    def is_unreachable(self, address: str) -> bool:
        """Unknown until a connection attempt fails (the simulator can peek
        at the remote process's liveness; the real network cannot)."""
        return False

    def _latency(self) -> float:
        return 0.0001

    # -- sending --
    def send_from(
        self,
        src: RealProcess,
        dst,
        payload,
        priority: int = TaskPriority.DefaultEndpoint,
    ):
        if not src.alive:
            return
        self.messages_sent += 1
        if dst.address == self.address:
            # Local delivery: scheduled (never inline) so ordering matches
            # the simulator's send-then-return semantics.
            def deliver():
                self._deliver_local(dst.token, payload)

            self.loop._schedule(priority, deliver)
            return
        frame = encode_frame((dst.token, payload))
        if len(frame) > MAX_FRAME:
            raise ValueError("frame too large")
        self._get_conn(dst.address).enqueue(frame)

    def send(self, dst, payload, priority: int = TaskPriority.DefaultEndpoint):
        """Fire-and-forget, SimNetwork.send-compatible signature (no src)."""
        src = self._proc_list[0] if self._proc_list else None
        if src is not None:
            self.send_from(src, dst, payload, priority)

    def _reply_broken(self, msg):
        """Unknown endpoint token on a live process: break the request's
        reply promise (ref: FlowTransport deliver :430)."""
        reply_to = getattr(msg, "reply_to", None)
        if reply_to is not None and hasattr(msg, "request"):
            # May be local or remote.
            src = self._proc_list[0] if self._proc_list else None
            if src is not None:
                self.send_from(src, reply_to, (True, "broken_promise"))

    # -- connections --
    def _get_conn(self, peer: str) -> _Conn:
        conn = self._conns.get(peer)
        if conn is not None and not conn.closed:
            return conn
        host, port_s = peer.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        conn = _Conn(self, s, peer)
        self._conns[peer] = conn
        try:
            s.connect((host, int(port_s)))
        except BlockingIOError:
            pass
        except OSError:
            self.loop._schedule(
                TaskPriority.DefaultEndpoint, lambda c=conn: c.close()
            )
            return conn
        if self.tls is not None:
            conn.start_tls(server_side=False)
            if conn.closed:
                return conn
        # Handshake frame 0: protocol version + OUR listener address (ref:
        # ConnectPacket carrying protocolVersion + the canonical address,
        # FlowTransport.actor.cpp:189-210).  A peer speaking a different
        # protocol is rejected AT CONNECT — the live-upgrade story starts
        # with being able to tell versions apart on the wire.  Under TLS it
        # rides the encrypted channel after the TLS handshake.
        conn.enqueue(self.protocol_version + b" " + self.address.encode())
        self.selector.register(
            s,
            selectors.EVENT_READ | selectors.EVENT_WRITE,
            lambda mask, c=conn: self._on_io(c, mask),
        )
        return conn

    def _want_write(self, conn: _Conn):
        if conn.closed:
            return
        try:
            self.selector.modify(
                conn.sock,
                selectors.EVENT_READ | selectors.EVENT_WRITE,
                lambda mask, c=conn: self._on_io(c, mask),
            )
        except KeyError:
            pass

    def _on_accept(self, _mask):
        try:
            s, _addr = self._listener.accept()
        except OSError:
            return
        s.setblocking(False)
        conn = _Conn(self, s, None)  # peer learned from the handshake frame
        if self.tls is not None:
            conn.start_tls(server_side=True)
            if conn.closed:
                return
        self.selector.register(
            s,
            selectors.EVENT_READ,
            lambda mask, c=conn: self._on_io(c, mask),
        )

    def _on_io(self, conn: _Conn, mask):
        if conn.closed:
            return
        if mask & selectors.EVENT_WRITE:
            if not conn.connected:
                # A FAILED non-blocking connect also selects writable;
                # SO_ERROR is the real verdict (classic reactor gotcha —
                # without this, a refused dial looks 'established' to the
                # connection post-mortem).
                err = conn.sock.getsockopt(
                    socket.SOL_SOCKET, socket.SO_ERROR
                )
                if err != 0:
                    conn.close()
                    return
                conn.connected = True
            conn.last_activity = time.monotonic()
            if conn.outbuf:
                try:
                    n = conn.sock.send(conn.outbuf)
                    conn.outbuf = conn.outbuf[n:]
                except BlockingIOError:
                    pass
                except OSError:
                    conn.close()
                    return
            if not conn.outbuf:
                try:
                    self.selector.modify(
                        conn.sock,
                        selectors.EVENT_READ,
                        lambda m, c=conn: self._on_io(c, m),
                    )
                except KeyError:
                    pass
        if mask & selectors.EVENT_READ:
            try:
                data = conn.sock.recv(1 << 20)
            except BlockingIOError:
                return
            except OSError:
                conn.close()
                return
            if not data:
                conn.close()
                return
            conn.last_activity = time.monotonic()
            conn.feed_raw(data)  # TLS decrypt (or identity) into inbuf
            if conn.closed:
                return
            self._drain_frames(conn)

    def _drain_frames(self, conn: _Conn):
        while True:
            if len(conn.inbuf) < _LEN.size:
                return
            (length,) = _LEN.unpack_from(conn.inbuf, 0)
            if length > MAX_FRAME:
                conn.close()
                return
            if len(conn.inbuf) < _LEN.size + length:
                return
            frame = conn.inbuf[_LEN.size : _LEN.size + length]
            conn.inbuf = conn.inbuf[_LEN.size + length :]
            if conn.peer is None:
                # First frame on an accepted connection: the handshake.
                if b" " not in frame:
                    # Pre-versioning peers sent a bare address: still an
                    # incompatible protocol — reject LOUDLY so a
                    # mixed-version rollout is diagnosable.
                    TraceEvent(
                        "IncompatibleProtocolVersion", severity=30
                    ).detail("peer_version", "<unversioned>").detail(
                        "local_version", self.protocol_version.decode()
                    ).log()
                    conn.close()
                    return
                ver, addr = frame.split(b" ", 1)
                if ver != self.protocol_version:
                    TraceEvent(
                        "IncompatibleProtocolVersion", severity=30
                    ).detail("peer_version", ver.decode(errors="replace")).detail(
                        "local_version", self.protocol_version.decode()
                    ).log()
                    conn.close()
                    return
                conn.peer = addr.decode()
                old = self._conns.get(conn.peer)
                if old is not None and old is not conn and not old.closed:
                    # Simultaneous connect: the accepted conn wins.  The
                    # replaced dial is closed WITHOUT breaking the peer's
                    # pending replies — they are keyed by peer address and
                    # ride whichever connection is current (ref: the
                    # canonical-connection arbitration in connectionKeeper).
                    old.superseded = True
                    old.close()
                self._conns[conn.peer] = conn
                continue
            try:
                decoded = decode_frame(frame)
                token, payload = decoded
                if not isinstance(token, int):
                    raise WireDecodeError("token not an int")
            except (WireDecodeError, TypeError, ValueError) as e:
                # Corrupt or incompatible frame: drop the connection loudly
                # (decode constructs data only — nothing executed).
                TraceEvent("WireDecodeFailed", severity=30).detail(
                    "peer", conn.peer
                ).detail("error", str(e)[:200]).log()
                conn.close()
                return
            self._deliver_local(token, payload)

    def _deliver_local(self, token: int, payload):
        for p in self._proc_list:
            receiver = p._endpoints.get(token)
            if receiver is not None:
                receiver(payload)
                return
        self._reply_broken(payload)

    def _on_conn_closed(self, conn: _Conn):
        """Break reply promises pending on the lost peer (ref: the NetSAV
        breakage on connection failure, FlowTransport.actor.cpp:355).  A
        superseded duplicate (simultaneous connect) closes silently."""
        if self._conns.get(conn.peer) is conn:
            del self._conns[conn.peer]
        # Post-mortem for connection classification (e.g. the MultiVersion
        # probe distinguishing hello-rejected from never-reached): did this
        # connection ever complete the TCP connect?
        if conn.peer is not None:
            self._last_close_established[conn.peer] = conn.connected
        if conn.superseded:
            return
        TraceEvent("ConnectionClosed").detail("peer", conn.peer).log()
        for p in self._proc_list:
            pending = p._pending_on.pop(conn.peer, None)
            if not pending:
                continue
            for promise, reply_ep in pending:
                p.drop_endpoint(reply_ep)
                if not promise.is_set():
                    self.loop._schedule(
                        TaskPriority.DefaultEndpoint,
                        lambda pr=promise: (
                            None
                            if pr.is_set()
                            else pr.send_error(FdbError("broken_promise"))
                        ),
                    )

    # -- the reactor loop (ref: Net2::run flow/Net2.actor.cpp:121) --
    def stop(self):
        self._stopped = True

    def close(self):
        """Full teardown: every connection, the listener, and the selector
        fd.  stop() alone leaves fds open — fine for process-lifetime
        networks, a leak for per-probe ones (the MultiVersion client
        constructs one network per protocol generation probed)."""
        self.stop()
        for conn in list(self._conns.values()):
            conn.superseded = True  # plain teardown: no broken-promise storm
            conn.close()
        self._conns.clear()
        try:
            self.selector.unregister(self._listener)
        except (KeyError, ValueError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            self.selector.close()
        except OSError:
            pass

    def run_realtime(
        self,
        until=None,
        timeout_s: Optional[float] = None,
    ):
        """Drive timers + IO on wall-clock time.  `until`: optional Future;
        returns its value when ready.  Virtual `loop.now()` is anchored to
        time.monotonic() at first call."""
        loop = self.loop
        t0 = time.monotonic() - loop._now
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while not self._stopped:
            if until is not None and until.is_ready():
                return until.get()
            if loop.failed_actors:
                name, err = loop.failed_actors[0]
                loop.failed_actors = []
                raise RuntimeError(
                    f"unhandled exception in actor {name!r}: {err!r}"
                ) from err
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("run_realtime deadline exceeded")
            now = time.monotonic() - t0
            if loop._heap and loop._heap[0][0] <= now:
                # Due event: let virtual time follow the wall clock.
                loop.run_one()
                continue
            wait = min(loop._heap[0][0] - now, 0.05) if loop._heap else 0.05
            events = self.selector.select(max(0.0, wait))
            loop._now = time.monotonic() - t0
            for key, mask in events:
                key.data(mask)
        return None
