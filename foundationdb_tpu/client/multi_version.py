"""MultiVersion client: pick the protocol generation the cluster speaks.

Ref: fdbclient/MultiVersionTransaction.h:402 / MultiVersionApi — the
reference app links ONE fdb_c version but loads every installed client
library; whichever library's protocol matches the cluster serves the
traffic, and a cluster upgrade switches libraries under the app without a
restart.  The rebuild's analog: a registry of client *implementations*,
each owning a codec generation (its wire PROTOCOL_VERSION and connect
recipe); `MultiVersionClient.connect` probes the cluster with each in
preference order — the transport rejects mismatched hellos AT CONNECT, so
an incompatible generation fails fast and the next is tried (ref: the
protocol-version gate in FlowTransport.actor.cpp:189-210).

A generation here is (protocol_version, bootstrap) where bootstrap builds
a Database over a RealNetwork speaking that version.  With one shipping
protocol the registry holds one real generation; the tests register a
fake future generation to prove the selection and rejection mechanics —
exactly what the reference's MultiVersionApi tests do with dummy client
libs.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

from ..flow.error import FdbError


class ClientGeneration:
    """One loadable 'client library': a protocol version + its connect()."""

    def __init__(self, protocol_version: bytes,
                 bootstrap: Callable, description: str = ""):
        self.protocol_version = protocol_version
        self.bootstrap = bootstrap
        self.description = description or protocol_version.decode(
            errors="replace"
        )


def current_generation() -> ClientGeneration:
    """The generation this tree ships (the linked-in fdb_c analog)."""
    from ..rpc.real_network import PROTOCOL_VERSION

    return ClientGeneration(
        PROTOCOL_VERSION, _bootstrap_current, "current tree"
    )


def _bootstrap_current(address: str, loop, protocol_version: bytes,
                       timeout_s: float):
    """Connect + bootstrap a Database over the given codec generation.
    Raises FdbError('incompatible_protocol_version') when the cluster
    rejects the hello (connection closed without a reply)."""
    from ..rpc.network import Endpoint
    from ..rpc.real_network import RealNetwork
    from ..rpc.stream import RequestStreamRef, well_known_token
    from .transaction import Database

    net = RealNetwork(loop, protocol_version=protocol_version)  # fdblint: ignore[DET101]: real-mode bootstrap by identity — drives a wall-clock RealNetwork, never simulator-executed (sim covers this path via SimNetwork clusters)
    proc = net.process("mv_client")
    boot = RequestStreamRef(
        Endpoint(address, well_known_token("bootstrap")), "bootstrap"
    )

    async def probe():
        return await boot.get_reply(proc, None)

    task = proc.spawn(probe(), "mv_probe")
    try:
        ifaces = net.run_realtime(until=task, timeout_s=timeout_s)  # fdblint: ignore[DET101]: real-mode bootstrap — run_realtime IS the wall-anchored driver; see the ignore on the RealNetwork construction above
    except (FdbError, TimeoutError, RuntimeError) as e:
        conn = net._conns.get(address)
        established = (
            (conn is not None and conn.connected)
            # The transport removes a closed conn from _conns; its
            # post-mortem records whether TCP connect ever completed.
            or net._last_close_established.get(address, False)
        )
        net.close()
        if isinstance(e, TimeoutError):
            raise FdbError("timed_out") from e
        if not established:
            # Never reached the hello at all (refused / unreachable): a
            # DOWN cluster is not a protocol mismatch — misreporting it as
            # one would send the operator chasing version skew.
            raise FdbError("connection_failed") from e
        # Established then closed: the hello was rejected -> broken_promise
        # on the bootstrap reply.
        raise FdbError("incompatible_protocol_version") from e
    db = Database(
        proc,
        ifaces["proxy"],
        ifaces["storage"],
        proxies=ifaces.get("proxies"),
    )
    return net, proc, db


class MultiVersionClient:
    """Probe the cluster with every registered generation, newest first
    (ref: MultiVersionApi::createDatabase trying each client library)."""

    def __init__(self, generations: Optional[List[ClientGeneration]] = None):
        self.generations = generations or [current_generation()]
        self.selected: Optional[ClientGeneration] = None
        self.attempts: List[Tuple[str, str]] = []  # (description, outcome)

    def connect(self, address: str, loop, timeout_s: float = 10.0):
        """(net, proc, db) over the first compatible generation; raises
        incompatible_protocol_version if none matches."""
        deadline = time.monotonic() + timeout_s  # fdblint: ignore[DET001]: connect() probes a REAL cluster over RealNetwork; the deadline bounds real socket connects
        last = "incompatible_protocol_version"
        for gen in self.generations:
            budget = deadline - time.monotonic()  # fdblint: ignore[DET001]: see deadline above — remaining real-time budget for the next generation probe
            if budget <= 0:
                # The stated timeout is a contract: no per-generation floor
                # once it has elapsed.
                self.attempts.append((gen.description, "skipped_deadline"))
                continue
            try:
                net, proc, db = gen.bootstrap(
                    address, loop, gen.protocol_version, budget
                )
            except FdbError as e:
                self.attempts.append((gen.description, e.name))
                last = e.name
                continue
            self.attempts.append((gen.description, "selected"))
            self.selected = gen
            return net, proc, db
        # Every generation failed: surface the most informative error (a
        # down cluster reports connection_failed, not version skew).
        raise FdbError(
            last if last in ("connection_failed", "timed_out")
            else "incompatible_protocol_version"
        )
