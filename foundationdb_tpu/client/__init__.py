"""Client library: the rebuild of fdbclient/ — transaction API, RYW overlay,
atomic ops, wire types."""

from .atomic import apply_atomic, transform_versionstamp
from .transaction import Database, Transaction, transactional
from .types import (
    ALL_KEYS,
    CommitTransactionRef,
    KeySelector,
    Mutation,
    MutationType,
    key_after,
    strinc,
)

__all__ = [
    "apply_atomic",
    "transform_versionstamp",
    "Database",
    "Transaction",
    "transactional",
    "ALL_KEYS",
    "CommitTransactionRef",
    "KeySelector",
    "Mutation",
    "MutationType",
    "key_after",
    "strinc",
]
