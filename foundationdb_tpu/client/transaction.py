"""Client transaction API: the NativeAPI + ReadYourWrites rebuild (v1).

Ref: fdbclient/NativeAPI.actor.cpp (getReadVersion :2770, getValue :1164,
getRange :1603, tryCommit :2361, retry loop onError) and
fdbclient/ReadYourWrites.actor.cpp (uncommitted-write overlay on reads).

RYW model: the transaction keeps its ordered mutation log; a read replays
the mutations affecting that key over the storage snapshot value — simpler
than the reference's versioned WriteMap treap but the same observable
semantics (including atomic-op stacks and set/clear ordering).  Reads add
read conflict ranges unless snapshot=True; every mutation adds its write
conflict range (ref: commitMutations adding ranges per mutation).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..conflict.types import Range
from ..flow.error import FdbError
from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..flow.future import Future, Promise
from ..server.interfaces import (
    CommitTransactionRequest,
    GetKeyServersLocationsRequest,
    GetKeyValuesRequest,
    GetReadVersionRequest,
    GetValueRequest,
    ProxyInterface,
    StorageInterface,
    WatchValueRequest,
)
from ..utils import RangeMap
from .atomic import apply_atomic
from .types import (
    ATOMIC_TYPES,
    CommitTransactionRef,
    KeySelector,
    Mutation,
    MutationType,
    key_after,
)


# Reroute policy shared by every routed read (point, range, watch): on
# wrong_shard_server / broken_promise, invalidate the cached location, wait,
# re-resolve, retry (ref: the backoff in getValue/getRange wrong-shard paths).
MAX_REROUTE_ATTEMPTS = 60
REROUTE_DELAY = 0.01


class Database:
    """A handle bound to a client process + cluster interfaces (ref:
    Database/Cluster in NativeAPI.h).

    Static mode: fixed proxy/storage interfaces (SimCluster).  Dynamic mode:
    `info_var` holds a ClientDBInfo maintained by a cluster-controller
    monitor; interfaces refresh across recoveries (ref: the client's
    monitorProxies / ClientDBInfo subscription).

    The location cache (ref: getKeyLocation_internal
    NativeAPI.actor.cpp:1027) maps key ranges to storage teams, filled from
    the proxy's key-location service and invalidated on wrong_shard_server /
    broken_promise so reads re-route after shard moves and storage deaths."""

    def __init__(
        self,
        process: SimProcess,
        proxy: ProxyInterface = None,
        storage: StorageInterface = None,
        info_var=None,
        proxies: Optional[List[ProxyInterface]] = None,
    ):
        self.process = process
        self._proxy = proxy
        self._proxies = list(proxies) if proxies else ([proxy] if proxy else [])
        self._proxy_rr: dict = {}
        self._storage = storage
        self.info_var = info_var
        # range -> tuple(StorageInterface) | () unsharded | None unknown
        self._loc_cache = RangeMap(None)
        # Per-replica latency/failure model for read routing (ref:
        # QueueModel fdbrpc/QueueModel.h, fed by loadBalance).
        from ..rpc.loadbalance import QueueModel

        self.queue_model = QueueModel()
        # Endpoint liveness pushed from the CC's failure detector (ref:
        # FailureMonitorClient): addr -> failed.  loadBalance orders dead
        # replicas last so reads avoid them WITHOUT eating a timeout.
        self.failure_states: dict = {}
        # Per-flags GRV coalescing lanes (ref: readVersionBatcher,
        # NativeAPI.actor.cpp:2698): {flags: (pending promises, inflight)}.
        self._grv_lanes: dict = {}
        # Client-observed latency distributions, surfaced by status (ref:
        # the latency sample buckets in ClientDBInfo/Status).
        from ..flow.stats import ContinuousSample

        rng = process.network.loop.rng
        self.latency_samples = {
            "grv": ContinuousSample(rng),
            "commit": ContinuousSample(rng),
        }
        # Retries that skipped the GRV round-trip because a structured
        # not_committed carried a witness retry hint (ISSUE 17) — the
        # soak's A/B arm reads this to attribute goodput.
        self.witness_hint_retries = 0
        if info_var is not None:
            from ..server.failure_monitor import run_failure_monitor_client

            process.spawn(
                run_failure_monitor_client(self), "failure_monitor_client"
            )

    def _note_hint_retry(self) -> None:
        self.witness_hint_retries += 1

    def _sample_debug_id(self) -> Optional[str]:
        """A fresh debug id for the latency trace chain, or None when the
        transaction is not sampled (ref: debugTransaction sampling)."""
        rng = self.process.network.loop.rng
        if rng.random01() >= g_knobs.client.latency_sample_rate:
            return None
        return f"{rng.random_int(0, 1 << 62):015x}"

    # --- client-side GRV batching (ref: readVersionBatcher :2698) ---
    async def batched_read_version(self, flags: int) -> int:
        """Coalesce concurrent get_read_version calls: while one GRV
        request is in flight, later callers queue and are all answered by
        the NEXT single request — natural batching under load, zero added
        latency when idle (the reference's batcher has the same shape:
        requests accumulate behind the in-flight one)."""
        lane = self._grv_lanes.setdefault(flags, {"pending": [], "busy": False})
        p = Promise()
        lane["pending"].append(p)
        if not lane["busy"]:
            # Marked busy HERE, not inside the drain: spawn() only schedules,
            # so two same-tick callers would otherwise both observe idle and
            # launch duplicate in-flight GRV requests.
            lane["busy"] = True
            self.process.spawn(self._grv_drain(flags), "grv_batcher")
        return await p.future

    async def _grv_drain(self, flags: int):
        from ..flow.error import ActorCancelled
        from ..flow.trace import trace_batch

        loop = self.process.network.loop
        lane = self._grv_lanes[flags]
        try:
            while lane["pending"]:  # fdblint: ignore[WAIT001]: lane dicts are per-flag singletons — the loop test re-reads the live channel on purpose
                batch, lane["pending"] = lane["pending"], []  # fdblint: ignore[WAIT001]: lane dicts are per-flag singletons (setdefault once, never replaced); the alias IS the shared channel with start-GRV callers
                debug_id = self._sample_debug_id()
                from ..flow.spans import NULL_SPAN, begin_span

                gspan = (
                    begin_span("grv", role="client",
                               attrs={"debug_id": str(debug_id)})
                    if debug_id is not None
                    else NULL_SPAN
                )
                trace_batch(
                    "TransactionDebug",
                    "NativeAPI.getConsistentReadVersion.Before",
                    debug_id,
                )
                t0 = loop.now()
                try:
                    version = await self.pick_proxy(
                        "grv"
                    ).get_consistent_read_version.get_reply(
                        self.process,
                        GetReadVersionRequest(flags=flags, debug_id=debug_id),
                    )
                    self.latency_samples["grv"].add(loop.now() - t0)
                    gspan.end(attrs={"version": version})
                    trace_batch(
                        "TransactionDebug",
                        "NativeAPI.getConsistentReadVersion.After",
                        debug_id,
                    )
                    for p in batch:
                        p.send(version)
                except ActorCancelled:
                    raise  # process dying: waiters die with it
                except FdbError as e:
                    # Each waiter retries through its own on_error loop.
                    gspan.end(attrs={"error": e.name})
                    for p in batch:
                        p.send_error(FdbError(e.name))
                except Exception:  # noqa: BLE001
                    # A non-FdbError (e.g. no proxy during a failover
                    # window) must NOT strand the coalesced waiters in a
                    # silent hang — before batching, each caller saw its
                    # own exception.  Fail them retryably and keep
                    # draining.
                    gspan.end(attrs={"error": "broken_promise"})
                    for p in batch:
                        p.send_error(FdbError("broken_promise"))
        finally:
            lane["busy"] = False  # fdblint: ignore[WAIT001]: same singleton lane — clearing busy on the shared dict is the drain's handshake, not a stale read

    def is_failed(self, iface) -> bool:
        """Is the process behind this interface marked failed?  Keyed by
        any stream ref's endpoint address."""
        for f in vars(iface).values():
            ep = getattr(f, "endpoint", None)
            if ep is not None:
                return bool(self.failure_states.get(ep.address))
        return False

    def invalidate_location(self, begin: bytes, end: Optional[bytes] = None):
        self._loc_cache.set_range(begin, end or key_after(begin), None)

    async def get_locations(self, begin: bytes, end: bytes):
        """(b, e, team) entries covering [begin, end); team () = unsharded
        (use the default storage interface).  Refetches until every gap is
        filled — the proxy truncates replies at its limit, so a huge range
        may take several round trips (ref: the paged getKeyServersLocations
        in getRange, NativeAPI.actor.cpp:1603)."""
        for _ in range(100):
            entries = list(self._loc_cache.intersecting(begin, end))
            gap = next(
                ((b, e) for b, e, v in entries if v is None), None
            )
            if gap is None:
                return entries
            gb, ge = gap
            rep = await self.pick_proxy("loc").get_key_servers_locations.get_reply(
                self.process,
                GetKeyServersLocationsRequest(
                    begin=gb, end=end if ge is None else min(ge, end)
                ),
            )
            if not rep.results:
                # Proxy has no entry (shouldn't happen: RangeMap is total);
                # treat as unsharded rather than spin.
                self._loc_cache.set_range(gb, ge if ge is not None else end, ())
                continue
            for b, e, ifaces in rep.results:
                self._loc_cache.set_range(b, e, tuple(ifaces))
        return list(self._loc_cache.intersecting(begin, end))

    async def storage_for_key(self, key: bytes, attempt: int = 0) -> StorageInterface:
        """Replica for a read; successive attempts rotate through the team
        (the minimal loadBalance, ref fdbrpc/LoadBalance.actor.h:159)."""
        locs = await self.get_locations(key, key_after(key))
        _b, _e, team = locs[0]
        if team:
            return team[attempt % len(team)]
        return self.storage

    @property
    def proxy(self) -> ProxyInterface:
        if self.info_var is not None and self.info_var.get().proxy is not None:
            return self.info_var.get().proxy
        return self._proxy

    def pick_proxy(self, kind: str = "") -> ProxyInterface:
        """Round-robin across the generation's proxies (ref: the proxy
        load-balancing in getConsistentReadVersion / tryCommit via
        loadBalance over ProxyInfo).  A separate counter per call site
        (`kind`): one shared counter phase-locks with the fixed GRV+commit
        call pattern (2 picks/txn), pinning every commit to one proxy."""
        proxies = None
        if self.info_var is not None:
            info = self.info_var.get()
            proxies = getattr(info, "proxies", None) or (
                [info.proxy] if info.proxy is not None else None
            )
        if not proxies:
            proxies = self._proxies
        if not proxies:
            return self.proxy
        self._proxy_rr[kind] = self._proxy_rr.get(kind, 0) + 1
        return proxies[self._proxy_rr[kind] % len(proxies)]

    @property
    def storage(self) -> StorageInterface:
        if self.info_var is not None and self.info_var.get().storage is not None:
            return self.info_var.get().storage
        return self._storage

    async def wait_connected(self):
        while self.proxy is None or self.storage is None:
            await self.info_var.on_change()

    def create_transaction(self) -> "Transaction":
        return Transaction(self)

    async def run(self, fn):
        """Retry loop (ref: the @fdb.transactional decorator / onError)."""
        tr = self.create_transaction()
        while True:
            try:
                result = await fn(tr)
                await tr.commit()
                return result
            except FdbError as e:
                await tr.on_error(e)


class Transaction:
    def __init__(self, db: Database):
        self.db = db
        self._read_version: Optional[int] = None
        self.mutations: List[Mutation] = []
        self.read_conflict_ranges: List[Range] = []
        self.write_conflict_ranges: List[Range] = []
        self.committed_version: Optional[int] = None
        self.options: dict = {}
        self._retries = 0
        self._watches: List[tuple] = []  # (key, value, Promise), armed at commit
        self._committing = False  # set at commit() entry, cleared by reset()
        self._wm_init()

    def _wm_init(self):
        """The WriteMap: mutation-index-keyed structures so RYW reads cost
        O(ops on the key + log) instead of scanning the whole mutation log
        (ref: ReadYourWrites' WriteMap, fdbclient/WriteMap.h).  Issue-time
        snapshots become an `upto` index — the structures are append-only,
        so 'the write map as of mutation i' is answerable at any time."""
        from ..server.storage import VersionedClears
        from ..utils.indexed_set import IndexedSet

        self._wm_key_ops: dict = {}  # key -> [mutation index] (non-clear ops)
        # Ordered key index (O(log n) insert/range — insort's O(n) list
        # shifts would punish descending-key write patterns).
        self._wm_keys = IndexedSet(self.db.process.network.loop.rng)
        self._wm_clears = VersionedClears()  # version = mutation index
        self._wm_stamps: List[tuple] = []  # (index, lo, hi) of SVK ranges

    def _append_mutation(self, m: Mutation):
        idx = len(self.mutations)
        self.mutations.append(m)
        if m.type == MutationType.CLEAR_RANGE:
            self._wm_clears.add(m.param1, m.param2, idx, 0)
        elif m.type == MutationType.SET_VERSIONSTAMPED_KEY:
            (lo, hi), = _stamp_ranges([m])
            self._wm_stamps.append((idx, lo, hi))
        else:
            ops = self._wm_key_ops.get(m.param1)
            if ops is None:
                self._wm_key_ops[m.param1] = [idx]
                self._wm_keys.set(m.param1, 1)
            else:
                ops.append(idx)

    # --- versions ---
    async def get_read_version(self) -> int:
        if self._read_version is None:
            if self.db.info_var is not None:
                await self.db.wait_connected()
            from ..server.interfaces import (
                GRV_FLAG_LOCK_AWARE,
                GRV_FLAG_PRIORITY_BATCH,
            )

            flags = (
                GRV_FLAG_PRIORITY_BATCH
                if self.options.get("priority_batch")
                else 0
            ) | (GRV_FLAG_LOCK_AWARE if self.options.get("lock_aware") else 0)
            version = await self.db.batched_read_version(flags)
            # Re-check after the await: a concurrent get_read_version (or a
            # set_read_version) resolved while this one was suspended, and
            # overwriting it would split the transaction's reads across two
            # snapshot versions.  First resolution wins; everyone returns it.
            if self._read_version is None:
                self._read_version = version
        return self._read_version

    def set_read_version(self, version: int):
        self._read_version = version

    # --- local overlay (RYW) ---
    def _replay(
        self, key: bytes, base: Optional[bytes], upto: int
    ) -> Optional[bytes]:
        """The write map's view of `key` as of mutation index `upto` (the
        ISSUE-TIME snapshot: a write issued while the storage read was in
        flight must not leak into the result — ref: RYW's WriteMap
        consulted when the read is issued, ReadYourWrites.actor.cpp
        readThrough; the WriteDuringRead workload checks exactly this).

        Semantics are identical to an in-order scan of mutations[:upto]:
        a pending SVK whose stamp range covers the key — or a pending SVV
        on the key — is unreadable EVEN IF a later clear masks it (the
        scan raised at the earlier op's position)."""
        for idx, lo, hi in self._wm_stamps:
            if idx < upto and lo <= key <= hi:
                raise FdbError("accessed_unreadable")
        c, _s = (
            self._wm_clears.latest_over(key, upto - 1)
            if upto > 0
            else (-1, -1)
        )
        val = None if c >= 0 else base
        for idx in self._wm_key_ops.get(key, ()):
            if idx >= upto:
                break
            m = self.mutations[idx]
            if m.type == MutationType.SET_VERSIONSTAMPED_VALUE:
                raise FdbError("accessed_unreadable")
            if idx < c:
                continue  # masked by the later clear
            if m.type == MutationType.SET_VALUE:
                val = m.param2
            elif m.type in ATOMIC_TYPES:
                val = apply_atomic(m.type, val, m.param2)
        return val

    def _touched_keys(self, begin: bytes, end: bytes, upto: int) -> List[bytes]:
        """Keys in [begin, end) with any pending non-clear op below `upto`
        (clear masking is _replay's business)."""
        return [
            k
            for k in self._wm_keys.keys_in(begin, end)
            if self._wm_key_ops[k][0] < upto
        ]

    def _check_usable(self):
        """Reads and writes on a transaction whose commit has started (and
        until reset/on_error) fail with used_during_commit (ref:
        ReadYourWritesTransaction's checkUsedDuringCommit,
        ReadYourWrites.actor.cpp)."""
        if self._committing:
            raise FdbError("used_during_commit")

    # --- reads ---
    async def _get_from_storage(self, key: bytes, version: int):
        """Routed point read: the replica team is ordered by the queue
        model and slow replies hedge to the runner-up (ref: loadBalance
        fdbrpc/LoadBalance.actor.h:159); wrong_shard_server invalidates the
        location cache and re-resolves (ref: getValue's handling,
        NativeAPI.actor.cpp:1164)."""
        from ..rpc.loadbalance import load_balance

        loop = self.db.process.network.loop
        last = FdbError("broken_promise")
        for attempt in range(MAX_REROUTE_ATTEMPTS):
            locs = await self.db.get_locations(key, key_after(key))
            # Entry value None (unresolved after the gap-fill cap) or ()
            # (unsharded) both fall back to the default storage.
            team = list(locs[0][2] or ()) or [self.db.storage]
            try:
                return await load_balance(
                    self.db.process,
                    self.db.queue_model,
                    team,
                    lambda iface: iface.get_value.get_reply(
                        self.db.process,
                        GetValueRequest(key=key, version=version),
                    ),
                    key_of=lambda iface: getattr(iface, "storage_id", "")
                    or id(iface),
                    failed=self.db.is_failed,
                )
            except FdbError as e:
                if e.name not in (
                    "wrong_shard_server",
                    "broken_promise",
                    "future_version",
                    "all_alternatives_failed",
                ):
                    raise
                if e.name == "future_version":
                    # The team is just behind its log — retry without
                    # invalidating (a location refetch would return the
                    # identical team and only load the proxy).
                    last = e
                    await loop.delay(REROUTE_DELAY)
                    continue
                last = e
                # Invalidate on broken_promise too: if the WHOLE cached team
                # is dead (healed away), only a location refetch recovers
                # (ref: re-resolving on all_alternatives_failed).
                self.db.invalidate_location(key)
                await loop.delay(REROUTE_DELAY)
        raise last

    async def get(self, key: bytes, snapshot: bool = False) -> Optional[bytes]:
        self._check_usable()
        self._check_legal_key(key)  # reads of \xff.. need the option too
        upto = len(self.mutations)  # issue-time RYW snapshot
        version = await self.get_read_version()
        reply = await self._get_from_storage(key, version)
        if not snapshot:
            self.add_read_conflict_range(key, key_after(key))
        return self._replay(key, reply.value, upto)

    async def get_range(
        self,
        begin: bytes,
        end: bytes,
        limit: int = 1 << 30,
        reverse: bool = False,
        snapshot: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        self._check_usable()
        self._check_legal_key(begin)
        if end > b"\xff" and not self.options.get("access_system_keys"):
            raise FdbError("key_outside_legal_range")
        upto = len(self.mutations)  # issue-time RYW snapshot
        # A scan intersecting any pending versionstamped-key stamp range is
        # unreadable as a whole (computed once per call, not per row; ref:
        # RYW's unreadable ranges for range reads).
        for idx_s, lo_s, hi_s in self._wm_stamps:
            if idx_s < upto and begin <= hi_s and lo_s < end:
                raise FdbError("accessed_unreadable")
        version = await self.get_read_version()
        out: List[Tuple[bytes, bytes]] = []
        loop = self.db.process.network.loop
        # Page through storage until `limit` MERGED rows exist or the range
        # is exhausted: local clears can mask base rows, so a single fetch of
        # `limit` rows may under-fill even though more matching keys exist
        # beyond the fetched extent (ref: RYW readThrough continuation).
        # Each page is clipped to one shard (ref: getRange's per-shard
        # iteration, NativeAPI.actor.cpp:1603).
        lo, hi = begin, end  # remaining un-scanned extent
        misroutes = 0
        while len(out) < limit and lo < hi:
            locs = await self.db.get_locations(lo, hi)
            if reverse:
                b, _e, team = locs[-1]
                req_lo, req_hi = max(b, lo), hi
            else:
                _b, e, team = locs[0]
                req_lo = lo
                req_hi = hi if e is None else min(e, hi)
            if team:
                # Rotate on misroutes, but prefer replicas the failure
                # monitor considers alive (ref: IFailureMonitor-aware pick).
                cand = [
                    team[(misroutes + j) % len(team)]
                    for j in range(len(team))
                ]
                iface = next(
                    (x for x in cand if not self.db.is_failed(x)), cand[0]
                )
            else:
                iface = self.db.storage
            try:
                reply = await iface.get_key_values.get_reply(
                    self.db.process,
                    GetKeyValuesRequest(
                        begin=req_lo,
                        end=req_hi,
                        version=version,
                        limit=limit - len(out),
                        reverse=reverse,
                    ),
                )
            except FdbError as e:
                if e.name not in (
                    "wrong_shard_server",
                    "broken_promise",
                    "future_version",
                ):
                    raise
                misroutes += 1
                if misroutes > MAX_REROUTE_ATTEMPTS:
                    raise
                self.db.invalidate_location(req_lo, req_hi)
                await loop.delay(REROUTE_DELAY)
                continue
            base = dict(reply.data)
            if reply.more:
                # Covered extent ends at the last base row fetched; continue
                # from there next page.
                if reverse:
                    cov_lo, cov_hi = reply.data[-1][0], req_hi
                    hi = cov_lo
                else:
                    cov_lo, cov_hi = req_lo, key_after(reply.data[-1][0])
                    lo = cov_hi
            else:
                cov_lo, cov_hi = req_lo, req_hi
                if reverse:
                    hi = req_lo
                else:
                    lo = req_hi
            merged = set(base)
            merged.update(self._touched_keys(cov_lo, cov_hi, upto))
            for k in sorted(merged, reverse=reverse):
                v = self._replay(k, base.get(k), upto)
                if v is not None:
                    out.append((k, v))
                    if len(out) >= limit:
                        break
        if not snapshot:
            # Conflict range covers only what was actually observed: when the
            # limit truncated the scan, trim to the returned extent (ref: RYW
            # readThrough trimming on limited reads).
            if len(out) >= limit and out:
                if reverse:
                    self.add_read_conflict_range(out[-1][0], end)
                else:
                    self.add_read_conflict_range(begin, key_after(out[-1][0]))
            else:
                self.add_read_conflict_range(begin, end)
        return out

    async def get_key(self, selector: KeySelector, snapshot: bool = False) -> bytes:
        """Resolve a KeySelector to a key (ref: Transaction::getKey; storage
        getKeyQ).  Resolution: index into the sorted key list at
        (first key {>|>=} sel.key) + offset - 1; before-the-front resolves
        to b"" and past-the-end to b"\\xff" (allKeys end), like the ref."""
        start = key_after(selector.key) if selector.or_equal else selector.key
        if selector.offset >= 1:
            rows = await self.get_range(
                start, b"\xff", limit=selector.offset, snapshot=snapshot
            )
            if len(rows) >= selector.offset:
                return rows[selector.offset - 1][0]
            return b"\xff"
        back = 1 - selector.offset
        rows = await self.get_range(
            b"", start, limit=back, reverse=True, snapshot=snapshot
        )
        if len(rows) >= back:
            return rows[back - 1][0]
        return b""

    # --- writes ---
    def set(self, key: bytes, value: bytes):
        self._check_usable()
        self._check_size(key, value)
        self._append_mutation(Mutation(MutationType.SET_VALUE, key, value))
        self.add_write_conflict_range(key, key_after(key))

    def clear(self, key: bytes):
        self._check_usable()
        self._check_legal_key(key)
        self._append_mutation(
            Mutation(MutationType.CLEAR_RANGE, key, key_after(key))
        )
        self.add_write_conflict_range(key, key_after(key))

    def clear_range(self, begin: bytes, end: bytes):
        self._check_usable()
        if begin > end:
            raise FdbError("inverted_range")
        self._check_legal_key(begin)
        if end > b"\xff" and not self.options.get("access_system_keys"):
            raise FdbError("key_outside_legal_range")
        self._append_mutation(Mutation(MutationType.CLEAR_RANGE, begin, end))
        self.add_write_conflict_range(begin, end)

    def atomic_op(self, op: MutationType, key: bytes, operand: bytes):
        self._check_usable()
        assert op in ATOMIC_TYPES, op
        self._check_size(key, operand)
        if op == MutationType.SET_VERSIONSTAMPED_KEY:
            from .atomic import validate_versionstamp_param

            validate_versionstamp_param(key)
            # The stamped key is unknown until commit; conflict on the whole
            # possible stamp range (ref: getVersionstampKeyRange :226).
            # Same computation as the RYW-unreadable check, by construction.
            m = Mutation(op, key, operand)
            self._append_mutation(m)  # records the stamp range once
            _idx, lo, hi = self._wm_stamps[-1]
            self.add_write_conflict_range(lo, key_after(hi))
            return
        if op == MutationType.SET_VERSIONSTAMPED_VALUE:
            from .atomic import validate_versionstamp_param

            validate_versionstamp_param(operand)
        self._append_mutation(Mutation(op, key, operand))
        self.add_write_conflict_range(key, key_after(key))

    def _check_size(self, key: bytes, value: bytes):
        ck = g_knobs.client
        if len(key) > ck.key_size_limit:
            raise FdbError("key_too_large")
        if len(value) > ck.value_size_limit:
            raise FdbError("value_too_large")
        self._check_legal_key(key)

    def _check_legal_key(self, key: bytes):
        """Clients may not touch the system keyspace (ref: keys >= \\xff are
        illegal without ACCESS_SYSTEM_KEYS; fdbclient key_outside_legal_range)."""
        if key >= b"\xff" and not self.options.get("access_system_keys"):
            raise FdbError("key_outside_legal_range")

    # --- watches (ref: Transaction::watch + commitAndWatch NativeAPI:2544) ---
    async def watch(self, key: bytes) -> Future:
        """Future that fires when `key`'s value changes from what this
        transaction observes.  Registered only after a successful commit
        (read-only transactions register at the read version); the watch
        re-arms itself across storage failures."""
        self._check_legal_key(key)
        value = await self.get(key, snapshot=True)
        p = Promise()
        self._watches.append((key, value, p))
        return p.future

    async def _arm_watch(self, key: bytes, value, promise: Promise, version: int):
        while True:
            try:
                iface = await self.db.storage_for_key(key)
                fired = await iface.watch_value.get_reply(
                    self.db.process, WatchValueRequest(key, value, version)
                )
                if not promise.is_set():
                    promise.send(fired)
                return
            except FdbError as e:
                if e.name == "wrong_shard_server":
                    # Shard moved: re-route and re-register.
                    self.db.invalidate_location(key)
                elif e.name not in ("broken_promise", "transaction_too_old"):
                    if not promise.is_set():
                        promise.send_error(e)
                    return
                # Storage moved/restarted: re-register against the current
                # value; if it changed while we were down, fire.
                await self.db.process.network.loop.delay(0.1)
                tr = self.db.create_transaction()
                try:
                    now_val = await tr.get(key, snapshot=True)
                except FdbError:
                    continue
                if now_val != value:
                    if not promise.is_set():
                        promise.send(tr._read_version)
                    return
                version = tr._read_version

    # --- conflict ranges ---
    def add_read_conflict_range(self, begin: bytes, end: bytes):
        if begin < end:
            self.read_conflict_ranges.append((begin, end))

    def add_write_conflict_range(self, begin: bytes, end: bytes):
        if begin < end:
            self.write_conflict_ranges.append((begin, end))

    # --- commit ---
    async def commit(self) -> Optional[int]:
        self._check_usable()
        self._committing = True
        if not self.mutations and not self.write_conflict_ranges:
            self.committed_version = self._read_version
            self._launch_watches(self._read_version or 0)
            return self.committed_version  # read-only: nothing to do
        if self.db.info_var is not None:
            await self.db.wait_connected()
        read = _coalesce(self.read_conflict_ranges)
        write = _coalesce(self.write_conflict_ranges)
        # Self-conflict guarantee (ref: makeSelfConflicting NativeAPI:2052,
        # applied at :2505 unless causalWriteRisky): ensure read∩write is
        # non-empty so a commit_unknown_result can later be resolved by a
        # dummy transaction over a key in the intersection.
        if not self.options.get("causal_write_risky") and (
            _intersect_key(write, read) is None
        ):
            rng = self.db.process.network.loop.rng
            sc = b"\xff/SC/" + rng.random_int(0, 1 << 62).to_bytes(8, "big")
            r = (sc, key_after(sc))
            read = read + [r]
            write = write + [r]
        if read and self._read_version is None:
            # A blind write made self-conflicting still needs a snapshot to
            # resolve against (ref: the causal-read-risky getReadVersion for
            # commits without reads, NativeAPI:2497).
            await self.get_read_version()
        read_snapshot = (self._read_version if read else 0) or 0
        tref = CommitTransactionRef(
            read_snapshot=read_snapshot,
            read_conflict_ranges=read,
            write_conflict_ranges=write,
            mutations=list(self.mutations),
        )
        from ..flow.spans import NULL_SPAN, begin_span
        from ..flow.trace import trace_batch

        loop = self.db.process.network.loop
        debug_id = self.db._sample_debug_id()
        # Commit span (ISSUE 12): sampled transactions only — the same
        # volume bound as the trace_batch chain it sits beside.
        cspan = (
            begin_span("commit", role="client",
                       attrs={"debug_id": str(debug_id)})
            if debug_id is not None
            else NULL_SPAN
        )
        trace_batch("CommitDebug", "NativeAPI.commit.Before", debug_id)
        t0 = loop.now()
        from ..server.interfaces import COMMIT_FLAG_LOCK_AWARE

        commit_flags = (
            COMMIT_FLAG_LOCK_AWARE if self.options.get("lock_aware") else 0
        )
        try:
            version = await self.db.pick_proxy("commit").commit.get_reply(
                self.db.process,
                CommitTransactionRequest(
                    transaction=tref, flags=commit_flags, debug_id=debug_id
                ),
            )
        except FdbError as e:
            # Close the latency chain on the error path too: the
            # ratekeeper's CommitChainSampler ages OPEN chains as a
            # pipeline-stall signal, so a failed attempt must not
            # masquerade as a forever-wedged commit.
            cspan.end(attrs={"error": e.name})
            trace_batch("CommitDebug", "NativeAPI.commit.Error", debug_id)
            if e.name in ("commit_unknown_result", "broken_promise"):
                # The commit may still be in flight.  Before surfacing the
                # unknown result, commit a conflicting dummy transaction
                # over a key in the original's read∩write intersection: once
                # it commits, the original has either committed or will
                # forever conflict, so a retry observes definitive state
                # (ref: commitDummyTransaction NativeAPI:2315, invoked
                # :2430-2449).
                if not self.options.get("causal_write_risky"):
                    from ..flow.testprobe import test_probe

                    test_probe("commit_unknown_fence")
                    key = _intersect_key(write, read)
                    assert key is not None  # guaranteed by self-conflicting
                    await self._commit_dummy(key)
                raise FdbError("commit_unknown_result")
            raise
        self.db.latency_samples["commit"].add(loop.now() - t0)
        cspan.end(attrs={"version": version})
        trace_batch("CommitDebug", "NativeAPI.commit.After", debug_id)
        self.committed_version = version
        self._launch_watches(version)
        return version

    async def _commit_dummy(self, key: bytes):
        """Fence the in-flight original (ref commitDummyTransaction :2315).
        Retries ride the client retry knobs so the fence outlasts any
        recovery the adjacent on_error backoff would survive."""
        loop = self.db.process.network.loop
        ck = g_knobs.client
        for attempt in range(ck.dummy_commit_max_retries):
            tr = Transaction(self.db)
            tr.options["causal_write_risky"] = True
            tr.options["access_system_keys"] = True
            # The fence must work under a database lock iff the original
            # could commit under it.
            if self.options.get("lock_aware"):
                tr.options["lock_aware"] = True
            tr.add_read_conflict_range(key, key_after(key))
            tr.add_write_conflict_range(key, key_after(key))
            try:
                # A conflict-ranges-only transaction must still traverse the
                # commit pipeline: give it a read snapshot so it can
                # conflict.  Inside the retry guard: the fence runs exactly
                # when the generation is dying, so the GRV itself may get
                # broken_promise.
                await tr.get_read_version()
                await tr.commit()
                return
            except FdbError as e:
                if not (
                    e.is_retryable_in_transaction()
                    or e.name == "broken_promise"
                ):
                    raise
                await loop.delay(
                    min(
                        ck.max_retry_delay,
                        ck.initial_retry_delay * (2 ** min(attempt, 30)),
                    )
                )
        raise FdbError("commit_unknown_result")

    def _launch_watches(self, version: int):
        watches, self._watches = self._watches, []
        for key, value, promise in watches:
            self.db.process.spawn(
                self._arm_watch(key, value, promise, version), "watch"
            )

    async def on_error(self, e: FdbError):
        """Backoff + reset if retryable, else re-raise (ref: onError).

        Witness-guided retry (ISSUE 17): a structured not_committed
        carries the combined abort witness, including retry_version —
        the version the aborting batch resolved at, i.e. the newest
        snapshot at which the lost conflict is fully visible.  With
        FDB_TPU_WITNESS_RETRY on, the next attempt seeds its read
        version there instead of paying a fresh GRV round-trip, and
        skips the blind backoff: the backoff exists because an
        UNINFORMED retry risks stampeding with the same stale view,
        but a hinted retry is guaranteed to observe the write that
        aborted us, so the livelock it guards against cannot recur
        (reference clients always back off and re-GRV; fdbserver
        returns only the bare error)."""
        if not (
            e.is_retryable_in_transaction() or e.name == "broken_promise"
        ):
            raise e
        from ..flow.knobs import g_env

        hint = None
        if (
            e.name == "not_committed"
            and isinstance(e.detail, dict)
            and e.detail.get("retry_version") is not None
            and g_env.get("FDB_TPU_WITNESS_RETRY") not in ("", "0")
        ):
            hint = int(e.detail["retry_version"])
        ck = g_knobs.client
        delay = min(
            ck.max_retry_delay,
            ck.initial_retry_delay * (2 ** min(self._retries, 30)),
        )
        self._retries += 1
        if hint is None:
            await self.db.process.network.loop.delay(
                delay * self.db.process.network.loop.rng.random01()
            )
        self.reset()
        if hint is not None:
            self._read_version = hint
            self.db._note_hint_retry()

    def reset(self):
        self._read_version = None
        self._committing = False
        self.mutations = []
        self._wm_init()
        self.read_conflict_ranges = []
        self.write_conflict_ranges = []
        self.committed_version = None
        for _k, _v, promise in self._watches:
            if not promise.is_set():
                promise.send_error(FdbError("watch_cancelled"))
        self._watches = []


def _stamp_ranges(muts) -> List[Tuple[bytes, bytes]]:
    """[lo, hi] (inclusive) possible-key ranges of pending
    SET_VERSIONSTAMPED_KEY mutations (ref: getVersionstampKeyRange :226)."""
    out = []
    for m in muts:
        if m.type == MutationType.SET_VERSIONSTAMPED_KEY:
            pos = int.from_bytes(m.param1[-4:], "little", signed=True)
            body = m.param1[:-4]
            out.append(
                (
                    body[:pos] + b"\x00" * 10 + body[pos + 10 :],
                    body[:pos] + b"\xff" * 10 + body[pos + 10 :],
                )
            )
    return out


def _intersect_key(write: List[Range], read: List[Range]) -> Optional[bytes]:
    """A key inside some write∩read range overlap, or None (ref: the
    intersects() probe in tryCommit's commit_unknown_result handling,
    NativeAPI.actor.cpp:2440-2443)."""
    for wb, we in write:
        for rb, re_ in read:
            lo, hi = max(wb, rb), min(we, re_)
            if lo < hi:
                return lo
    return None


def _coalesce(ranges: List[Range]) -> List[Range]:
    """Merge overlapping/adjacent ranges (ref: the conflict-range coalescing
    in CommitTransactionRef construction)."""
    if len(ranges) <= 1:
        return list(ranges)
    s = sorted(ranges)
    out = [list(s[0])]
    for b, e in s[1:]:
        if b <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([b, e])
    return [(b, e) for b, e in out]


def transactional(fn):
    """`@transactional` (ref: the python binding's fdb.transactional,
    bindings/python/fdb/impl.py): the decorated coroutine's first
    argument may be a Database (a fresh transaction + the retry loop
    wraps the call) or a Transaction (the call joins the caller's
    transaction — no commit, no retry; composability is the point)."""
    import functools

    @functools.wraps(fn)
    async def wrapper(db_or_tr, *args, **kwargs):
        if isinstance(db_or_tr, Transaction):
            return await fn(db_or_tr, *args, **kwargs)
        return await db_or_tr.run(
            lambda tr: fn(tr, *args, **kwargs)
        )

    return wrapper
