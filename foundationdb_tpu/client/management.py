"""ManagementAPI: cluster configuration as transactions on `\xff/conf`.

Ref: fdbclient/ManagementAPI.actor.cpp — `configure`, exclude/include are
ordinary transactions on system keys (configKeysPrefix `\xff/conf/`,
excludedServersPrefix); every role learns changes through the mutation
stream, and the cluster controller reacts by recruiting a new generation
when the topology no longer matches (changeConfig -> waitForFullReplication
-> recovery).

Supported here: proxy count (stateless; applied at the next generation),
plus storage exclusion records consumed by DD healing.  Stateful counts
(tlogs/storages) are recorded but not auto-applied — their disks pin them
to machines, and resizing the log set changes tag placement for old
epochs (see tlog.begin_version); that arrives with log-epoch routing.
"""

from __future__ import annotations

from typing import Dict, List, Optional

CONF_PREFIX = b"\xff/conf/"
CONF_END = b"\xff/conf0"
EXCLUDED_PREFIX = b"\xff/conf/excluded/"
EXCLUDED_END = b"\xff/conf/excluded0"

_INT_KEYS = (
    "proxies",
    "resolvers",
    "logs",
    "storage_team_size",
    # Multi-region (ref: the region configuration in DatabaseConfiguration
    # — usable_regions=2 keeps a second region's replica set; satellites
    # are the synchronous full-stream logs in the primary region that make
    # remote failover lossless).  Recorded in `\xff/conf` like the
    # reference; SimCluster(n_satellite_tlogs=..) + LogRouter build the
    # topology these knobs describe.
    "usable_regions",
    "satellite_logs",
)


def conf_key(name: str) -> bytes:
    return CONF_PREFIX + name.encode()


async def configure(db, **params) -> None:
    """Transactionally set configuration fields, e.g.
    configure(db, proxies=2) (ref: changeConfig ManagementAPI:253)."""

    async def txn(tr):
        tr.options["access_system_keys"] = True
        for name, value in params.items():
            if name not in _INT_KEYS:
                raise ValueError(f"unknown configuration key {name!r}")
            tr.set(conf_key(name), b"%d" % int(value))

    await db.run(txn)


async def get_configuration(db) -> Dict[str, int]:
    out = {}

    async def txn(tr):
        tr.options["access_system_keys"] = True
        rows = await tr.get_range(CONF_PREFIX, CONF_END)
        for k, v in rows:
            name = k[len(CONF_PREFIX):].decode()
            if name.startswith("excluded/") or name == "resolverSplit":
                continue
            out[name] = int(v.decode())

    await db.run(txn)
    return out


async def exclude_servers(db, storage_ids: List[str]) -> None:
    """Mark storages for removal (ref: excludeServers ManagementAPI:556);
    DD healing treats excluded servers like failed ones — moves their data
    to teammates and unregisters their log tags."""

    async def txn(tr):
        tr.options["access_system_keys"] = True
        for sid in storage_ids:
            tr.set(EXCLUDED_PREFIX + sid.encode(), b"1")

    await db.run(txn)


async def include_servers(db, storage_ids: Optional[List[str]] = None) -> None:
    """Clear exclusion records (ref: includeServers ManagementAPI:606);
    None = include everything."""

    async def txn(tr):
        tr.options["access_system_keys"] = True
        if storage_ids is None:
            tr.clear_range(EXCLUDED_PREFIX, EXCLUDED_END)
        else:
            for sid in storage_ids:
                tr.clear(EXCLUDED_PREFIX + sid.encode())

    await db.run(txn)


async def get_excluded_servers(db) -> List[str]:
    out: List[str] = []

    async def txn(tr):
        tr.options["access_system_keys"] = True
        rows = await tr.get_range(EXCLUDED_PREFIX, EXCLUDED_END)
        out[:] = [k[len(EXCLUDED_PREFIX):].decode() for k, _v in rows]

    await db.run(txn)
    return out
