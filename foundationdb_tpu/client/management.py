"""ManagementAPI: cluster configuration as transactions on `\xff/conf`.

Ref: fdbclient/ManagementAPI.actor.cpp — `configure`, exclude/include are
ordinary transactions on system keys (configKeysPrefix `\xff/conf/`,
excludedServersPrefix); every role learns changes through the mutation
stream, and the cluster controller reacts by recruiting a new generation
when the topology no longer matches (changeConfig -> waitForFullReplication
-> recovery).

Supported here: proxy count (stateless; applied at the next generation),
plus storage exclusion records consumed by DD healing.  Stateful counts
(tlogs/storages) are recorded but not auto-applied — their disks pin them
to machines, and resizing the log set changes tag placement for old
epochs (see tlog.begin_version); that arrives with log-epoch routing.
"""

from __future__ import annotations

from typing import Dict, List, Optional

CONF_PREFIX = b"\xff/conf/"
CONF_END = b"\xff/conf0"
EXCLUDED_PREFIX = b"\xff/conf/excluded/"
EXCLUDED_END = b"\xff/conf/excluded0"

_INT_KEYS = (
    "proxies",
    "resolvers",
    "logs",
    "storage_team_size",
    # Multi-region (ref: the region configuration in DatabaseConfiguration
    # — usable_regions=2 keeps a second region's replica set; satellites
    # are the synchronous full-stream logs in the primary region that make
    # remote failover lossless).  Recorded in `\xff/conf` like the
    # reference; SimCluster(n_satellite_tlogs=..) + LogRouter build the
    # topology these knobs describe.
    "usable_regions",
    "satellite_logs",
)


def conf_key(name: str) -> bytes:
    return CONF_PREFIX + name.encode()


async def configure(db, **params) -> None:
    """Transactionally set configuration fields, e.g.
    configure(db, proxies=2) (ref: changeConfig ManagementAPI:253)."""

    async def txn(tr):
        tr.options["access_system_keys"] = True
        for name, value in params.items():
            if name not in _INT_KEYS:
                raise ValueError(f"unknown configuration key {name!r}")
            tr.set(conf_key(name), b"%d" % int(value))

    await db.run(txn)


async def get_configuration(db) -> Dict[str, int]:
    out = {}

    async def txn(tr):
        tr.options["access_system_keys"] = True
        rows = await tr.get_range(CONF_PREFIX, CONF_END)
        for k, v in rows:
            name = k[len(CONF_PREFIX):].decode()
            if (
                name.startswith("excluded/")
                or name.startswith("class/")
                or name in ("resolverSplit", "coordinators")
            ):
                continue
            out[name] = int(v.decode())

    await db.run(txn)
    return out


CLASS_PREFIX = b"\xff/conf/class/"
CLASS_END = b"\xff/conf/class0"

VALID_CLASSES = ("unset", "stateless", "transaction", "storage",
                 "coordinator")


async def change_coordinators(db, new_addresses: List[str]) -> None:
    """Request a coordinator quorum change (ref: changeQuorum
    ManagementAPI.actor.cpp:684).  Client-side safety checks here; the
    acting cluster controller performs the movable-state handoff (write
    manifest to the new quorum, fence + forward the old) and the change is
    complete when every election client has retargeted.
    """
    if not new_addresses:
        raise ValueError("empty coordinator set")
    if len(set(new_addresses)) != len(new_addresses):
        raise ValueError("duplicate coordinator address")
    if len(new_addresses) % 2 == 0:
        # An even quorum tolerates no more failures than the next odd size
        # down and doubles the tie surface (the reference warns similarly).
        raise ValueError("coordinator count must be odd")

    async def txn(tr):
        tr.options["access_system_keys"] = True
        tr.set(conf_key("coordinators"), ",".join(new_addresses).encode())

    await db.run(txn)


async def get_requested_coordinators(db) -> Optional[List[str]]:
    out: List[Optional[bytes]] = [None]

    async def txn(tr):
        tr.options["access_system_keys"] = True
        out[0] = await tr.get(conf_key("coordinators"))

    await db.run(txn)
    return out[0].decode().split(",") if out[0] else None


async def set_process_class(db, address: str, process_class: str) -> None:
    """Assign a recruitment class to the worker at `address` (ref: setclass
    fdbcli / processClass in SystemData) — applied at the next generation's
    recruitment."""
    if process_class not in VALID_CLASSES:
        raise ValueError(f"unknown process class {process_class!r}")

    async def txn(tr):
        tr.options["access_system_keys"] = True
        if process_class == "unset":
            tr.clear(CLASS_PREFIX + address.encode())
        else:
            tr.set(CLASS_PREFIX + address.encode(), process_class.encode())

    await db.run(txn)


async def get_process_classes(db) -> Dict[str, str]:
    out: Dict[str, str] = {}

    async def txn(tr):
        tr.options["access_system_keys"] = True
        rows = await tr.get_range(CLASS_PREFIX, CLASS_END)
        out.clear()
        for k, v in rows:
            out[k[len(CLASS_PREFIX):].decode()] = v.decode()

    await db.run(txn)
    return out


async def lock_database(db, uid: Optional[bytes] = None) -> bytes:
    """Lock the database (ref: lockDatabase ManagementAPI.actor.cpp:400):
    writes a UID into `\xff/dbLocked`; every non-lock-aware GRV/commit
    fails database_locked until unlock.  Locking an already-locked
    database with a DIFFERENT uid raises database_locked; same uid is
    idempotent."""
    if uid is None:
        uid = b"%016x" % db.process.network.loop.rng.random_int(1, 1 << 62)
    await _write_lock_record(db, uid, uid)
    return uid


async def _write_lock_record(db, holder_uid: bytes, value: bytes) -> None:
    """Shared lock/unlock writer.  Explicit retry loop: db.run would retry
    database_locked (it is in the client retry set, as in the reference's
    onError), but a CONFLICTING holder must surface — the reference's
    lockDatabase rethrows it before onError (ManagementAPI.actor.cpp:1279).
    Idempotent under commit_unknown_result: rewriting the same value is
    harmless."""
    from ..flow.error import FdbError
    from ..server.system_keys import DB_LOCKED_KEY

    tr = db.create_transaction()
    while True:
        try:
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            cur = await tr.get(DB_LOCKED_KEY)
            if cur and cur != holder_uid:
                raise FdbError("database_locked")  # someone else's lock
            tr.set(DB_LOCKED_KEY, value)
            await tr.commit()
            return
        except FdbError as e:
            if e.name == "database_locked":
                raise
            await tr.on_error(e)


async def unlock_database(db, uid: bytes) -> None:
    """Ref: unlockDatabase — only the holder of the lock UID may unlock.
    Writes the empty value (= unlocked; see DB_LOCKED_KEY)."""
    await _write_lock_record(db, uid, b"")


async def exclude_servers(db, storage_ids: List[str]) -> None:
    """Mark storages for removal (ref: excludeServers ManagementAPI:556);
    DD healing treats excluded servers like failed ones — moves their data
    to teammates and unregisters their log tags."""

    async def txn(tr):
        tr.options["access_system_keys"] = True
        for sid in storage_ids:
            tr.set(EXCLUDED_PREFIX + sid.encode(), b"1")

    await db.run(txn)


async def include_servers(db, storage_ids: Optional[List[str]] = None) -> None:
    """Clear exclusion records (ref: includeServers ManagementAPI:606);
    None = include everything."""

    async def txn(tr):
        tr.options["access_system_keys"] = True
        if storage_ids is None:
            tr.clear_range(EXCLUDED_PREFIX, EXCLUDED_END)
        else:
            for sid in storage_ids:
                tr.clear(EXCLUDED_PREFIX + sid.encode())

    await db.run(txn)


async def get_excluded_servers(db) -> List[str]:
    out: List[str] = []

    async def txn(tr):
        tr.options["access_system_keys"] = True
        rows = await tr.get_range(EXCLUDED_PREFIX, EXCLUDED_END)
        out[:] = [k[len(EXCLUDED_PREFIX):].decode() for k, _v in rows]

    await db.run(txn)
    return out


async def version_from_timestamp(db, timestamp: float) -> int:
    """Map a wall-clock time to the LAST commit version known to be at or
    before it, from the CC's TimeKeeper samples (ref: fdbbackup's
    timeKeeperVersionFromDatetime, backup.actor.cpp:1828 — used for
    `restore --timestamp`).  Raises restore_error when no sample covers
    the time (cluster younger than the timestamp, or TimeKeeper
    disabled)."""
    from ..flow.error import FdbError
    from ..server.system_keys import (
        TIME_KEEPER_PREFIX,
        time_keeper_key,
    )

    async def txn(tr):
        tr.options["access_system_keys"] = True
        tr.options["lock_aware"] = True
        rows = await tr.get_range(
            TIME_KEEPER_PREFIX,
            time_keeper_key(max(0, int(timestamp) + 1)),
            limit=1,
            reverse=True,
        )
        return int(rows[0][1]) if rows else None

    v = await db.run(txn)
    if v is None:
        raise FdbError("restore_error")
    return v
