"""Atomic-op semantics shared by client RYW and storage apply.

Ref: fdbclient/Atomic.h (doLittleEndianAdd, doAnd/V2, doOr, doXor,
doAppendIfFits, doMax, doMin/V2, doByteMin, doByteMax).  Semantics are
matched exactly — including the quirks: results take the operand's length
(add/and/min/max truncate or zero-extend the existing value), and the
pre-V2 And/Min treat a *missing* key as empty rather than absent.  The byte
loops become Python int arithmetic on little-endian values.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..flow.knobs import g_knobs
from .types import MutationType


def _le(b: bytes) -> int:
    return int.from_bytes(b, "little")


def _le_bytes(v: int, length: int) -> bytes:
    return (v & ((1 << (8 * length)) - 1)).to_bytes(length, "little")


def add_value(existing: Optional[bytes], operand: bytes) -> bytes:
    ex = existing or b""
    if not ex or not operand:
        return operand
    return _le_bytes(_le(ex) + _le(operand), len(operand))


def and_(existing: Optional[bytes], operand: bytes) -> bytes:
    ex = existing or b""
    if not operand:
        return operand
    # AND over the overlap; bytes beyond the existing value are zero.
    return _le_bytes(_le(ex) & _le(operand), len(operand))


def and_v2(existing: Optional[bytes], operand: bytes) -> bytes:
    if existing is None:
        return operand
    return and_(existing, operand)


def or_(existing: Optional[bytes], operand: bytes) -> bytes:
    ex = existing or b""
    if not ex or not operand:
        return operand
    return _le_bytes(_le(ex[: len(operand)]) | _le(operand), len(operand))


def xor(existing: Optional[bytes], operand: bytes) -> bytes:
    ex = existing or b""
    if not ex or not operand:
        return operand
    return _le_bytes(_le(ex[: len(operand)]) ^ _le(operand), len(operand))


def append_if_fits(existing: Optional[bytes], operand: bytes) -> bytes:
    ex = existing or b""
    if not ex:
        return operand
    if not operand:
        return ex
    if len(ex) + len(operand) > g_knobs.client.value_size_limit:
        return ex
    return ex + operand


def max_(existing: Optional[bytes], operand: bytes) -> bytes:
    ex = existing or b""
    if not ex or not operand:
        return operand
    ex_t = _le(ex[: len(operand)])
    if _le(operand) >= ex_t:
        return operand
    return _le_bytes(ex_t, len(operand))


def min_(existing: Optional[bytes], operand: bytes) -> bytes:
    if not operand:
        return operand
    ex = existing or b""
    ex_t = _le(ex[: len(operand)])
    if _le(operand) < ex_t:
        return operand
    return _le_bytes(ex_t, len(operand))


def min_v2(existing: Optional[bytes], operand: bytes) -> bytes:
    if existing is None:
        return operand
    return min_(existing, operand)


def byte_min(existing: Optional[bytes], operand: bytes) -> bytes:
    if existing is None:
        return operand
    return min(existing, operand)


def byte_max(existing: Optional[bytes], operand: bytes) -> bytes:
    if existing is None:
        return operand
    return max(existing, operand)


APPLY: Dict[MutationType, Callable[[Optional[bytes], bytes], bytes]] = {
    MutationType.ADD_VALUE: add_value,
    MutationType.AND: and_,
    MutationType.AND_V2: and_v2,
    MutationType.OR: or_,
    MutationType.XOR: xor,
    MutationType.APPEND_IF_FITS: append_if_fits,
    MutationType.MAX: max_,
    MutationType.MIN: min_,
    MutationType.MIN_V2: min_v2,
    MutationType.BYTE_MIN: byte_min,
    MutationType.BYTE_MAX: byte_max,
}


def apply_atomic(
    op: MutationType, existing: Optional[bytes], operand: bytes
) -> bytes:
    return APPLY[op](existing, operand)


def transform_versionstamp(data: bytes, version: int, txn_number: int) -> bytes:
    """Substitute the 10-byte versionstamp into a SET_VERSIONSTAMPED_* param.

    Ref: Atomic.h transformVersionstampMutation :258 / placeVersionstamp
    :249 — the param's final 4 bytes are a little-endian offset (stripped);
    the stamp is 8-byte big-endian commit version + 2-byte big-endian
    transaction-number-in-batch.  An out-of-bounds offset is
    client_invalid_operation (ref: getVersionstampKeyRange :240), checked
    client-side at mutation time via validate_versionstamp_param.
    """
    validate_versionstamp_param(data)
    pos = int.from_bytes(data[-4:], "little", signed=True)
    body = bytearray(data[:-4])
    body[pos : pos + 8] = version.to_bytes(8, "big")
    body[pos + 8 : pos + 10] = txn_number.to_bytes(2, "big")
    return bytes(body)


def validate_versionstamp_param(data: bytes) -> None:
    from ..flow.error import FdbError

    if len(data) < 4:
        raise FdbError("client_invalid_operation")
    pos = int.from_bytes(data[-4:], "little", signed=True)
    if pos < 0 or pos + 10 > len(data) - 4:
        raise FdbError("client_invalid_operation")
