"""MetricLogger: persist counter collections into the `\xff/metrics`
keyspace.

Ref: fdbclient/MetricLogger.actor.cpp — TDMetric time series are written
into the database itself on a cadence, so operators and tools read metrics
with ordinary transactions (fdbcli, StatusWorkload).  Here each counter
lands at `\xff/metrics/<collection>/<name>` with a packed (time, value)
sample appended to a bounded series.
"""

from __future__ import annotations

import pickle
from typing import List

METRICS_PREFIX = b"\xff/metrics/"
METRICS_END = b"\xff/metrics0"
MAX_SAMPLES = 64  # bounded series per metric (oldest dropped)


def metric_key(collection: str, name: str) -> bytes:
    return METRICS_PREFIX + collection.encode() + b"/" + name.encode()


async def log_metrics_once(db, collections: List) -> None:
    """One flush of every counter's current value (appended to its
    series)."""
    loop = db.process.network.loop
    now = loop.now()

    async def txn(tr):
        tr.options["access_system_keys"] = True
        for coll in collections:
            for name, c in coll.counters.items():
                key = metric_key(coll.name, name)
                raw = await tr.get(key)
                series = pickle.loads(raw) if raw else []
                series.append((now, c.value))
                tr.set(
                    key, pickle.dumps(series[-MAX_SAMPLES:], protocol=4)
                )

    await db.run(txn)


async def run_metric_logger(db, collections: List, interval: float = 5.0):
    """The periodic flush actor (ref: runMetrics MetricLogger.actor.cpp)."""
    loop = db.process.network.loop
    while True:
        await loop.delay(interval)
        await log_metrics_once(db, collections)


async def read_metrics(db, collection: str) -> dict:
    """{name: [(time, value)]} for one collection (the consumer side)."""
    out = {}

    async def txn(tr):
        tr.options["access_system_keys"] = True
        prefix = METRICS_PREFIX + collection.encode() + b"/"
        rows = await tr.get_range(prefix, prefix + b"\xff")
        for k, v in rows:
            out[k[len(prefix):].decode()] = pickle.loads(v)

    await db.run(txn)
    return out
