"""MetricLogger: persist counter collections into the `\xff/metrics`
keyspace as MULTI-RESOLUTION time series.

Ref: fdbclient/MetricLogger.actor.cpp + flow/TDMetric.actor.h:168 — the
reference's TDMetricCollection keeps each metric at several time LEVELS
(finer-recent, coarser-long: each level covers ~4x the span of the one
below) and writes them into the database itself, so operators and tools
read metrics with ordinary transactions (fdbcli, StatusWorkload).  Here
each counter lands at `\xff/metrics/<collection>/<name>` as LEVELS
bounded series: level 0 records every flush; level i records one sample
per BASE_RESOLUTION * 4**i seconds — 64 samples/level means level 3
covers ~5.7 hours at a 5 s cadence while level 0 stays 5 s-grained.
Values use the versioned wire codec (no pickle in stored state).
"""

from __future__ import annotations

from typing import List

from ..rpc.wire import WireDecodeError, decode_frame, encode_frame


def _decode_levels(raw):
    """Stored series -> levels; a foreign/corrupt value (e.g. rows written
    by the old pickle format) resets the series instead of killing the
    metric logger actor for the process lifetime."""
    if raw:
        try:
            levels = decode_frame(raw)
            if isinstance(levels, list) and len(levels) == LEVELS:
                return levels
        except WireDecodeError:
            pass
    return [[] for _ in range(LEVELS)]

METRICS_PREFIX = b"\xff/metrics/"
METRICS_END = b"\xff/metrics0"
MAX_SAMPLES = 64  # bounded series per level (oldest dropped)
LEVELS = 4
BASE_RESOLUTION = 5.0  # level i samples every BASE_RESOLUTION * 4**i


def metric_key(collection: str, name: str) -> bytes:
    return METRICS_PREFIX + collection.encode() + b"/" + name.encode()


async def log_metrics_once(db, collections: List) -> None:
    """One flush of every counter's current value (appended to its
    series)."""
    loop = db.process.network.loop
    now = loop.now()

    async def txn(tr):
        tr.options["access_system_keys"] = True
        for coll in collections:
            for name, c in coll.counters.items():
                key = metric_key(coll.name, name)
                raw = await tr.get(key)
                levels = _decode_levels(raw)
                for lv in range(LEVELS):
                    series = levels[lv]
                    period = BASE_RESOLUTION * (4 ** lv)
                    if lv == 0 or not series or now - series[-1][0] >= period:
                        series.append((now, c.value))
                        del series[:-MAX_SAMPLES]
                tr.set(key, encode_frame(levels))

    await db.run(txn)


async def run_metric_logger(
    db, collections: List, interval: float = BASE_RESOLUTION
):
    """The periodic flush actor (ref: runMetrics MetricLogger.actor.cpp)."""
    loop = db.process.network.loop
    while True:
        await loop.delay(interval)
        await log_metrics_once(db, collections)


async def read_metrics(db, collection: str) -> dict:
    """{name: [(time, value)]} for one collection (the consumer side)."""
    out = {}

    async def txn(tr):
        tr.options["access_system_keys"] = True
        prefix = METRICS_PREFIX + collection.encode() + b"/"
        rows = await tr.get_range(prefix, prefix + b"\xff")
        for k, v in rows:
            out[k[len(prefix):].decode()] = _decode_levels(v)

    await db.run(txn)
    return {name: levels[0] for name, levels in out.items()}


async def read_metric_levels(db, collection: str, name: str) -> list:
    """All resolution levels of one metric: [[(time, value)], ...] — level
    i sampled every BASE_RESOLUTION * 4**i (ref: the per-level blocks in
    TDMetric.actor.h)."""
    out = {}

    async def txn(tr):
        tr.options["access_system_keys"] = True
        raw = await tr.get(metric_key(collection, name))
        out["levels"] = _decode_levels(raw)

    await db.run(txn)
    return out["levels"]
