"""Cluster connection file: `description:id@host:port,host:port,...`.

Ref: fdbclient/ClusterConnectionFile (MonitorLeader.actor.cpp's
ClusterConnectionString parse :53-120 and the file rewrite on coordinator
changes).  The description names the cluster for humans; the id changes
when the coordinator set changes; the address list is how every client
and server finds the coordinators.  Rewrites are atomic (write-aside +
rename) so a crash never leaves a torn file.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List


class ClusterFileError(ValueError):
    pass


@dataclass
class ClusterConnectionString:
    description: str
    cluster_id: str
    coordinators: List[str]  # "host:port" strings

    @classmethod
    def parse(cls, text: str) -> "ClusterConnectionString":
        """Parse `desc:id@addr,addr,...` (comments and blank lines allowed
        around the single connection line, like the reference's file)."""
        lines = [
            ln.strip()
            for ln in text.splitlines()
            if ln.strip() and not ln.strip().startswith("#")
        ]
        if len(lines) != 1:
            raise ClusterFileError(
                f"expected exactly one connection line, got {len(lines)}"
            )
        line = lines[0]
        head, sep, addrs = line.partition("@")
        if not sep or ":" not in head:
            raise ClusterFileError(f"malformed connection string: {line!r}")
        desc, _, cid = head.partition(":")
        if not desc or not cid:
            raise ClusterFileError(f"malformed description:id in {line!r}")
        if not all(c.isalnum() or c == "_" for c in desc):
            raise ClusterFileError(f"illegal description {desc!r}")
        if not all(c.isalnum() for c in cid):
            raise ClusterFileError(f"illegal id {cid!r}")
        coords = [a.strip() for a in addrs.split(",") if a.strip()]
        if not coords:
            raise ClusterFileError("no coordinator addresses")
        for a in coords:
            if ":" not in a:
                raise ClusterFileError(f"address {a!r} lacks a port")
        return cls(description=desc, cluster_id=cid, coordinators=coords)

    def format(self) -> str:
        return (
            f"{self.description}:{self.cluster_id}@"
            + ",".join(self.coordinators)
        )


def read_cluster_file(path: str) -> ClusterConnectionString:
    with open(path, "r", encoding="utf-8") as f:  # fdblint: ignore[IO001]: the cluster file is real client-side state (fdb.cluster analog); sim clusters never call this
        return ClusterConnectionString.parse(f.read())


def write_cluster_file(path: str, cs: ClusterConnectionString) -> None:
    """Atomic rewrite (ref: the reference rewriting the file when the
    coordinator set changes — never torn, old readers see old or new)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:  # fdblint: ignore[IO001]: atomic rewrite of the real on-disk cluster file; write-tmp-then-rename needs direct file access
        f.write(cs.format() + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
