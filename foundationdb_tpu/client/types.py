"""Core KV / transaction wire types (ref: fdbclient/CommitTransaction.h,
fdbclient/FDBTypes.h).  MutationRef::Type values match the reference enum
(CommitTransaction.h:31) so traces and future wire formats stay comparable.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum
from typing import List, Optional, Tuple

from ..conflict.types import Range


class MutationType(IntEnum):
    # ref CommitTransaction.h:31 enum Type
    SET_VALUE = 0
    CLEAR_RANGE = 1
    ADD_VALUE = 2
    DEBUG_KEY_RANGE = 3
    DEBUG_KEY = 4
    NO_OP = 5
    AND = 6
    OR = 7
    XOR = 8
    APPEND_IF_FITS = 9
    AVAILABLE_FOR_REUSE = 10
    RESERVED_FOR_LOG_PROTOCOL_MESSAGE = 11
    MAX = 12
    MIN = 13
    SET_VERSIONSTAMPED_KEY = 14
    SET_VERSIONSTAMPED_VALUE = 15
    BYTE_MIN = 16
    BYTE_MAX = 17
    MIN_V2 = 18
    AND_V2 = 19


ATOMIC_TYPES = frozenset(
    {
        MutationType.ADD_VALUE,
        MutationType.AND,
        MutationType.OR,
        MutationType.XOR,
        MutationType.APPEND_IF_FITS,
        MutationType.MAX,
        MutationType.MIN,
        MutationType.SET_VERSIONSTAMPED_KEY,
        MutationType.SET_VERSIONSTAMPED_VALUE,
        MutationType.BYTE_MIN,
        MutationType.BYTE_MAX,
        MutationType.MIN_V2,
        MutationType.AND_V2,
    }
)


@dataclass
class Mutation:
    """Ref: MutationRef CommitTransaction.h:29 (type, param1, param2)."""

    type: MutationType
    param1: bytes  # key (or range begin for CLEAR_RANGE)
    param2: bytes  # value (or range end for CLEAR_RANGE)


@dataclass
class CommitTransactionRef:
    """THE wire unit of a commit (ref: CommitTransaction.h:89-104)."""

    read_snapshot: int = 0
    read_conflict_ranges: List[Range] = field(default_factory=list)
    write_conflict_ranges: List[Range] = field(default_factory=list)
    mutations: List[Mutation] = field(default_factory=list)


# Key-space constants (ref: fdbclient/FDBTypes.h allKeys / systemKeys)
ALL_KEYS: Range = (b"", b"\xff")
SYSTEM_KEY_BEGIN = b"\xff"
MAX_KEY = b"\xff\xff"


def strinc(key: bytes) -> bytes:
    """First key not prefixed by `key` (ref: strinc in fdbclient)."""
    k = key.rstrip(b"\xff")
    if not k:
        raise ValueError("key must contain a byte != 0xff")
    return k[:-1] + bytes([k[-1] + 1])


def key_after(key: bytes) -> bytes:
    """Immediate successor key (ref: keyAfter)."""
    return key + b"\x00"


@dataclass
class KeyValue:
    key: bytes
    value: bytes


@dataclass
class KeySelector:
    """Ref: KeySelectorRef FDBTypes.h — resolve relative to a key.

    Resolves to: the (offset)th key at-or-after `key` if or_equal else
    strictly-after/before per the standard fdb definition.
    """

    key: bytes
    or_equal: bool = False
    offset: int = 1

    @classmethod
    def last_less_than(cls, key: bytes) -> "KeySelector":
        return cls(key, False, 0)

    @classmethod
    def last_less_or_equal(cls, key: bytes) -> "KeySelector":
        return cls(key, True, 0)

    @classmethod
    def first_greater_than(cls, key: bytes) -> "KeySelector":
        return cls(key, True, 1)

    @classmethod
    def first_greater_or_equal(cls, key: bytes) -> "KeySelector":
        return cls(key, False, 1)
