"""fdblint: AST-based determinism & actor-hygiene analyzer.

The reference's actor compiler is not just a code generator — it is a static
gate: every ``.actor.cpp`` file is rewritten and patterns that would break
replayable simulation are rejected at build time.  The Python rebuild has no
compile step, so this analyzer fills the role: it walks the package's ASTs
and rejects constructs that silently destroy the one property the whole test
strategy rests on — that a simulation run is bit-reproducible from its seed
(SURVEY.md §5; README "Determinism").

Rules
-----
DET001  wall-clock read (``time.time``/``monotonic``/``perf_counter``/
        ``sleep``, ``datetime.now``, ...) in simulator-executed code.  Use
        ``loop.now()`` / ``loop.delay()``: virtual time is the only clock
        actors may observe (ref: INetwork::now, flow/network.h).
DET002  global entropy (the ``random`` module, ``os.urandom``,
        ``uuid.uuid4``, ``secrets``) in simulator-executed code.  Use the
        loop's ``DeterministicRandom`` (``flow/rng.py``), the analog of
        g_random (flow/DeterministicRandom.h): every random decision must
        replay from the seed.
DET003  ``threading`` / ``asyncio`` / ``multiprocessing`` primitives in
        simulator-executed code.  The simulator is one cooperative thread
        (the reference's one-network-thread rule); OS-scheduled concurrency
        makes event order irreproducible.
ACT001  actor-coroutine call whose result is neither awaited nor handed to
        a spawn API: the statement ``self._run()`` creates a coroutine
        object and drops it — the actor never executes (the analog of
        discarding an ``ACTOR`` Future, which the actor compiler makes
        impossible to do silently).
JAX001  host synchronization or Python side effects (``.item()``,
        ``.tolist()``, ``float()``/``int()``/``bool()``, ``print``, host
        ``numpy`` calls, ``global`` mutation) inside a ``@jax.jit``-traced
        function.  These either fail at trace time, silently bake a traced
        value into the compiled graph, or force a device sync per call.
IO001   direct ``open()`` / ``socket`` use outside the real backends
        (``fileio/realfile.py``, ``fileio/blobstore.py``,
        ``rpc/real_network.py``, ``tools/``).  Simulated code does I/O
        through ``SimFileSystem`` / ``SimNetwork`` so faults are injectable
        and replayable.
TRC001  a ``TraceEvent(...)`` built as a bare statement but never
        ``.log()``ed and not used as a context manager: unlike the
        reference (destructor emit, flow/Trace.h), the rebuild emits only
        on ``.log()`` / ``with`` exit, so the event silently never reaches
        the collector — the trace-layer mirror of ACT001's dropped future.
        Statement-level like ACT001: ``ev = TraceEvent(...)`` held in a
        variable is assumed to be logged later by the holder.
ERR001  a broad ``except`` (bare, ``Exception``, or ``BaseException``)
        whose handler neither re-raises, nor TraceEvents, nor propagates
        the error (``send_error``/using the bound exception).  Silent
        swallowing is how degraded modes go unnoticed: the reference
        routes every unexpected error through ``Error``/TraceEvent, and
        the device-fault work (conflict/device_faults.py) depends on
        faults SURFACING so the breaker can count and route them.  The
        pragma goes on the ``except`` line itself.
PRG001  a ``# fdblint: ignore[...]`` pragma with no reason string.  Every
        suppression must say *why* the rule does not apply.
PRG002  a pragma that suppresses nothing (stale after a refactor).

Suppression
-----------
Same-line pragma, reason mandatory::

    self.t = time.monotonic()  # fdblint: ignore[DET001]: real-mode token bucket; sim leaves rate=None

Whole modules that are real-deployment components by identity (the real
network backend, operational tools) are exempted per-rule in the allowlist
config instead of pragma-spam; see DEFAULT_ALLOW below and ``--config``.

CLI
---
``python -m foundationdb_tpu.tools.fdblint [path ...] [--format=text|json]
[--config FILE] [--list-rules]``; exit 0 iff no unsuppressed findings.
``tests/test_lint.py`` runs this over the whole package as a tier-1 gate.
"""

from __future__ import annotations

import argparse
import ast
import fnmatch
import io
import json
import os
import re
import sys
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

RULES: Dict[str, str] = {
    "DET001": "wall-clock read in simulator-executed code (use loop.now())",
    "DET002": "global entropy source (use the loop's DeterministicRandom, flow/rng.py)",
    "DET003": "threading/asyncio/multiprocessing primitive in simulator-executed code",
    "ACT001": "actor coroutine called but neither awaited nor spawned (dropped future)",
    "JAX001": "host sync or Python side effect inside a jit-traced function",
    "IO001": "direct open()/socket outside the real I/O backends",
    "TRC001": "TraceEvent constructed but never .log()ed nor used as a context manager (dropped event)",
    "ERR001": "broad except that neither re-raises, TraceEvents, nor propagates the error (silent swallow)",
    "PRG001": "fdblint ignore pragma carries no reason string",
    "PRG002": "fdblint ignore pragma suppresses nothing (stale)",
}

# Canonical dotted names considered wall-clock reads.  Referencing one as a
# value (e.g. ``clock = time.monotonic``) is flagged like calling it: binding
# the function is how wall time gets smuggled past a call-site-only check.
WALL_CLOCK = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
    "time.sleep",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# Entropy: exact names plus whole-module prefixes.
ENTROPY_EXACT = {"os.urandom", "uuid.uuid1", "uuid.uuid4"}
ENTROPY_MODULES = {"random", "secrets"}

THREADING_MODULES = {
    "threading", "_thread", "asyncio", "multiprocessing", "concurrent.futures",
}

IO_CALLS = {"open", "os.open", "os.fdopen", "io.open"}
IO_MODULES = {"socket", "ssl"}

# Modules where JAX001 applies (the jit-traced surface of the repo).
TRACED_MODULE_GLOBS = ("conflict/engine_jax.py", "ops/*.py", "parallel/*.py")

# Attribute calls that force a device->host sync inside a trace.
JAX_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# Builtins that concretize a traced value (or are pure side effects).
JAX_BAD_BUILTINS = {"print", "breakpoint", "input", "float", "int", "bool"}

# Per-rule allowlist: package-relative posix globs for modules that are
# real-deployment components by identity, where the rule does not apply.
# The IO001 set mirrors the rule text: fileio/ real backends +
# rpc/real_network.py; tools/ are operational programs (fdbcli, fdbmonitor,
# real_node) that never run under the simulator.
DEFAULT_ALLOW: Dict[str, Tuple[str, ...]] = {
    "DET001": (
        "rpc/real_network.py",   # wall-anchored loop driver IS its purpose
        "tools/*.py",            # operational programs (fdbcli/fdbmonitor/
        #                          real_node analogs) never run under sim
        "utils/procutil.py",     # OS process plumbing
    ),
    "DET002": (),
    "DET003": (
        "rpc/real_network.py",
        "fileio/blobstore.py",   # threaded blocking-socket client/server
        "fileio/realfile.py",
        "flow/profiler.py",      # sampling thread = the SIGPROF analog
        "tools/*.py",
        "utils/procutil.py",
    ),
    "ACT001": (),
    "JAX001": (),
    "TRC001": (),
    "ERR001": (
        "rpc/real_network.py",   # teardown paths on real sockets: close()
        #                          best-effort by design
        "tools/*.py",            # operational programs, not sim-executed
        "utils/procutil.py",     # post-fork/pre-exec: may not even print
    ),
    "IO001": (
        "fileio/realfile.py",
        "fileio/blobstore.py",
        "rpc/real_network.py",
        "tools/*.py",
        "utils/procutil.py",
    ),
}

# The linter's own modules are not simulator-executed.
SKIP_MODULE_GLOBS = ("tools/fdblint.py",)


def _match_any(relpath: str, globs) -> bool:
    """Glob match against the relpath or any of its trailing sub-paths, so
    'rpc/real_network.py' matches whether the scan root was the package dir
    (relpath 'rpc/real_network.py') or an ancestor (relpath
    'foundationdb_tpu/rpc/real_network.py', the single-file CLI mode)."""
    parts = relpath.split("/")
    tails = ["/".join(parts[i:]) for i in range(len(parts))]
    return any(fnmatch.fnmatch(t, g) for t in tails for g in globs)


@dataclass
class Finding:
    rule: str
    path: str          # package-relative posix path
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str = ""   # pragma reason when suppressed
    end_line: int = 0  # last physical line of the flagged node (pragma scope)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
            "suppressed": self.suppressed, "reason": self.reason,
        }


@dataclass
class LintConfig:
    allow: Dict[str, Tuple[str, ...]] = field(
        default_factory=lambda: {k: tuple(v) for k, v in DEFAULT_ALLOW.items()}
    )

    @classmethod
    def load(cls, path: str, use_defaults: bool = True) -> "LintConfig":
        """JSON config {"allow": {"RULE": ["glob", ...]}}, merged over (or
        replacing, with use_defaults=False) the built-in allowlist."""
        with open(path, "r", encoding="utf-8") as f:  # fdblint: ignore[IO001]: linter config read; the linter never runs under the simulator
            raw = json.load(f)
        base: Dict[str, Tuple[str, ...]] = (
            {k: tuple(v) for k, v in DEFAULT_ALLOW.items()} if use_defaults else {}
        )
        for rule, globs in raw.get("allow", {}).items():
            if rule not in RULES:
                raise ValueError(f"config allowlists unknown rule {rule!r}")
            base[rule] = tuple(base.get(rule, ())) + tuple(globs)
        return cls(allow=base)

    def allows(self, rule: str, relpath: str) -> bool:
        return _match_any(relpath, self.allow.get(rule, ()))


# ---------------------------------------------------------------------------
# Pragmas
# ---------------------------------------------------------------------------

_PRAGMA_RE = re.compile(
    r"#\s*fdblint:\s*ignore\[(?P<rules>[A-Z0-9,\s]+)\](?:\s*:\s*(?P<reason>.*\S))?"
)


@dataclass
class Pragma:
    line: int
    rules: Set[str]
    reason: str
    used: bool = False


def parse_pragmas(source: str) -> Dict[int, Pragma]:
    """Pragmas from REAL comment tokens only: a pragma example quoted in a
    docstring or string literal must not register (it would then be
    reported as stale PRG002 with no way to appease it)."""
    pragmas: Dict[int, Pragma] = {}
    for tok in tokenize.generate_tokens(io.StringIO(source).readline):
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if not m:
            continue
        line = tok.start[0]
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        pragmas[line] = Pragma(line, rules, (m.group("reason") or "").strip())
    return pragmas


# ---------------------------------------------------------------------------
# Symbol resolution: map names/attribute chains to canonical dotted paths
# ---------------------------------------------------------------------------


class _Aliases:
    """Tracks module-level import bindings so ``t.monotonic`` resolves to
    ``time.monotonic`` regardless of aliasing.  Function-local imports are
    folded into the same table — a rename collision between scopes could in
    principle misattribute, which for a linter errs on the loud side."""

    def __init__(self):
        self.map: Dict[str, str] = {}

    def add_import(self, node: ast.Import):
        for a in node.names:
            self.map[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )

    def add_import_from(self, node: ast.ImportFrom):
        if node.module is None or node.level:
            return  # relative import: package-internal, never a stdlib clock
        for a in node.names:
            if a.name == "*":
                continue
            self.map[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted canonical path for a Name/Attribute chain, or None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.map.get(node.id, node.id)
        return ".".join([root] + list(reversed(parts)))

    def root_bound(self, node: ast.AST) -> bool:
        """True iff the chain's root name is an import binding.  A local
        variable that merely *shares* a module name (e.g. a parameter
        named `random` holding a DeterministicRandom — this repo's core
        idiom) must not light up module-prefix rules."""
        while isinstance(node, ast.Attribute):
            node = node.value
        return isinstance(node, ast.Name) and node.id in self.map


# ---------------------------------------------------------------------------
# The analyzer
# ---------------------------------------------------------------------------


class ModuleLinter(ast.NodeVisitor):
    def __init__(self, relpath: str, tree: ast.Module, config: LintConfig):
        self.relpath = relpath
        self.tree = tree
        self.config = config
        self.aliases = _Aliases()
        self.findings: List[Finding] = []
        # ACT001 name scoping: a bare `foo()` statement only matches module-
        # level async functions; `self.foo()` / `cls.foo()` only async
        # methods of the ENCLOSING class (per-class spans below).  Matching
        # any attribute call by name alone drowns real bugs in collisions
        # with generic names (`set`, `sync`) on unrelated objects, and a
        # module-wide method set would still cross-fire between classes.
        self.async_funcs: Set[str] = set()
        # (class start line, class end line, async method names) per class
        self.class_spans: List[Tuple[int, int, Set[str]]] = []
        self.traced = _match_any(relpath, TRACED_MODULE_GLOBS)
        # Simple-statement line spans: a pragma anywhere on the physical
        # lines of the statement containing a flagged expression counts
        # (multi-line expressions put the node's lineno above the spot
        # where a trailing comment can live).
        self.stmt_spans: List[Tuple[int, int]] = []
        # Names of functions that are jit-traced (decorated, jax.jit(f),
        # partial(jax.jit, ...)(f), or handed to shard_map).
        self.jitted_names: Set[str] = set()
        # Line spans of jitted function bodies (incl. nested defs).
        self.jitted_spans: List[Tuple[int, int]] = []

    # -- emit --
    _SIMPLE_STMTS = (
        ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr, ast.Return,
        ast.Import, ast.ImportFrom, ast.Raise, ast.Assert, ast.Delete,
        ast.Global, ast.Nonlocal,
    )

    def flag(self, rule: str, node: ast.AST, message: str,
             end_line: Optional[int] = None):
        if self.config.allows(rule, self.relpath):
            return
        if end_line is not None:
            # Caller pinned the pragma scope (ERR001: the `except` line
            # only — its node span covers the whole handler body, which
            # must not become one giant suppression region).
            end = end_line
        else:
            # Pragma scope: through the end of the innermost SIMPLE
            # statement containing the node (never a compound statement —
            # a def/if body must not become one giant suppression
            # region).  Falls back to the node's own span for nodes
            # outside any simple statement (decorators, if/while tests).
            end = getattr(node, "end_lineno", None) or node.lineno
            best = None
            for s, e in self.stmt_spans:
                if s <= node.lineno <= e:
                    if best is None or s > best[0] or (s == best[0] and e < best[1]):
                        best = (s, e)
            if best is not None:
                end = max(end, best[1])
        self.findings.append(
            Finding(rule, self.relpath, node.lineno, node.col_offset, message,
                    end_line=end)
        )

    # -- prepass: aliases, async defs, jitted functions --
    def prepass(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                self.aliases.add_import(node)
            elif isinstance(node, ast.ImportFrom):
                self.aliases.add_import_from(node)
            if isinstance(node, self._SIMPLE_STMTS):
                self.stmt_spans.append(
                    (node.lineno, node.end_lineno or node.lineno)
                )
        self._collect_async_defs(self.tree, in_class=False)
        if self.traced:
            self._collect_jitted()

    def _collect_async_defs(self, node: ast.AST, in_class: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.AsyncFunctionDef):
                if not in_class:
                    self.async_funcs.add(child.name)
                self._collect_async_defs(child, in_class=False)
            elif isinstance(child, ast.ClassDef):
                names = {
                    m.name for m in child.body
                    if isinstance(m, ast.AsyncFunctionDef)
                }
                self.class_spans.append(
                    (child.lineno, child.end_lineno or child.lineno, names)
                )
                self._collect_async_defs(child, in_class=True)
            else:
                self._collect_async_defs(child, in_class=in_class)

    def _enclosing_class_async_methods(self, lineno: int) -> Set[str]:
        """Async method names of the innermost class containing lineno."""
        best = None
        for start, end, names in self.class_spans:
            if start <= lineno <= end and (best is None or start > best[0]):
                best = (start, names)
        return best[1] if best else set()

    def _is_jit(self, node: ast.AST) -> bool:
        path = self.aliases.resolve(node)
        return path is not None and (path == "jit" or path.endswith(".jit"))

    def _jit_target_name(self, call: ast.Call) -> Optional[str]:
        """Name of the function a jit/shard_map call wraps, unwrapping one
        level of functools.partial around the target."""
        if not call.args:
            return None
        target = call.args[0]
        if isinstance(target, ast.Call):
            fn = self.aliases.resolve(target.func)
            if fn in ("partial", "functools.partial") and target.args:
                target = target.args[0]
        if isinstance(target, ast.Name):
            return target.id
        return None

    def _collect_jitted(self):
        for node in ast.walk(self.tree):
            # @jit / @jax.jit / @partial(jax.jit, ...)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jit(dec):
                        self.jitted_names.add(node.name)
                    elif isinstance(dec, ast.Call):
                        fn = self.aliases.resolve(dec.func)
                        if self._is_jit(dec.func) or (
                            fn in ("partial", "functools.partial")
                            and dec.args
                            and self._is_jit(dec.args[0])
                        ):
                            self.jitted_names.add(node.name)
            elif isinstance(node, ast.Call):
                fn_path = self.aliases.resolve(node.func)
                # jax.jit(step, ...) / shard_map(body, ...)
                if self._is_jit(node.func) or (
                    fn_path is not None
                    and (fn_path == "shard_map" or fn_path.endswith(".shard_map"))
                ):
                    name = self._jit_target_name(node)
                    if name:
                        self.jitted_names.add(name)
                # partial(jax.jit, ...)(detect_core)
                elif (
                    isinstance(node.func, ast.Call)
                    and self.aliases.resolve(node.func.func)
                    in ("partial", "functools.partial")
                    and node.func.args
                    and self._is_jit(node.func.args[0])
                ):
                    name = self._jit_target_name(node)
                    if name:
                        self.jitted_names.add(name)
        # Body spans: a def whose name is jitted, anywhere in the module
        # (nested defs inside a jitted body fall inside its span).
        for node in ast.walk(self.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in self.jitted_names
            ):
                self.jitted_spans.append((node.lineno, node.end_lineno or node.lineno))

    def _in_jitted(self, node: ast.AST) -> bool:
        ln = getattr(node, "lineno", None)
        return ln is not None and any(a <= ln <= b for a, b in self.jitted_spans)

    # -- visitors --
    def visit_Import(self, node: ast.Import):
        for a in node.names:
            top = a.name.split(".")[0]
            full = a.name
            if top in ENTROPY_MODULES:
                self.flag("DET002", node, f"import of entropy module '{a.name}'")
            if top in THREADING_MODULES or full in THREADING_MODULES:
                self.flag("DET003", node, f"import of '{a.name}'")
            if top in IO_MODULES:
                self.flag("IO001", node, f"import of '{a.name}'")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module is not None and not node.level:
            top = node.module.split(".")[0]
            if top in ENTROPY_MODULES:
                self.flag("DET002", node, f"import from entropy module '{node.module}'")
            if top in THREADING_MODULES or node.module in THREADING_MODULES:
                self.flag("DET003", node, f"import from '{node.module}'")
            if top in IO_MODULES:
                self.flag("IO001", node, f"import from '{node.module}'")
            for a in node.names:
                if f"{node.module}.{a.name}" in WALL_CLOCK:
                    self.flag(
                        "DET001", node,
                        f"import of wall-clock '{node.module}.{a.name}'",
                    )
        self.generic_visit(node)

    def _check_path_reference(self, node: ast.AST, path: str):
        if path in WALL_CLOCK:
            self.flag("DET001", node, f"wall-clock '{path}'")
        elif path in ENTROPY_EXACT or path.split(".")[0] in ENTROPY_MODULES:
            self.flag("DET002", node, f"entropy source '{path}'")

    def visit_Attribute(self, node: ast.Attribute):
        # Attribute *references* (called or not) to wall clocks / entropy —
        # only chains rooted at an actual import binding (see root_bound).
        path = self.aliases.resolve(node)
        if path is not None:
            # Pure Name/Attribute chain: check it once, don't recurse
            # (recursing would re-report each prefix of a.b.c).
            if self.aliases.root_bound(node):
                self._check_path_reference(node, path)
        else:
            # Chain contains calls/subscripts — keep walking to reach them.
            self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        # A bare name bound by `from time import monotonic` style imports.
        path = self.aliases.resolve(node)
        if path is not None and path != node.id and self.aliases.root_bound(node):
            self._check_path_reference(node, path)

    def visit_Call(self, node: ast.Call):
        path = self.aliases.resolve(node.func)
        if path is not None and path in IO_CALLS and (
            path == "open" or self.aliases.root_bound(node.func)
        ):
            self.flag("IO001", node, f"direct '{path}()' call")
        if self._in_jitted(node):
            self._check_jax_call(node, path)
        self.generic_visit(node)

    def _check_jax_call(self, node: ast.Call, path: Optional[str]):
        if isinstance(node.func, ast.Name) and node.func.id in JAX_BAD_BUILTINS:
            self.flag(
                "JAX001", node,
                f"'{node.func.id}()' inside a jit-traced function "
                f"(host sync / trace-time side effect)",
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in JAX_SYNC_METHODS
        ):
            self.flag(
                "JAX001", node,
                f"'.{node.func.attr}()' forces device sync inside a "
                f"jit-traced function",
            )
        elif (
            path is not None
            and path.split(".")[0] in ("numpy", "np")
            and self.aliases.root_bound(node.func)
        ):
            self.flag(
                "JAX001", node,
                f"host numpy call '{path}' inside a jit-traced function",
            )

    # -- ERR001: silent broad excepts --
    _BROAD_EXC = {"Exception", "BaseException",
                  "builtins.Exception", "builtins.BaseException"}

    def _is_broad_except(self, t: Optional[ast.AST]) -> bool:
        if t is None:
            return True  # bare `except:`
        if isinstance(t, ast.Tuple):
            return any(self._is_broad_except(e) for e in t.elts)
        return self.aliases.resolve(t) in self._BROAD_EXC

    def _handler_surfaces_error(self, node: ast.excepthandler) -> bool:
        """True when the handler visibly deals with the error: re-raises
        (anywhere in its body, incl. nested cleanup), TraceEvents it,
        forwards it via send_error, or reads the bound exception name
        (passing it on IS handling; what ERR001 hunts is the error
        vanishing without a trace)."""
        for stmt in node.body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Raise):
                    return True
                if (
                    node.name
                    and isinstance(n, ast.Name)
                    and n.id == node.name
                ):
                    return True
                if isinstance(n, ast.Call):
                    if (
                        isinstance(n.func, ast.Attribute)
                        and n.func.attr == "send_error"
                    ):
                        return True
                    path = self.aliases.resolve(n.func)
                    if path is not None and path.split(".")[-1] == "TraceEvent":
                        return True
        return False

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if self._is_broad_except(node.type) and not self._handler_surfaces_error(node):
            caught = "except:" if node.type is None else (
                f"except {self.aliases.resolve(node.type) or '...'}"
            )
            self.flag(
                "ERR001", node,
                f"'{caught}' swallows errors silently "
                f"(re-raise, TraceEvent, or propagate the error)",
                end_line=node.lineno,
            )
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global):
        if self._in_jitted(node):
            self.flag(
                "JAX001", node,
                f"global mutation of {', '.join(node.names)} inside a "
                f"jit-traced function",
            )
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr):
        # ACT001: statement-level call of a module-local async def whose
        # coroutine object is dropped on the floor.
        v = node.value
        if isinstance(v, ast.Call):
            dropped = None
            if isinstance(v.func, ast.Name) and v.func.id in self.async_funcs:
                dropped = v.func.id
            elif (
                isinstance(v.func, ast.Attribute)
                and isinstance(v.func.value, ast.Name)
                and v.func.value.id in ("self", "cls")
                and v.func.attr
                in self._enclosing_class_async_methods(node.lineno)
            ):
                dropped = v.func.attr
            if dropped is not None:
                self.flag(
                    "ACT001", node,
                    f"coroutine '{dropped}()' is neither awaited nor spawned "
                    f"(dropped actor)",
                )
            self._check_dropped_trace_event(node, v)
        self.generic_visit(node)

    def _check_dropped_trace_event(self, stmt: ast.Expr, call: ast.Call):
        """TRC001: a statement-level TraceEvent(...) builder chain whose
        outermost call is not .log() — the event is constructed, detailed,
        and dropped (the rebuild has no destructor emit)."""
        methods: List[str] = []
        c: ast.AST = call
        while isinstance(c, ast.Call):
            # The root constructor call: its func is a pure Name/Attribute
            # chain resolving to TraceEvent (bare, aliased, or module-
            # qualified); builder methods between it and the statement are
            # Attribute hops over inner Calls, collected in `methods`.
            path = self.aliases.resolve(c.func)
            if path is not None and path.split(".")[-1] == "TraceEvent":
                if "log" not in methods:
                    self.flag(
                        "TRC001", stmt,
                        "TraceEvent built but never .log()ed nor used as "
                        "a context manager (dropped event)",
                    )
                return
            if not isinstance(c.func, ast.Attribute):
                return
            methods.append(c.func.attr)
            c = c.func.value

    def run(self) -> List[Finding]:
        self.prepass()
        self.visit(self.tree)
        return self.findings


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def lint_source(
    source: str, relpath: str, config: Optional[LintConfig] = None
) -> List[Finding]:
    """Lint one module's source; findings suppressed by same-line pragmas
    are returned with suppressed=True.  PRG001/PRG002 police the pragmas
    themselves and are never suppressible."""
    config = config or LintConfig()
    if _match_any(relpath, SKIP_MODULE_GLOBS):
        return []
    tree = ast.parse(source, filename=relpath)
    findings = ModuleLinter(relpath, tree, config).run()
    pragmas = parse_pragmas(source)
    out: List[Finding] = []
    for f in findings:
        # A pragma anywhere on the flagged statement's physical lines
        # suppresses it (a multi-line expression puts the node's lineno on
        # a different line than the trailing comment).
        for ln in range(f.line, max(f.end_line, f.line) + 1):
            p = pragmas.get(ln)
            if p is not None and f.rule in p.rules:
                p.used = True
                f.suppressed = True
                f.reason = p.reason
                break
        out.append(f)
    for p in pragmas.values():
        unknown = p.rules - set(RULES)
        if unknown:
            out.append(Finding(
                "PRG002", relpath, p.line, 0,
                f"pragma names unknown rule(s) {sorted(unknown)}",
            ))
        if not p.reason:
            out.append(Finding(
                "PRG001", relpath, p.line, 0,
                "ignore pragma carries no reason (append ': why')",
            ))
        if not p.used and not unknown:
            out.append(Finding(
                "PRG002", relpath, p.line, 0,
                f"pragma for {sorted(p.rules)} suppresses nothing here",
            ))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_file(
    path: str, root: str, config: Optional[LintConfig] = None
) -> List[Finding]:
    relpath = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, "r", encoding="utf-8") as f:  # fdblint: ignore[IO001]: the linter reads the sources it checks; never simulator-executed
        source = f.read()
    return lint_source(source, relpath, config)


def iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_package(
    root: str, config: Optional[LintConfig] = None
) -> List[Finding]:
    """Lint every .py under root (root is the package directory; paths in
    findings are relative to it).  A single .py file is reported relative
    to its outermost enclosing package, so that allowlist / traced-module
    globs like 'rpc/real_network.py' keep matching (via _match_any's
    trailing-sub-path semantics) in single-file mode."""
    findings: List[Finding] = []
    if os.path.isfile(root):
        base = os.path.dirname(os.path.abspath(root))
        while os.path.exists(os.path.join(base, "__init__.py")):
            base = os.path.dirname(base)
        return lint_file(root, base, config)
    for path in iter_py_files(root):
        findings.extend(lint_file(path, root, config))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdblint",
        description="AST-based determinism & actor-hygiene analyzer "
                    "(the actor compiler's static-gate role).",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="package dirs or .py files (default: foundationdb_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--config", help="JSON allowlist config to merge over defaults")
    ap.add_argument("--no-default-config", action="store_true",
                    help="ignore the built-in allowlist")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print pragma-suppressed findings")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in RULES.items():
            print(f"{rule}  {desc}")
        return 0

    if args.config:
        config = LintConfig.load(args.config, use_defaults=not args.no_default_config)
    elif args.no_default_config:
        config = LintConfig(allow={})
    else:
        config = LintConfig()

    paths = args.paths or [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    findings: List[Finding] = []
    for p in paths:
        findings.extend(lint_package(p, config))

    unsuppressed = [f for f in findings if not f.suppressed]
    shown = findings if args.show_suppressed else unsuppressed
    if args.format == "json":
        print(json.dumps(
            {
                "findings": [f.to_dict() for f in shown],
                "total": len(findings),
                "unsuppressed": len(unsuppressed),
            },
            indent=2,
        ))
    else:
        for f in shown:
            tag = " (suppressed: %s)" % f.reason if f.suppressed else ""
            print(f.format() + tag)
        n_sup = len(findings) - len(unsuppressed)
        print(
            f"fdblint: {len(unsuppressed)} finding(s), {n_sup} suppressed",
            file=sys.stderr,
        )
    return 1 if unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
