"""fdblint CLI shim — the analyzer now lives in the lint/ package.

Grown in ISSUE 5 from an 853-line single module into a multi-pass
analysis package (``foundationdb_tpu/tools/lint/``): project loader with
a per-file AST cache, module-graph + call-graph builder, and per-rule
passes (the WAIT state-across-await rules, interprocedural DET101 taint,
RPY001 reply-promise paths, ENV001 env-flag drift — on top of the
original DET/ACT/JAX/IO/TRC/ERR families).  This module re-exports the
public API verbatim so the existing gate (``pytest -m lint``), pragma
syntax, allowlist config and ``python -m foundationdb_tpu.tools.fdblint``
entry point all keep working.  See ``lint/__init__.py`` for the layout
and README "Determinism rules" for the rule table."""

if __package__ in (None, ""):
    # Script mode (`python path/to/fdblint.py`): there is no parent
    # package for the relative import below — bootstrap the repo root
    # and re-dispatch as if `-m foundationdb_tpu.tools.fdblint` ran.
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    import foundationdb_tpu.tools  # noqa: F401  (parent for the relative import)

    __package__ = "foundationdb_tpu.tools"

from .lint import (  # noqa: F401
    DEFAULT_ALLOW,
    Finding,
    LintConfig,
    Pragma,
    Project,
    RULES,
    count_by_rule,
    default_cache_path,
    format_counts,
    iter_py_files,
    lint_file,
    lint_package,
    lint_source,
    main,
    parse_pragmas,
    to_sarif,
)

if __name__ == "__main__":
    import sys

    sys.exit(main())
