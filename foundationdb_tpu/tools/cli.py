"""fdbcli-equivalent interactive shell (ref: fdbcli/fdbcli.actor.cpp — the
command table :423-464: get/set/clear/clearrange/getrange/status/writemode,
transaction begin/commit/rollback).

The command processor is decoupled from I/O so tests drive it directly; the
__main__ entry runs a REPL against a fresh simulated cluster (attaching to
a real deployment reuses the same Database handle).
"""

from __future__ import annotations

import json
import shlex
from typing import List, Optional

from ..flow.error import FdbError
from ..server.status import cluster_status


def _fmt_key(b: bytes) -> str:
    return repr(b)[1:]  # b'x' -> 'x' repr without the b prefix


class CliProcessor:
    """One command in, list of output lines out."""

    HELP = {
        "get": "get <key> — read a value",
        "set": "set <key> <value> — write a key (writemode must be on)",
        "clear": "clear <key> — delete a key",
        "clearrange": "clearrange <begin> <end> — delete a key range",
        "getrange": "getrange <begin> [end] [limit] — read a range",
        "getrangekeys": "getrangekeys <begin> [end] [limit] — keys only",
        "status": "status [json | --format=json] — cluster status "
        "(json form includes the resolver/tpu telemetry section)",
        "metrics": "metrics [--diff] [--format=json] — metrics-registry "
        "snapshots (proxy/resolver counters, device kernel telemetry); "
        "--diff prints counter/histogram deltas since the previous "
        "metrics command instead of lifetime totals",
        "flightrec": "flightrec [--format=json] — flight-recorder "
        "captures (triggered black-box windows: time-series deltas, "
        "recent trace events, transition logs); text form lists the "
        "capture inventory, json dumps the artifacts",
        "mirror-check": "mirror-check [--format=json] — on-demand live "
        "diff of each resolver's CPU mirror snapshot against its device "
        "export (the consistency check the periodic resolver actor runs; "
        "confirmed divergence opens the circuit breaker)",
        "contention": "contention [--format=json] [--limit=N] — conflict "
        "provenance explorer: joins the per-abort witness records "
        "(conflicting write version + losing read range) with the "
        "resolver span rings and the decayed top-K into per-range "
        "abort timelines; lists contention-spike flight-recorder "
        "captures",
        "shards": "shards [--format=json] — shard-mesh explorer: split "
        "points, per-shard occupancy/boundary counts, breaker states, "
        "the balancer decision log, and the reshard move log in one "
        "canonical sorted-keys doc (byte-identical per seed)",
        "latency": "latency [--chains] [--format=json] — per-stage "
        "latency percentiles from the span layer (default); --chains "
        "uses the legacy trace_batch debug-id chain reassembly "
        "(in-memory collectors only — the trace-file-only input path)",
        "trace-export": "trace-export [--out=PATH] [--include-wall] — "
        "export the span layer as a Chrome trace-event / Perfetto JSON "
        "artifact (one track per role, pipeline batches as nested "
        "slices); byte-identical across same-seed runs unless "
        "--include-wall adds real-clock durations",
        "consistencycheck": "consistencycheck — compare every "
        "multi-replica shard across its team (fdbserver -r "
        "consistencycheck analog)",
        "writemode": "writemode <on|off> — allow writes",
        "begin": "begin — start an explicit transaction",
        "commit": "commit — commit the explicit transaction",
        "rollback": "rollback — abandon the explicit transaction",
        "watch": "watch <key> — report when the key changes",
        "configure": "configure <name>=<value> ... — change configuration "
        "(proxies=N, storage_team_size=N, ...)",
        "exclude": "exclude <storage_id> ... — mark storages for removal",
        "include": "include [<storage_id> ...] — clear exclusions "
        "(no args: all)",
        "coordinators": "coordinators [<address> ...] — change the "
        "coordinator quorum (odd count; no args: show requested)",
        "profile": "profile <on|off|report> [interval] — sampling CPU "
        "profiler runtime toggle",
        "lock": "lock — lock the database (non-lock-aware work fails)",
        "unlock": "unlock [uid] — release the database lock",
        "setclass": "setclass <address> <class> — recruitment class "
        "(stateless|transaction|storage|unset)",
        "backup": "backup <start|status|restore|describe|expire> <path> "
        "[version | --timestamp=T] — continuous backup driver "
        "(fdbbackup analog)",
        "soak": "soak — the chaos-soak harness runs its OWN rated "
        "cluster: invoke as `python -m foundationdb_tpu.tools.cli soak "
        "[--format=json] ...` (see --help for load/fault options)",
        "dr": "dr <start|status|switch> — replicate into the destination "
        "cluster; switch reverses the roles (fdbdr analog)",
        "help": "help — this text",
    }

    def __init__(self, cluster, db, dst_db=None, dst_cluster=None):
        self.cluster = cluster
        self.db = db
        # Destination database for `dr` commands (the fdbdr tool takes two
        # cluster files; the shell takes two database handles).  The
        # destination CLUSTER handle enables `dr switch` (the reverse
        # agent needs the destination's logs).
        self.dst_db = dst_db
        self.dst_cluster = dst_cluster
        self.write_mode = False
        self._tr = None  # explicit transaction, between begin/commit
        self._backups: dict = {}  # path -> ContinuousBackupAgent
        self._dr_agent = None

    async def run_command(self, line: str) -> List[str]:
        try:
            parts = shlex.split(line)
        except ValueError as e:
            return [f"ERROR: {e}"]
        if not parts:
            return []
        cmd, *args = parts
        # Hyphenated commands (mirror-check) map onto underscore handlers.
        handler = getattr(self, f"_cmd_{cmd.replace('-', '_')}", None)
        if handler is None:
            return [f"ERROR: unknown command `{cmd}'; type `help' for help"]
        try:
            return await handler(args)
        except FdbError as e:
            return [f"ERROR: {e.name} ({e.code})"]

    # -- transaction plumbing: implicit per-command or explicit begin/commit
    def _txn(self):
        return self._tr if self._tr is not None else self.db.create_transaction()

    async def _finish(self, tr) -> List[str]:
        if self._tr is None:
            await tr.commit()
        return []

    # -- commands --
    async def _cmd_help(self, args):
        return [self.HELP[k] for k in sorted(self.HELP)]

    async def _cmd_backup(self, args):
        """The fdbbackup driver (ref: fdbbackup's start/status/restore
        subcommands over FileBackupAgent), running the continuous agent."""
        if len(args) < 2:
            return ["ERROR: backup <start|status|restore> <path> [version]"]
        sub, path = args[0], args[1]
        from ..fileio import SimFileSystem
        from ..layers.backup import ContinuousBackupAgent, open_container

        if sub == "start":
            if path in self._backups:
                return [f"ERROR: backup to `{path}' already running"]
            fs = getattr(self.cluster, "fs", None) or SimFileSystem(
                self.cluster.net
            )
            try:
                # Scheme dispatch: blobstore:// targets the object store,
                # anything else the cluster filesystem.
                container = open_container(
                    path, fs, self.cluster.net.process(f"bk:{path}")
                )
            except ValueError as e:
                return [f"ERROR: {e}"]
            agent = ContinuousBackupAgent(
                self.db,
                fs,
                [t.interface() for t in self.cluster.tlogs],
                container,
                tag=f"_backup/{path}",
            )
            v = await agent.start()
            self.db.process.spawn(agent.run(), f"backup:{path}")
            self._backups[path] = agent
            return [f"Backup started to `{path}' at version {v}"]
        agent = self._backups.get(path)
        if sub == "status":
            if agent is None:
                return [f"No backup to `{path}'"]
            return [
                f"Backup `{path}': snapshot {agent.snapshot_version}, "
                f"logged through {agent.logged_through} "
                f"({agent._chunks} log chunks)"
            ]
        if sub == "restore":
            if agent is None:
                return [f"No backup to `{path}'"]
            return await self._backup_restore(agent, path, args)
        if sub == "describe":
            # Ref: fdbbackup describe.
            from ..layers.backup import describe_container

            container = (
                agent.container if agent is not None
                else open_container(
                    path,
                    getattr(self.cluster, "fs", None),
                    self.cluster.net.process(f"bk:{path}"),
                )
            )
            d = await describe_container(container)
            if not d.get("restorable"):
                return [f"`{path}': not restorable (no manifest)"]
            return [
                f"`{path}': restorable [{d['restorable_from']}, "
                f"{d['restorable_to']}], snapshot {d['version']} "
                f"({d['pages']} pages), log chunks "
                f"{d.get('first_log_chunk', 0)}..{d.get('log_chunks', 0)}"
            ]
        if sub == "expire":
            # Ref: fdbbackup expire — re-snapshot, then drop redundant
            # log chunks.
            if agent is None:
                return [f"No backup to `{path}'"]
            deleted = await agent.expire()
            return [
                f"Expired {deleted} log chunk(s); new snapshot at "
                f"{agent.snapshot_version}"
            ]
        return [f"ERROR: unknown backup subcommand `{sub}'"]

    async def _backup_restore(self, agent, path, args):
        # Resolve the target version FIRST — argument parsing and the
        # TimeKeeper mapping must not run with the agent paused (a
        # failure there would strand the backup stopped, and the resume
        # would race a tailer that never observed the pause).
        target = None
        if len(args) > 2:
            if args[2].startswith("--timestamp="):
                # Restore-to-timestamp via the TimeKeeper map (ref:
                # fdbbackup restore --timestamp,
                # timeKeeperVersionFromDatetime backup.actor.cpp:1828).
                from ..client.management import version_from_timestamp
                from ..flow.error import FdbError

                try:
                    ts = float(args[2].split("=", 1)[1])
                except ValueError:
                    return [f"ERROR: bad --timestamp value {args[2]!r}"]
                try:
                    target = await version_from_timestamp(self.db, ts)
                except FdbError as e:
                    if e.name != "restore_error":
                        raise  # unrelated failure: report truthfully
                    return ["ERROR: restore_error: no TimeKeeper sample "
                            "covers that time"]
            else:
                try:
                    target = int(args[2])
                except ValueError:
                    return [f"ERROR: bad version {args[2]!r}"]
        # Pause tailing for the restore, then RESUME it — the backup
        # stays live afterwards (the restore's own writes are logged
        # like any other mutations).
        agent.stopped = True
        try:
            v = await agent.restore(target_version=target)
        finally:
            agent.stopped = False
            self.db.process.spawn(agent.run(), f"backup:{path}")
        return [f"Restored `{path}' at version {v}; backup resumed"]

    async def _cmd_dr(self, args):
        """The fdbdr driver (ref: fdbbackup/fdbdr's start/status over
        DatabaseBackupAgent): continuous replication into the destination
        database this shell was constructed with."""
        if not args:
            return ["ERROR: dr <start|status>"]
        if self.dst_db is None:
            return ["ERROR: no destination cluster configured"]
        sub = args[0]
        if sub == "start":
            if self._dr_agent is not None:
                return ["ERROR: DR already running"]
            from ..layers.dr import DRAgent

            agent = DRAgent(
                self.db,
                self.dst_db,
                [t.interface() for t in self.cluster.tlogs],
            )
            v = await agent.start()
            self.db.process.spawn(agent.run(), "dr_agent")
            self._dr_agent = agent
            return [f"DR started; initial snapshot at version {v}"]
        if sub == "status":
            if self._dr_agent is None:
                return ["DR: not running"]
            return [
                f"DR: tailing, destination reflects source version "
                f"{self._dr_agent.applied}"
            ]
        if sub == "switch":
            # Ref: fdbdr switch -> atomicSwitchover.
            if self._dr_agent is None:
                return ["ERROR: no DR running to switch"]
            if self.dst_cluster is None:
                return ["ERROR: switch needs the destination cluster handle"]
            try:
                rev = await self._dr_agent.switchover(
                    [t.interface() for t in self.dst_cluster.tlogs]
                )
            except FdbError as e:
                # switchover unwound its locks; resume forward replication.
                self.db.process.spawn(self._dr_agent.run(), "dr_agent")
                return [f"ERROR: switch failed ({e.name}); DR resumed"]
            self.db.process.spawn(rev.run(), "dr_agent_rev")
            self._dr_agent = rev
            return [
                "Switched: destination is now primary; old primary locked "
                "as its replica"
            ]
        return [f"ERROR: unknown dr subcommand `{sub}'"]

    async def _cmd_get(self, args):
        (key,) = args
        tr = self._txn()
        v = await tr.get(key.encode())
        await self._finish(tr)
        if v is None:
            return [f"`{key}': not found"]
        return [f"`{key}' is `{v.decode(errors='replace')}'"]

    async def _cmd_set(self, args):
        if not self.write_mode:
            return ["ERROR: writemode must be enabled (writemode on)"]
        key, value = args
        tr = self._txn()
        tr.set(key.encode(), value.encode())
        await self._finish(tr)
        return ["Committed" if self._tr is None else "Staged"]

    async def _cmd_clear(self, args):
        if not self.write_mode:
            return ["ERROR: writemode must be enabled (writemode on)"]
        (key,) = args
        tr = self._txn()
        tr.clear(key.encode())
        await self._finish(tr)
        return ["Committed" if self._tr is None else "Staged"]

    async def _cmd_clearrange(self, args):
        if not self.write_mode:
            return ["ERROR: writemode must be enabled (writemode on)"]
        begin, end = args
        tr = self._txn()
        tr.clear_range(begin.encode(), end.encode())
        await self._finish(tr)
        return ["Committed" if self._tr is None else "Staged"]

    async def _cmd_getrange(self, args, keys_only=False):
        begin = args[0].encode()
        end = args[1].encode() if len(args) > 1 else b"\xff"
        limit = int(args[2]) if len(args) > 2 else 25
        tr = self._txn()
        rows = await tr.get_range(begin, end, limit=limit)
        await self._finish(tr)
        out = [f"Range limited to {limit} keys"] if len(rows) >= limit else []
        for k, v in rows:
            if keys_only:
                out.append(f"`{_fmt_key(k)}'")
            else:
                out.append(f"`{_fmt_key(k)}' is `{v.decode(errors='replace')}'")
        return out

    async def _cmd_getrangekeys(self, args):
        return await self._cmd_getrange(args, keys_only=True)

    async def _cmd_writemode(self, args):
        (mode,) = args
        self.write_mode = mode == "on"
        return []

    async def _cmd_consistencycheck(self, args):
        """On-demand cross-replica comparison (ref: the ConsistencyCheck
        role, fdbserver.actor.cpp role list + workloads/
        ConsistencyCheck.actor.cpp checkDataConsistency :562): every
        multi-replica shard read at one version from every team member
        and compared byte-exact."""
        from ..workloads.consistency import check_consistency

        try:
            compared = await check_consistency(self.db, self.cluster)
        except AssertionError as e:
            return [f"INCONSISTENT: {e}"]
        if compared == 0:
            return ["OK (no multi-replica shards to compare)"]
        return [f"OK: {compared} replica comparisons matched"]

    async def _cmd_status(self, args):
        doc = cluster_status(self.cluster)
        if args and args[0] in ("json", "--format=json"):
            from ..flow.eventloop import timeout_after

            # The json form runs the ACTIVE probe like the reference's
            # clusterGetStatus (Status.actor.cpp latency_probe section) —
            # under a timeout: a throttled/recovering cluster (exactly
            # what status diagnoses) must not hang the command.
            loop = self.db.process.network.loop
            task = self.db.process.spawn(
                self._probe_swallowing(), "status_probe"
            )
            probe = await timeout_after(loop, task, 5.0, default=None)
            if probe is None:
                task.cancel()
                probe = {"error": "probe timed out"}
            doc["cluster"]["latency_probe"] = probe
            return json.dumps(doc, indent=2, default=str).splitlines()
        cl = doc["cluster"]
        lines = [
            "Configuration:",
            f"  Recovery state   - {cl['recovery_state']['name']} "
            f"(generation {cl['recovery_state']['generation']})",
            f"  Roles            - "
            + ", ".join(f"{r}x{len(a)}" for r, a in sorted(cl["roles"].items())),
        ]
        if "data" in cl:
            d = cl["data"]
            if "storage_version" in d:  # absent while no storage role lives
                lines.append(
                    f"  Storage          - version {d['storage_version']}, "
                    f"~{d.get('total_keys_estimate', 0)} keys, "
                    f"queue {d.get('storage_queue_bytes', 0)}B"
                )
            lines.append(
                f"  Shards           - {d.get('partitions_count', 1)} "
                f"({d.get('moving_shards', 0)} moving)"
            )
        if "logs" in cl:
            lg = cl["logs"]
            lines.append(
                f"  Logs             - version {lg['log_version']}, "
                f"queue {lg['queue_bytes']}B"
                + (
                    f", spilled through {lg['spilled_through_version']}"
                    if lg.get("spilled_through_version")
                    else ""
                )
            )
        if "qos" in cl and "transactions_per_second_limit" in cl["qos"]:
            q = cl["qos"]
            lines.append(
                f"  Ratekeeper       - limit {q['transactions_per_second_limit']:.0f} tps"
                f" (batch {q['batch_transactions_per_second_limit']:.0f}), "
                f"limited by: {q['performance_limited_by']}"
            )
        if "workload" in cl:
            t = cl["workload"]["transactions"]
            lines.append(
                f"  Workload         - {t['committed']} committed, "
                f"{t['conflicted']} conflicted"
            )
        if "resolver" in cl:
            r = cl["resolver"]
            lines.append(
                f"  Resolver         - x{r['count']} "
                f"({', '.join(r['backends']) or 'unknown'}), "
                f"{r['total_resolved']} resolved"
                + (", device telemetry live" if "tpu" in r else "")
            )
        return lines

    async def _cmd_metrics(self, args):
        """Registry snapshots straight off the live roles (the `fdbcli
        status json` habit of reading counters, but for the ISSUE 2
        metrics pipeline: proxy/resolver registries + kernel telemetry).
        `--diff` replaces the registry snapshots with counter/histogram
        DELTAS against the previous metrics command (same math as the
        time-series sampler, flow/timeseries.snapshot_delta) — lifetime
        totals hide what changed in the last thirty seconds."""
        from ..server.status import role_objects

        doc: dict = {}
        registries: dict = {}  # (section, name) -> registry snapshot
        for p in role_objects(self.cluster, "proxy"):
            m = getattr(p, "metrics", None)
            if m is not None:
                snap = m.snapshot()
                doc.setdefault("proxies", {})[p.proxy_id] = snap
                registries[("proxies", p.proxy_id)] = snap
            stats = getattr(p, "stats", None)
            if stats is not None:
                doc.setdefault("proxy_counters", {})[
                    p.proxy_id
                ] = stats.snapshot()
        for r in role_objects(self.cluster, "resolver"):
            m = getattr(r, "metrics", None)
            if m is not None:
                snap = m.snapshot()
                doc.setdefault("resolvers", {})[r.process.name] = snap
                registries[("resolvers", r.process.name)] = snap
            dm = getattr(getattr(r, "conflicts", None), "device_metrics", None)
            snap = dm() if callable(dm) else None
            if snap:
                doc.setdefault("tpu", {})[r.process.name] = snap
                registries[("tpu", r.process.name)] = snap
        diff = "--diff" in args
        no_baseline = False
        if diff:
            from ..flow.timeseries import snapshot_delta

            prev = getattr(self, "_metrics_prev", {})
            if not prev:
                # First invocation: there is nothing to diff against.
                # Say so clearly (and still show lifetime totals) instead
                # of presenting totals that LOOK like a window delta.
                no_baseline = True
            for (section, name), snap in registries.items():
                # Replace ONLY the registry keys (counters/gauges/
                # histograms) with deltas; instantaneous diagnostic
                # blocks (backend_state, breaker, mirror, tiers,
                # programs, ...) are not lifetime totals and pass
                # through unchanged — an operator diagnosing a degraded
                # device must not lose them in the diff view.
                delta = snapshot_delta(prev.get((section, name)), snap)
                doc[section][name] = {**snap, **delta}
        # Baseline for the NEXT --diff: every metrics command resets it,
        # so two successive `metrics --diff` calls show the in-between
        # window.
        self._metrics_prev = registries
        note = (
            "no prior snapshot — showing lifetime totals; run "
            "`metrics --diff` again for the in-between window"
        )
        if "--format=json" in args:
            if no_baseline:
                doc = {"note": note, **doc}
            return json.dumps(doc, indent=2, default=str).splitlines()
        if no_baseline:
            lines = [f"({note})"]
        elif diff:
            lines = ["(deltas since previous metrics command)"]
        else:
            lines = []
        for section in sorted(doc):
            lines.append(f"{section}:")
            for name, snap in sorted(doc[section].items()):
                lines.append(f"  {name}:")
                for k, v in sorted(snap.items()):
                    if isinstance(v, dict):
                        for kk, vv in sorted(v.items()):
                            lines.append(f"    {k}.{kk} = {vv}")
                    else:
                        lines.append(f"    {k} = {v}")
        return lines or ["(no metrics registries live)"]

    async def _cmd_flightrec(self, args):
        """Flight-recorder surface (ISSUE 10): list captures (text) or
        dump the full artifacts (--format=json) from the process-global
        recorder — the black-box record of breaker opens, mirror
        divergence, and admission throttling."""
        from ..flow.flight_recorder import global_flight_recorder

        rec = global_flight_recorder()
        if args and args[0] == "--format=json":
            doc = {
                "status": rec.status_section(),
                "captures": list(rec.captures),
            }
            return json.dumps(doc, indent=2, default=str).splitlines()
        if not rec.captures:
            counts = rec.trigger_counts
            return [
                "flight recorder: no captures"
                + (f" ({sum(counts.values())} triggers suppressed by "
                   "cooldown)" if counts else "")
            ]
        lines = [
            f"flight recorder: {len(rec.captures)} capture(s) retained "
            f"({rec.capture_seq} lifetime)"
        ]
        for cap in rec.captures:
            series = cap.get("timeseries", {})
            n_samples = sum(len(s) for s in series.values())
            lines.append(
                f"  #{cap['capture_seq']} t={cap['time']:.3f} "
                f"{cap['trigger']}: {len(series)} series / "
                f"{n_samples} samples, "
                f"{len(cap.get('recent_events', []))} trace events"
                + (f", detail={cap['detail']}" if cap.get("detail") else "")
            )
        return lines

    async def _cmd_mirror_check(self, args):
        """On-demand mirror consistency check (ISSUE 9): run
        ConflictSet.mirror_check() on every live resolver and report the
        verdicts.  Text form is one line per resolver; --format=json
        returns the raw report dicts (status ok|diverged|skipped)."""
        from ..server.status import role_objects

        doc: dict = {}
        for r in role_objects(self.cluster, "resolver"):
            mc = getattr(getattr(r, "conflicts", None), "mirror_check", None)
            if not callable(mc):
                continue
            rep = mc()
            name = getattr(getattr(r, "process", None), "name", None) or (
                f"resolver{len(doc)}"
            )
            doc[name] = (
                rep if rep is not None else {"status": "no_device_engine"}
            )
        if args and args[0] == "--format=json":
            return json.dumps(doc, indent=2, default=str).splitlines()
        if not doc:
            return ["(no resolvers live)"]
        lines = []
        for name, rep in sorted(doc.items()):
            status = rep.get("status", "?")
            if status == "ok":
                lines.append(
                    f"{name}: OK ({rep['boundaries']} boundaries match)"
                )
            elif status == "diverged":
                lines.append(
                    f"{name}: DIVERGED ({rep['mismatch_keys']} mismatched "
                    f"keys over {rep['boundaries']} mirror / "
                    f"{rep['device_boundaries']} device boundaries) — "
                    "breaker opened, device will rehydrate from snapshot"
                )
            elif status == "skipped":
                lines.append(f"{name}: skipped ({rep.get('reason', '?')})")
            else:
                lines.append(f"{name}: {status}")
        return lines

    async def _cmd_latency(self, args):
        """Per-stage latency percentiles.  Default source is the span
        layer (ISSUE 12): exact per-role stage durations straight off
        the resolver/proxy/client/tlog span rings — no chain
        reassembly, and it works on file-backed trace collectors too.
        `--chains` keeps the legacy g_traceBatch debug-id reassembly
        (flow/latency_chain.py) for trace-file-only inputs."""
        from ..flow.spans import global_span_hub, span_latency_summary

        use_chains = "--chains" in args
        hub = global_span_hub()
        if not use_chains and hub.rings:
            summary = span_latency_summary(hub)
            if "--format=json" in args:
                return json.dumps(
                    summary, indent=2, default=str
                ).splitlines()
            lines = ["per-stage span latency (virtual seconds):"]
            for role, stages in summary.items():
                if not stages:
                    continue
                lines.append(f"{role}:")
                for stage, s in stages.items():
                    lines.append(
                        f"  {stage:<16} n={s['count']:<5} "
                        f"p50={s['p50']:.6f} p90={s['p90']:.6f} "
                        f"p99={s['p99']:.6f} max={s['max']:.6f}"
                    )
            # Host-phase share (ISSUE 19): worst resolver's deterministic
            # encode+mirror_apply+readback fraction of host+device extent.
            from ..server.status import role_objects

            hf = None
            for r in role_objects(self.cluster, "resolver"):
                m = getattr(r, "metrics", None)
                if m is not None and "host_fraction" in m.gauges:
                    v = m.gauges["host_fraction"].value
                    hf = v if hf is None else max(hf, v)
            if hf is not None:
                lines.append(f"host_fraction: {hf:.4f}")
            return lines
        from ..flow.latency_chain import latency_summary
        from ..flow.trace import global_collector

        col = global_collector()
        if col.path is not None:
            return [
                "ERROR: trace collector is file-backed (events spooled "
                f"to {col.path}); chain reassembly needs the in-memory "
                "collector — the span layer (`latency` without "
                "--chains) works regardless"
            ]
        summary = latency_summary(col.events)
        if "--format=json" in args:
            return json.dumps(summary, indent=2, default=str).splitlines()
        lines = []
        for chain in ("commit", "grv"):
            lines.append(f"{chain} pipeline (seconds):")
            stages = summary[chain]
            any_sampled = any(s["count"] for s in stages.values())
            if not any_sampled:
                lines.append(
                    "  (no sampled chains; raise "
                    "client.latency_sample_rate)"
                )
                continue
            for stage, s in stages.items():
                if not s["count"]:
                    continue
                lines.append(
                    f"  {stage:<18} n={s['count']:<5} "
                    f"p50={s['p50']:.6f} p90={s['p90']:.6f} "
                    f"p99={s['p99']:.6f} max={s['max']:.6f}"
                )
        return lines

    async def _cmd_trace_export(self, args):
        """Perfetto / Chrome trace-event export of the span layer
        (ISSUE 12): one process per role, pipeline batches as nested
        slices, device phase-attribution children under their dispatch
        span.  Canonical compact JSON — byte-identical across same-seed
        runs unless --include-wall opts real-clock durations in."""
        from ..flow.spans import global_span_hub
        from ..flow.trace_export import perfetto_json

        include_wall = "--include-wall" in args
        out_path = next(
            (a.split("=", 1)[1] for a in args if a.startswith("--out=")),
            None,
        )
        blob = perfetto_json(include_wall=include_wall)
        if out_path:
            with open(out_path, "w", encoding="utf-8") as f:
                f.write(blob + "\n")
            hub = global_span_hub()
            return [
                f"wrote {out_path} "
                f"({sum(len(r) for r in hub.rings.values())} spans, "
                f"{len(hub.rings)} role tracks)"
            ]
        return [blob]

    async def _cmd_contention(self, args):
        """Conflict provenance explorer (ISSUE 17): joins each resolver's
        per-abort witness records — the per-batch contention timeline
        ring and the decayed top-K — into per-range abort timelines,
        alongside the resolver span-stage percentiles (the latency cost
        of the contention the witnesses attribute) and the
        contention_spike flight-recorder captures.  All inputs are
        virtual-time deterministic, so --format=json (canonical, sorted
        keys) is byte-identical across same-seed runs."""
        from ..flow.flight_recorder import global_flight_recorder
        from ..flow.spans import global_span_hub, span_latency_summary
        from ..server.status import role_objects

        limit = next(
            (int(a.split("=", 1)[1]) for a in args
             if a.startswith("--limit=")),
            8,
        )
        doc: dict = {"resolvers": {}}
        for r in role_objects(self.cluster, "resolver"):
            cw = getattr(r, "conflict_witness", None)
            if not callable(cw):
                continue
            rep = cw()
            name = getattr(getattr(r, "process", None), "name", None) or (
                f"resolver{len(doc['resolvers'])}"
            )
            # Fold the per-batch timeline into per-range abort series:
            # every batch that witnessed aborts against a range
            # contributes one [commit_version, aborts] point, so an
            # operator reads WHEN a range got hot, not just that it did.
            ranges: dict = {}
            for entry in rep["contention"]["timeline"]:
                for b, e, n in entry["ranges"]:
                    slot = ranges.setdefault(
                        f"{b}..{e}", {"aborts": 0, "timeline": []}
                    )
                    slot["aborts"] += n
                    slot["timeline"].append([entry["version"], n])
            top = sorted(
                ranges.items(), key=lambda kv: (-kv[1]["aborts"], kv[0])
            )[:limit]
            doc["resolvers"][name] = {
                "aborts": rep["aborts"],
                "topk": rep["topk"][:limit],
                "witness_batches": rep["contention"]["witness_batches"],
                "streak": rep["contention"]["streak"],
                "spikes": rep["contention"]["spikes"],
                "ranges": dict(top),
            }
        hub = global_span_hub()
        summary = span_latency_summary(hub) if hub.rings else {}
        # Ring keys are "Resolver.<name>" — strip the role prefix so the
        # span block keys line up with the witness block above.
        doc["spans"] = {
            role.split(".", 1)[1]: stages
            for role, stages in summary.items()
            if role.startswith("Resolver.")
        }
        rec = global_flight_recorder()
        doc["captures"] = [
            {
                "capture_seq": c["capture_seq"],
                "time": c["time"],
                "detail": c.get("detail"),
            }
            for c in rec.captures
            if c.get("trigger") == "contention_spike"
        ]
        if "--format=json" in args:
            return json.dumps(
                doc, indent=2, sort_keys=True, default=str
            ).splitlines()
        if not doc["resolvers"]:
            return ["(no resolvers live)"]
        lines = []
        for name, rr in sorted(doc["resolvers"].items()):
            lines.append(
                f"{name}: {rr['aborts']} witnessed aborts over "
                f"{rr['witness_batches']} batches "
                f"(streak {rr['streak']}, {rr['spikes']} spike(s))"
            )
            for key, slot in sorted(
                rr["ranges"].items(),
                key=lambda kv: (-kv[1]["aborts"], kv[0]),
            ):
                tl = slot["timeline"]
                lines.append(
                    f"  [{key}]  {slot['aborts']} aborts over "
                    f"{len(tl)} batches, last @v{tl[-1][0]}"
                )
            if not rr["ranges"]:
                lines.append("  (no witnessed aborts in the timeline ring)")
        for name, stages in sorted(doc["spans"].items()):
            if not stages:
                continue
            lines.append(f"{name} span stages (virtual seconds):")
            for stage, s in stages.items():
                lines.append(
                    f"  {stage:<16} n={s['count']:<5} "
                    f"p50={s['p50']:.6f} p99={s['p99']:.6f}"
                )
        if doc["captures"]:
            lines.append(
                f"contention spike captures: "
                f"{len(doc['captures'])} "
                f"(`flightrec --format=json` for the artifacts)"
            )
        return lines

    async def _cmd_shards(self, args):
        """Shard-mesh explorer (ISSUE 18): the elastic-resharding twin of
        `contention` — per-resolver split points, occupancy gauges,
        breaker states, the ShardBalancer decision log, and the conflict
        set's reshard move log, plus the reshard flight-recorder
        captures.  All inputs are virtual-time deterministic, so
        --format=json (canonical, sorted keys) is byte-identical across
        same-seed runs."""
        from ..flow.flight_recorder import global_flight_recorder
        from ..server.status import role_objects

        doc: dict = {"resolvers": {}}
        for r in role_objects(self.cluster, "resolver"):
            cs = getattr(r, "conflicts", None)
            dm = getattr(cs, "device_metrics", None)
            if not callable(dm):
                continue
            shards = (dm() or {}).get("shards")
            if shards is None:
                continue
            name = getattr(getattr(r, "process", None), "name", None) or (
                f"resolver{len(doc['resolvers'])}"
            )
            bal = getattr(r, "shard_balancer", None)
            doc["resolvers"][name] = {
                "shards": shards,
                "move_log": [dict(e) for e in getattr(cs, "move_log", [])],
                "balancer": None
                if bal is None
                else {
                    "moves": bal.moves,
                    "decisions": [dict(d) for d in bal.decisions],
                },
            }
        rec = global_flight_recorder()
        doc["captures"] = [
            {
                "capture_seq": c["capture_seq"],
                "time": c["time"],
                "detail": c.get("detail"),
            }
            for c in rec.captures
            if c.get("trigger") == "reshard"
        ]
        if "--format=json" in args:
            return json.dumps(
                doc, indent=2, sort_keys=True, default=str
            ).splitlines()
        if not doc["resolvers"]:
            return ["(no mesh-sharded resolvers live)"]
        lines = []
        for name, rr in sorted(doc["resolvers"].items()):
            sh = rr["shards"]
            lines.append(
                f"{name}: {sh['total']}/{sh['max']} shards "
                f"({sh['degraded']} degraded, {len(rr['move_log'])} "
                f"move(s))"
            )
            lines.append(f"  states:    {' '.join(sh['states'])}")
            lines.append(
                "  occupancy: "
                + " ".join(str(o) for o in sh["occupancy"])
            )
            lines.append(
                "  splits:    "
                + (" ".join(sh["split_keys"]) or "(none)")
            )
            lm = sh.get("last_move")
            if lm:
                lines.append(
                    f"  last move: seq={lm['seq']} action={lm['action']} "
                    f"reason={lm['reason']} shards={lm['shards']}"
                )
            bal = rr["balancer"]
            if bal is not None:
                acted = [
                    d for d in bal["decisions"]
                    if d["action"] in ("move", "scale")
                ]
                lines.append(
                    f"  balancer:  {len(bal['decisions'])} tick(s), "
                    f"{bal['moves']} committed move(s), "
                    f"{len(acted)} decision(s) to act"
                )
        if doc["captures"]:
            lines.append(
                f"reshard captures: {len(doc['captures'])} "
                f"(`flightrec --format=json` for the artifacts)"
            )
        return lines

    async def _probe_swallowing(self):
        from ..server.status import latency_probe

        try:
            return await latency_probe(self.db)
        except FdbError:
            return {"error": "probe failed"}

    async def _cmd_begin(self, args):
        if self._tr is not None:
            return ["ERROR: already in a transaction"]
        self._tr = self.db.create_transaction()
        return ["Transaction started"]

    async def _cmd_commit(self, args):
        if self._tr is None:
            return ["ERROR: no transaction in progress"]
        tr, self._tr = self._tr, None
        version = await tr.commit()
        return [f"Committed ({version})"]

    async def _cmd_rollback(self, args):
        if self._tr is None:
            return ["ERROR: no transaction in progress"]
        self._tr = None
        return ["Transaction rolled back"]

    async def _cmd_configure(self, args):
        """Ref: fdbcli `configure proxies=2 ...` -> changeConfig."""
        from ..client import management as mgmt

        params = {}
        for a in args:
            if "=" not in a:
                return [f"ERROR: expected name=value, got `{a}'"]
            name, value = a.split("=", 1)
            try:
                params[name] = int(value)
            except ValueError:
                return [f"ERROR: `{name}' needs an integer value, got `{value}'"]
        try:
            await mgmt.configure(self.db, **params)
        except ValueError as e:
            return [f"ERROR: {e}"]
        return ["Configuration changed"]

    async def _cmd_exclude(self, args):
        from ..client import management as mgmt

        if not args:
            excluded = await mgmt.get_excluded_servers(self.db)
            return [f"Excluded: {', '.join(excluded) or '(none)'}"]
        await mgmt.exclude_servers(self.db, list(args))
        return [f"Excluded {len(args)} server(s)"]

    async def _cmd_include(self, args):
        from ..client import management as mgmt

        await mgmt.include_servers(self.db, list(args) or None)
        return ["Included"]

    async def _cmd_coordinators(self, args):
        """Ref: fdbcli `coordinators <addr> ...` -> changeQuorum
        (ManagementAPI.actor.cpp:684).  No args: show the requested set."""
        from ..client import management as mgmt

        if not args:
            cur = await mgmt.get_requested_coordinators(self.db)
            return [f"Coordinators: {', '.join(cur) if cur else '(default)'}"]
        try:
            await mgmt.change_coordinators(self.db, list(args))
        except ValueError as e:
            return [f"ERROR: {e}"]
        return ["Coordination state changed"]

    async def _cmd_setclass(self, args):
        """Ref: fdbcli `setclass <address> <class>`."""
        from ..client import management as mgmt

        if len(args) != 2:
            return ["ERROR: usage: setclass <address> <class>"]
        addr, cls = args
        try:
            await mgmt.set_process_class(self.db, addr, cls)
        except ValueError as e:
            return [f"ERROR: {e}"]
        return [f"Process class for `{addr}' set to {cls}"]

    async def _cmd_lock(self, args):
        """Ref: fdbcli `lock` -> lockDatabase."""
        from ..client import management as mgmt

        uid = await mgmt.lock_database(self.db)
        self._lock_uid = uid
        return [f"Database locked with uid {uid.decode()}"]

    async def _cmd_unlock(self, args):
        from ..client import management as mgmt

        uid = args[0].encode() if args else getattr(self, "_lock_uid", None)
        if uid is None:
            return ["ERROR: unlock <uid> (no lock taken in this session)"]
        await mgmt.unlock_database(self.db, uid)
        return ["Database unlocked"]

    async def _cmd_profile(self, args):
        """Ref: fdbcli `profile` + the CpuProfiler workload's runtime
        toggle (Profiler.actor.cpp:175)."""
        from ..flow.profiler import get_profiler, profiler_toggle

        if not args or args[0] not in ("on", "off", "report"):
            return ["ERROR: usage: profile <on|off|report> [interval]"]
        if args[0] == "report":
            rep = get_profiler().report(top=10)
            lines = [
                f"Profiler: {'running' if rep['running'] else 'stopped'}, "
                f"{rep['total_samples']} samples @ {rep['interval']*1e3:.1f}ms"
            ]
            for h in rep["hot_functions"]:
                lines.append(
                    f"  {h['fraction']*100:5.1f}%  {h['function']} "
                    f"({h['file'].rsplit('/', 1)[-1]}:{h['line']})"
                )
            return lines
        interval = float(args[1]) if len(args) > 1 else None
        state = profiler_toggle(args[0] == "on", interval)
        return [
            f"Profiler {'running' if state['running'] else 'stopped'}"
        ]

    async def _cmd_watch(self, args):
        (key,) = args
        tr = self.db.create_transaction()
        fut = await tr.watch(key.encode())
        await tr.commit()
        version = await fut
        return [f"`{key}' changed at version {version}"]

    async def _cmd_soak(self, args):
        # The soak builds (and tears down) its own rated cluster + event
        # loop; running it from inside THIS cluster's loop would nest two
        # simulations.  Point the operator at the subcommand instead.
        return [
            "ERROR: soak runs its own rated cluster — invoke it as a "
            "subcommand: python -m foundationdb_tpu.tools.cli soak "
            "[--format=json] (see --help)"
        ]


def soak_main(argv=None) -> int:
    """`cli soak`: run the chaos-soak harness (workloads/soak.py) and emit
    a BENCH-style JSON artifact (goodput, p99s, throttle/shed counts,
    fault timeline) so future BENCH_r0*.json rounds get a soak arm.
    Defaults come from the FDB_TPU_SOAK_* env flags (flow/knobs.py
    g_env); argv overrides them."""
    import argparse

    from ..flow.knobs import g_env
    from ..workloads.soak import default_config, run_soak

    ap = argparse.ArgumentParser(
        prog="cli soak",
        description="sustained chaos-soak: ramped Zipf load + scripted "
        "fault matrix against a rated simulated cluster",
    )
    ap.add_argument("--minutes", type=float,
                    default=float(g_env.get("FDB_TPU_SOAK_MINUTES")),
                    help="soak length in SIM minutes (virtual time)")
    ap.add_argument("--seed", type=int,
                    default=g_env.get_int("FDB_TPU_SOAK_SEED"))
    ap.add_argument("--tps", type=float,
                    default=float(g_env.get("FDB_TPU_SOAK_TPS")),
                    help="peak-phase open-loop arrival rate (txn/s)")
    ap.add_argument("--keys", type=int,
                    default=g_env.get_int("FDB_TPU_SOAK_KEYS"))
    ap.add_argument("--theta", type=float,
                    default=float(g_env.get("FDB_TPU_SOAK_THETA")),
                    help="Zipf skew exponent (0 = uniform)")
    ap.add_argument("--backend", default=None,
                    choices=("cpu", "jax", "hybrid", "sharded"),
                    help="conflict backend (default: FDB_TPU_SOAK_BACKEND)")
    ap.add_argument("--cluster", choices=("sim", "dynamic"), default="sim",
                    help="dynamic adds recovery-capable process kills")
    ap.add_argument("--mode", choices=("open", "closed"), default="open")
    ap.add_argument("--no-faults", action="store_true",
                    help="pure load run (baseline arm)")
    ap.add_argument("--shard-outage", action="store_true",
                    help="ISSUE 15: the shard-outage phase family on the "
                    "mesh-sharded backend — one shard's chip dies for the "
                    "middle phase while the survivors hold the floor")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--out", default="",
                    help="also write the JSON artifact to this path")
    args = ap.parse_args(argv)

    if args.shard_outage:
        # The shard-outage family fixes backend/cluster/faults by
        # construction — reject flags it would silently contradict.
        if args.cluster != "sim":
            ap.error("--shard-outage runs on the sim cluster only "
                     "(the sharded backend is SimCluster's conflict_set "
                     "seam)")
        if args.no_faults:
            ap.error("--shard-outage IS the shard_kill fault; "
                     "--no-faults contradicts it")
        # None = not given explicitly (env/default backends are
        # overridden by this purpose-built mode, never contradicted).
        if args.backend not in (None, "sharded"):
            ap.error("--shard-outage implies --backend sharded")
        from ..workloads.soak import shard_outage_config

        config = shard_outage_config(
            minutes=args.minutes, peak_tps=args.tps, seed=args.seed
        )
        config.keys = args.keys
        config.zipf_theta = args.theta
        config.mode = args.mode
    else:
        config = default_config(
            minutes=args.minutes,
            peak_tps=args.tps,
            seed=args.seed,
            cluster=args.cluster,
            backend=args.backend or g_env.get("FDB_TPU_SOAK_BACKEND"),
            mode=args.mode,
            keys=args.keys,
            zipf_theta=args.theta,
            faults=not args.no_faults,
        )
    report = run_soak(config)
    artifact = soak_artifact(report)
    blob = json.dumps(artifact, indent=2, sort_keys=True)
    if args.format == "json":
        print(blob)
    else:
        t = report["totals"]
        print(
            f"soak: {t['committed']} committed / {t['attempts']} attempts "
            f"in {t['sim_seconds']}s sim ({t['goodput_tps']} txn/s goodput)"
        )
        for ph in report["phases"]:
            print(
                f"  {ph['name']:<9} goodput={ph['goodput_tps']:<8} "
                f"(floor {ph['goodput_floor_tps']}) "
                f"p99={ph['commit_p99_chain']} "
                f"throttled={ph['throttled']} "
                f"{'OK' if ph['slo_ok'] else 'SLO-MISS'}"
            )
        for t0, kind, detail, t1 in report["faults"]:
            print(f"  fault {kind} [{t0:.2f}s..{t1:.2f}s] {detail}")
        print(f"  slo: {'OK' if report['slo']['ok'] else 'MISSED'}")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(blob + "\n")
    return 0 if report["slo"]["ok"] else 1


def soak_artifact(report: dict) -> dict:
    """BENCH-style artifact shape (one headline metric + the structured
    evidence), mirroring bench.py's {"metric", "value", "unit", ...}
    convention so the driver's BENCH_r0*.json collection can absorb it."""
    t = report["totals"]
    return {
        "metric": "soak_goodput_txn_per_sec",
        "value": t["goodput_tps"],
        "unit": "txn/s",
        "sim_seconds": t["sim_seconds"],
        "committed": t["committed"],
        "attempts": t["attempts"],
        "seed": report["config"]["seed"],
        "cluster": report["config"]["cluster"],
        "backend": report["config"]["backend"],
        "phases": report["phases"],
        "throttle_shed": report["throttle_shed"],
        "fault_timeline": report["faults"],
        "ratekeeper_transitions": report["ratekeeper"]["admission_log"],
        "breaker_transitions": report["breakers"],
        "slo": report["slo"],
        "flight_recorder": report.get("flight_recorder", {}).get(
            "status", {}
        ),
    }


def main(argv=None):  # pragma: no cover - interactive entry
    import sys

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "soak":
        return soak_main(argv[1:])

    from ..server import SimCluster

    cluster = SimCluster(seed=0)
    db = cluster.database("cli")
    cli = CliProcessor(cluster, db)
    print("fdbcli (tpu-kv simulated cluster); type `help' for help")
    while True:
        try:
            line = input("fdb> ")
        except (EOFError, KeyboardInterrupt):
            break
        if line.strip() in ("exit", "quit"):
            break

        async def run():
            return await cli.run_command(line)

        task = db.process.spawn(run())
        out = cluster.loop.run_until(task, timeout_vt=60.0)
        for ln in out:
            print(ln)
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main() or 0)
