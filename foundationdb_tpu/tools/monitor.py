"""Process watchdog: keep the configured server processes running.

Ref: fdbmonitor/fdbmonitor.cpp — a deliberately plain (non-flow) daemon
that parses an ini config, forks/execs one process per [section], restarts
crashed children with exponential backoff (:274-283), and re-reads the
config when it changes (inotify there; mtime polling here — same
observable behavior, no platform dependency).

Config format (ini):

    [general]
    restart_delay = 2          ; max backoff seconds
    logdir = /var/log/cluster  ; per-child stdout/err files (optional)

    [server.1]
    command = python -m foundationdb_tpu.tools.real_node server --port 4500

Run: python -m foundationdb_tpu.tools.monitor <conf-file>
"""

from __future__ import annotations

import configparser
import os
import shlex
import signal
import subprocess
import sys
import time
from typing import Dict, Optional

from ..utils.procutil import die_with_parent


class _Child:
    def __init__(self, name: str, command: str):
        self.name = name
        self.command = command
        self.proc: Optional[subprocess.Popen] = None
        self.failures = 0
        self.backoff_until = 0.0
        self.started_at = 0.0

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class Monitor:
    def __init__(self, conf_path: str, out=sys.stderr):
        self.conf_path = conf_path
        self.out = out
        self.children: Dict[str, _Child] = {}
        self.max_restart_delay = 2.0
        self.logdir: Optional[str] = None
        self._conf_mtime = 0.0
        self.stopped = False

    def _log(self, msg: str):
        print(f"[monitor] {msg}", file=self.out, flush=True)

    def load_config(self) -> bool:
        """(Re)read the config; returns True when it changed.  Sections
        other than [general] each define one child via `command`."""
        try:
            mtime = os.stat(self.conf_path).st_mtime
        except OSError:
            return False
        if mtime == self._conf_mtime:
            return False
        self._conf_mtime = mtime
        # A bad edit of the LIVE config must not take the cluster down:
        # keep supervising on the previous state and retry the parse on
        # the next change (ref: fdbmonitor surviving reload errors).
        try:
            cp = configparser.ConfigParser()
            cp.read(self.conf_path)
            if cp.has_option("general", "restart_delay"):
                self.max_restart_delay = cp.getfloat("general", "restart_delay")
            if cp.has_option("general", "logdir"):
                self.logdir = cp.get("general", "logdir")
                os.makedirs(self.logdir, exist_ok=True)
            wanted = {
                s: cp.get(s, "command")
                for s in cp.sections()
                if s != "general" and cp.has_option(s, "command")
            }
        except (configparser.Error, ValueError, OSError) as e:
            self._log(f"config reload failed (keeping previous): {e}")
            return False
        # Stop removed/changed children; add new ones (ref: the config
        # reload diffing in fdbmonitor's watch_conf_file handling).
        for name in list(self.children):
            ch = self.children[name]
            if name not in wanted or wanted[name] != ch.command:
                self._stop_child(ch)
                del self.children[name]
        for name, cmd in wanted.items():
            if name not in self.children:
                self.children[name] = _Child(name, cmd)
        self._log(f"config loaded: {sorted(self.children)}")
        return True

    def _start_child(self, ch: _Child):
        self._log(f"starting {ch.name}: {ch.command}")
        ch.started_at = time.monotonic()
        if self.logdir:
            # Per-child log files, like fdbmonitor's logdir (unbuffered so
            # READY-style liveness lines appear promptly).
            logf = open(
                os.path.join(self.logdir, f"{ch.name}.log"), "ab", buffering=0
            )
            ch.proc = subprocess.Popen(
                shlex.split(ch.command),
                stdout=logf,
                stderr=subprocess.STDOUT,
                preexec_fn=die_with_parent,
            )
            logf.close()
        else:
            ch.proc = subprocess.Popen(
                shlex.split(ch.command), preexec_fn=die_with_parent
            )

    def _stop_child(self, ch: _Child):
        if ch.alive():
            self._log(f"stopping {ch.name} (pid {ch.proc.pid})")
            ch.proc.send_signal(signal.SIGTERM)
            try:
                ch.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                ch.proc.kill()

    def poll_once(self, now: Optional[float] = None):
        """One supervision round: reap exits, schedule restarts with
        doubling backoff capped at restart_delay (ref: fdbmonitor
        :274-283 — delay halves again after a stable run)."""
        now = time.monotonic() if now is None else now
        self.load_config()
        for ch in self.children.values():
            if ch.alive():
                continue
            if ch.proc is not None:
                rc = ch.proc.poll()
                self._log(f"{ch.name} exited rc={rc}")
                ch.proc = None
                # A stable run forgives past crashes (ref: fdbmonitor
                # halving the delay after the child stays up).
                if now - ch.started_at > 2 * self.max_restart_delay + 5:
                    ch.failures = 0
                ch.failures += 1
                delay = min(
                    self.max_restart_delay, 0.1 * (2 ** min(ch.failures, 10))
                )
                ch.backoff_until = now + delay
            if now >= ch.backoff_until:
                try:
                    self._start_child(ch)
                except OSError as e:
                    # e.g. the command's binary is missing: count it as a
                    # crash and back off rather than killing the monitor.
                    self._log(f"start of {ch.name} failed: {e}")
                    ch.failures += 1
                    ch.backoff_until = now + min(
                        self.max_restart_delay,
                        0.1 * (2 ** min(ch.failures, 10)),
                    )

    def run(self):
        # SIGTERM/SIGINT must reach the finally block: without handlers the
        # default action kills this process outright and every supervised
        # child leaks as an orphan (ref: fdbmonitor's signal handling).
        def _stop(signum, frame):
            self.stopped = True

        signal.signal(signal.SIGTERM, _stop)
        signal.signal(signal.SIGINT, _stop)
        self.load_config()
        try:
            while not self.stopped:
                self.poll_once()
                time.sleep(0.2)
        finally:
            for ch in self.children.values():
                self._stop_child(ch)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: monitor <conf-file>", file=sys.stderr)
        return 2
    Monitor(argv[0]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
