"""Boot framework roles as a REAL OS process on TCP — the `fdbserver -r
fdbd` analog for the rebuilt stack.

Ref: fdbserver/fdbserver.actor.cpp:1468-1473 — the same role actors run on
the real network (`g_network = newNet2(...)`) or the simulator
(`startNewSimulator()`); this module is the real-network entry.  Topology
here is the static minimum slice (one process hosting
sequencer/resolver/tlog/storage/proxy, clients discovering interfaces via a
bootstrap endpoint); the elected control plane rides the same transport
later.

Usage:
  python -m foundationdb_tpu.tools.real_node server [--port N]
      prints "READY <host:port>" then serves forever.
  python -m foundationdb_tpu.tools.real_node client <server-addr> \
      --id NAME --ops N [--check-count M]
      runs N increment transactions (idempotence keys under NAME/), prints
      "DONE <count-after>" — with --check-count also asserts the final
      counter value.
"""

from __future__ import annotations

import argparse
import os
import sys

from ..flow.eventloop import EventLoop, set_event_loop
from ..rpc.real_network import RealNetwork
from ..rpc.stream import RequestStream, RequestStreamRef, well_known_token
from ..rpc.network import Endpoint


def _tls_config(args):
    from ..rpc.real_network import TLSConfig

    given = [
        bool(getattr(args, "tls_cert", "")),
        bool(getattr(args, "tls_key", "")),
        bool(getattr(args, "tls_ca", "")),
    ]
    if not any(given):
        return None
    if not all(given):
        # NEVER fall back to plaintext on a partial TLS config — that is a
        # silent security downgrade.
        raise SystemExit(
            "TLS requires all of --tls-cert, --tls-key, --tls-ca"
        )
    return TLSConfig(args.tls_cert, args.tls_key, args.tls_ca)


def _add_tls_args(parser):
    parser.add_argument("--tls-cert", default="", help="PEM cert (mutual TLS)")
    parser.add_argument("--tls-key", default="")
    parser.add_argument("--tls-ca", default="")


def run_server(port: int, datadir: str = "", tls=None) -> None:
    from ..flow.knobs import g_knobs
    from ..server.proxy import Proxy
    from ..server.resolver import Resolver
    from ..server.sequencer import Sequencer
    from ..server.storage import (
        OWNED_META_KEY,
        VERSION_META_KEY,
        StorageServer,
    )
    from ..server.tlog import TLog

    loop = EventLoop(seed=1)
    set_event_loop(loop)
    net = RealNetwork(loop, port=port, tls=tls)
    proc = net.process("server")

    if datadir:
        # Durable single-node deployment: the mutation log rides the
        # crash-safe DiskQueue on REAL files (the sim<->real IAsyncFile
        # swap), the storage base is the native C++ engine, and restart
        # follows the same recovery the simulated durable cluster runs —
        # recover the log, pick an epoch beyond every durable end, fast-
        # forward, and resume the storage from its engine's durable
        # version so it replays the log tail (ref: the restart path in
        # SimulatedCluster restartSimulatedSystem + IKeyValueStore.h:43).
        from ..rpc.wire import decode_frame

        from ..fileio.kvstore_native import NativeKeyValueStore
        from ..fileio.realfile import RealFileSystem
        from ..server.tlog import TLog as _TLog

        fs = RealFileSystem(datadir)
        kv = NativeKeyValueStore(os.path.join(datadir, "engine"))
        vmeta = kv.read_value(VERSION_META_KEY)
        durable = int(vmeta.decode()) if vmeta else 0
        owned_meta = kv.read_value(OWNED_META_KEY)
        meta = decode_frame(owned_meta) if owned_meta else None

        tlog = None

        async def recover_log():
            nonlocal tlog
            tlog = await _TLog.recover(proc, fs, "tlog.dq")

        t = proc.spawn(recover_log(), "recover_log")
        net.run_realtime(until=t, timeout_s=60.0)
        epoch_begin = (
            max(tlog.durable.get(), durable)
            + g_knobs.server.max_versions_in_flight
        )
        tlog.durable.set(epoch_begin)
        tlog.known_committed = epoch_begin
        storage = StorageServer(
            proc,
            [tlog.interface()],
            epoch_begin_version=durable,
            kvstore=kv,
            storage_id="ss0",
            owned_all=meta is None,
            meta=meta,
        )
    else:
        epoch_begin = 0
        tlog = TLog(proc)
        storage = StorageServer(
            proc, [tlog.interface()], storage_id="ss0", owned_all=True
        )

    sequencer = Sequencer(proc, epoch_begin_version=epoch_begin)
    resolver = Resolver(proc, backend="cpu", epoch_begin_version=epoch_begin)
    proxy = Proxy(
        proc,
        sequencer.interface(),
        [resolver.interface()],
        [tlog.interface()],
        epoch_begin_version=epoch_begin,
    )

    boot = RequestStream(proc, "bootstrap", well_known=True)

    async def serve_bootstrap():
        while True:
            _req, reply = await boot.pop()
            reply.send(
                {
                    "proxy": proxy.interface(),
                    "storage": storage.interface(),
                    "proxies": [proxy.interface()],
                }
            )

    proc.spawn(serve_bootstrap(), "bootstrap")
    # Real-deployment observability: per-process metrics cadence + the
    # slow-task profiler (ref: systemMonitor + Net2 slow-task profiling).
    from ..flow.system_monitor import run_system_monitor

    loop.slow_task_threshold = 0.25
    proc.spawn(run_system_monitor(proc, wall_metrics=True), "system_monitor")
    # Graceful SIGTERM (ISSUE 8 satellite): first TERM stops the reactor
    # so the transport closes and we exit 0 below; a second TERM SIGKILLs
    # the whole process group (procutil ladder) — multi-process soak
    # teardown can neither leak orphans nor hang on a wedged shutdown.
    from ..utils.procutil import install_graceful_term

    install_graceful_term(net.stop)
    print(f"READY {net.address}", flush=True)
    net.run_realtime()
    net.close()
    if datadir:
        kv.close()  # flush the native engine's WAL handle cleanly
    print("SHUTDOWN", flush=True)


def run_client(
    server: str, client_id: str, ops: int, check_count: int, tls=None,
    progress: bool = False,
) -> None:
    from ..client.transaction import Database

    loop = EventLoop(seed=2)
    set_event_loop(loop)
    net = RealNetwork(loop, tls=tls)
    proc = net.process(f"client-{client_id}")

    boot_ref = RequestStreamRef(
        Endpoint(server, well_known_token("bootstrap")), "bootstrap"
    )

    async def main():
        ifaces = await boot_ref.get_reply(proc, None)
        db = Database(
            proc,
            ifaces["proxy"],
            ifaces["storage"],
            proxies=ifaces["proxies"],
        )
        for i in range(ops):

            async def op(tr, i=i):
                v = await tr.get(b"count")
                n = int(v.decode()) if v else 0
                tr.set(b"count", b"%d" % (n + 1))
                tr.set(b"%s/%04d" % (client_id.encode(), i), b"x")

            await db.run(op)
            if progress:
                # One line per completed op: lets tests synchronize a
                # fault injection on REAL progress instead of wall clock.
                print(f"OP {i}", flush=True)

        out = {}

        async def readback(tr):
            v = await tr.get(b"count")
            out["count"] = int(v.decode()) if v else 0
            rows = await tr.get_range(
                client_id.encode() + b"/", client_id.encode() + b"0"
            )
            out["mine"] = len(rows)

        await db.run(readback)
        return out

    task = proc.spawn(main(), "client_main")
    out = net.run_realtime(until=task, timeout_s=60.0)
    assert out["mine"] == ops, out
    if check_count >= 0:
        assert out["count"] == check_count, out
    print(f"DONE {out['count']}", flush=True)


def run_ntserver(port: int, tls=None) -> None:
    """RPC echo server (ref: networktestServer, networktest.actor.cpp:40 —
    `fdbserver -r networktestserver`): answers each request with its
    payload, characterizing the fabric + codec end to end."""
    loop = EventLoop(seed=1)
    set_event_loop(loop)
    net = RealNetwork(loop, port=port, tls=tls)
    proc = net.process("ntserver")
    stream = RequestStream(proc, "networktest", well_known=True)

    async def serve():
        while True:
            payload, reply = await stream.pop()
            reply.send(payload)

    proc.spawn(serve(), "networktest_serve")
    from ..utils.procutil import install_graceful_term

    install_graceful_term(net.stop)
    print(f"READY {net.address}", flush=True)
    net.run_realtime()
    net.close()
    print("SHUTDOWN", flush=True)


def run_ntclient(server: str, requests: int, parallel: int, size: int,
                 tls=None) -> None:
    """Closed-loop throughput driver (ref: networktestClient,
    networktest.actor.cpp:57): `parallel` workers each keep one request in
    flight until `requests` total complete; prints one JSON line with
    req/s and payload MB/s."""
    import json
    import time as _time

    loop = EventLoop(seed=2)
    set_event_loop(loop)
    net = RealNetwork(loop, tls=tls)
    proc = net.process("ntclient")
    ref = RequestStreamRef(
        Endpoint(server, well_known_token("networktest")), "networktest"
    )
    payload = b"x" * size
    done = {"n": 0}

    async def worker():
        while done["n"] < requests:
            done["n"] += 1
            got = await ref.get_reply(proc, payload)
            assert got == payload

    async def main():
        # One warm-up round trip so connect/TLS handshake stays out of the
        # timed region, as the reference's warmup phase does.
        await ref.get_reply(proc, b"warm")
        t0 = _time.monotonic()
        from ..flow.eventloop import wait_for_all

        await wait_for_all(
            [proc.spawn(worker(), f"nt{i}") for i in range(parallel)]
        )
        dt = _time.monotonic() - t0
        return {
            "metric": "rpc_requests_per_sec",
            "value": round(requests / dt, 1),
            "unit": "req/s",
            "payload_bytes": size,
            "parallel": parallel,
            "mb_per_sec": round(requests * size / dt / 1e6, 2),
            "tls": tls is not None,
        }

    task = proc.spawn(main(), "nt_main")
    out = net.run_realtime(until=task, timeout_s=120.0)
    print(json.dumps(out), flush=True)


def run_kvcheck(datadir: str) -> int:
    """Offline durable-state integrity check (ref: the
    kvfileintegritycheck role, fdbserver.actor.cpp:637 — verify a store
    file without serving it).  Walks every durable artifact in a
    --datadir: the TLog DiskQueue (CRC-framed records, codec-decoded),
    its spill btree (strict CRC'd pages, codec-decoded rows), and the
    native C++ engine (WAL replay + full scan).  Prints one JSON report;
    exit 0 only if everything verifies."""
    import json as _json
    import shutil
    import tempfile
    import zlib as _zlib

    from ..fileio import diskqueue as _dq
    from ..fileio.btree import BTreeKeyValueStore
    from ..fileio.kvstore_native import NativeKeyValueStore
    from ..fileio.realfile import RealFileSystem
    from ..flow.error import FdbError
    from ..rpc.wire import WireDecodeError, decode_frame

    if not os.path.isdir(datadir):
        # A read-only check must not conjure an empty store into
        # existence (a typo'd path would get a clean bill of health).
        print(_json.dumps({"datadir": datadir, "ok": False,
                           "error": "no such directory"}), flush=True)
        return 1
    loop = EventLoop(seed=1)
    set_event_loop(loop)
    report = {"datadir": datadir, "ok": True}

    def classify_gap(img: bytes, start: int, is_frame) -> bool:
        """True iff a well-formed frame exists at/after `start` — the
        distinguisher between a legitimate torn tail (crash model:
        nothing valid follows) and MID-FILE corruption (valid frames
        beyond = data recovery would silently drop)."""
        j = start
        while j < len(img):
            if is_frame(img, j):
                return True
            j += 1
        return False

    # 1. TLog disk queue: PURE READ-ONLY frame walk (DiskQueue.open is a
    # RECOVERY entry point — it truncates at the first bad frame, which
    # an integrity check must never do to the store it verifies).
    dq_path = os.path.join(datadir, "tlog.dq")
    if os.path.exists(dq_path):
        img = open(dq_path, "rb").read()
        fhdr = _dq._FRAME_HDR
        off = _dq._HEADER_SIZE
        records = 0
        bad_payloads = 0
        stop = None
        while off + fhdr.size <= len(img):
            magic, seq, length, crc = fhdr.unpack_from(img, off)
            payload = img[off + fhdr.size: off + fhdr.size + length]
            if (
                magic != _dq._MAGIC
                or len(payload) != length
                or _dq._frame_crc(seq, payload) != crc
            ):
                stop = off
                break
            records += 1
            try:
                decode_frame(bytes(payload))
            except WireDecodeError:
                bad_payloads += 1
            off += fhdr.size + length
        report["tlog_records"] = records
        report["tlog_undecodable"] = bad_payloads
        if bad_payloads:
            report["ok"] = False

        def _dq_frame_at(b, j):
            if j + fhdr.size > len(b):
                return False
            m, sq, ln, c = fhdr.unpack_from(b, j)
            pl = b[j + fhdr.size: j + fhdr.size + ln]
            return (m == _dq._MAGIC and len(pl) == ln
                    and _dq._frame_crc(sq, pl) == c)

        if stop is not None and classify_gap(img, stop + 1, _dq_frame_at):
            report["tlog_corrupt_at"] = stop
            report["ok"] = False

    # 2. Spill btree: header validation on the ORIGINAL bytes, then a
    # full scan on a SCRATCH COPY (BTreeKeyValueStore.open would
    # reinitialize a both-headers-corrupt file — never on the original).
    spill_path = os.path.join(datadir, "tlog.dq.spill")
    if os.path.exists(spill_path) and os.path.getsize(spill_path) > 0:
        from ..fileio import btree as _bt

        raw = open(spill_path, "rb").read()
        valid_headers = 0
        for slot in (0, 1):
            page = raw[slot * _bt.PAGE_SIZE:(slot + 1) * _bt.PAGE_SIZE]
            if (
                len(page) >= 16
                and page[:8] == _bt.HEADER_MAGIC
                and _zlib.crc32(
                    page[16:16 + int.from_bytes(page[8:12], "big")]
                ) == int.from_bytes(page[12:16], "big")
            ):
                valid_headers += 1
        report["spill_valid_headers"] = valid_headers
        if valid_headers == 0:
            report["spill_error"] = "no valid header slot"
            report["ok"] = False
        else:
            tmpd = tempfile.mkdtemp(prefix="kvcheck_")
            try:
                shutil.copy(spill_path, os.path.join(tmpd, "spill"))
                sfs = RealFileSystem(tmpd)

                async def scan_copy():
                    bt = await BTreeKeyValueStore.open(sfs, None, "spill")
                    rows = bt.read_range(b"", b"\xff" * 8, limit=1 << 30)
                    report["spill_rows"] = len(rows)

                try:
                    loop.run_until(
                        loop.spawn(scan_copy(), "kvcheck"), timeout_vt=1e6
                    )
                except FdbError as e:
                    report["spill_error"] = str(e)
                    report["ok"] = False
            finally:
                shutil.rmtree(tmpd, ignore_errors=True)
    # 3. Native engine: open replays the WAL (CRC per record in C), then
    # a full scan touches every row.
    eng_dir = os.path.join(datadir, "engine")
    if os.path.isdir(eng_dir):
        try:
            kv = NativeKeyValueStore(eng_dir)
            rows = kv.read_range(b"", b"\xff\xff\xff", limit=1 << 30)
            report["engine_rows"] = len(rows)
            kv.close()
        except Exception as e:  # noqa: BLE001 - any engine fault = corrupt
            report["engine_error"] = f"{type(e).__name__}: {e}"
            report["ok"] = False
        # Tail classification the replay cannot do: recovery MUST stop at
        # the first bad frame (a torn tail IS the crash model), but an
        # integrity check distinguishes a torn tail (incomplete/invalid
        # FINAL bytes) from MID-FILE corruption (a CRC-valid frame exists
        # beyond the stop point — data recovery silently dropped).
        import glob as _glob
        import zlib as _zlib

        for wal in sorted(_glob.glob(os.path.join(eng_dir, "wal-*"))):
            img = open(wal, "rb").read()
            i = 0
            stop = None
            while i + 8 <= len(img):
                ln = int.from_bytes(img[i:i + 4], "little")
                if i + 8 + ln > len(img):
                    # Incomplete final frame — a torn tail ONLY if nothing
                    # valid follows (a flipped len field mid-file also
                    # lands here; the gap scan below distinguishes).
                    stop = i
                    break
                want = int.from_bytes(img[i + 4:i + 8], "little")
                if _zlib.crc32(img[i + 8:i + 8 + ln]) != want:
                    stop = i
                    break
                i += 8 + ln
            name = os.path.basename(wal)
            report[f"{name}_frames_bytes"] = i
            if stop is None:
                continue
            # Scan beyond the bad frame for any well-formed frame.
            j = stop + 1
            found_valid = False
            while j + 8 <= len(img):
                ln = int.from_bytes(img[j:j + 4], "little")
                if 9 <= ln <= len(img) - j - 8:
                    want = int.from_bytes(img[j + 4:j + 8], "little")
                    if _zlib.crc32(img[j + 8:j + 8 + ln]) == want:
                        found_valid = True
                        break
                j += 1
            if found_valid:
                report[f"{name}_corrupt_at"] = stop
                report["ok"] = False
    print(_json.dumps(report), flush=True)
    return 0 if report["ok"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="mode", required=True)
    s = sub.add_parser("server")
    s.add_argument("--port", type=int, default=0)
    s.add_argument(
        "--datadir",
        default="",
        help="directory for durable storage (native C++ engine); empty = "
        "in-memory only",
    )
    _add_tls_args(s)
    c = sub.add_parser("client")
    c.add_argument("server")
    c.add_argument("--id", default="c1")
    c.add_argument("--ops", type=int, default=20)
    c.add_argument("--check-count", type=int, default=-1)
    c.add_argument("--progress", action="store_true",
                   help="print one OP line per completed transaction")
    _add_tls_args(c)
    ns = sub.add_parser("ntserver")
    ns.add_argument("--port", type=int, default=0)
    _add_tls_args(ns)
    nc = sub.add_parser("ntclient")
    nc.add_argument("server")
    nc.add_argument("--requests", type=int, default=5000)
    nc.add_argument("--parallel", type=int, default=16)
    nc.add_argument("--size", type=int, default=128)
    _add_tls_args(nc)
    kc = sub.add_parser("kvcheck")
    kc.add_argument("--datadir", required=True)
    args = ap.parse_args(argv)
    if args.mode == "kvcheck":
        return run_kvcheck(args.datadir)
    if args.mode == "server":
        run_server(args.port, datadir=args.datadir, tls=_tls_config(args))
    elif args.mode == "ntserver":
        run_ntserver(args.port, tls=_tls_config(args))
    elif args.mode == "ntclient":
        run_ntclient(
            args.server, args.requests, args.parallel, args.size,
            tls=_tls_config(args),
        )
    else:
        run_client(
            args.server, args.id, args.ops, args.check_count,
            tls=_tls_config(args), progress=getattr(args, "progress", False),
        )


if __name__ == "__main__":
    sys.exit(main())
