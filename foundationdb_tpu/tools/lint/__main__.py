"""``python -m foundationdb_tpu.tools.lint`` -> the unified runner."""

import sys

from .runner import main

sys.exit(main())
