"""fdblint: multi-pass AST determinism & actor-hygiene analysis package.

The reference's actor compiler (flow/actorcompiler/ActorCompiler.cs) is a
static gate, not just a code generator: it rejects whole bug classes at
build time — state held across ``wait()``, dropped reply promises,
wall-clock reads in simulated code.  The Python rebuild has no compile
step, so this package fills the role over the repo's ASTs, grown from the
original single-module linter into per-rule passes over a cached project
model:

  base.py       rule registry, findings, pragmas, allowlist config
  local.py      single-module rules: DET001-3, ACT001, JAX001, IO001,
                TRC001, SPN001, ERR001, ENV001
  waitrules.py  WAIT001/WAIT002 — state captured/iterated across await
  rpy.py        RPY001 — reply-promise path analysis (broken-promise hang)
  graphs.py     module graph + call graph from per-file summaries
  det101.py     DET101 — interprocedural determinism taint
  promises.py   PRM001-004/TSK001 — promise lifecycle + wait-graph
                deadlock analysis (hangcheck; ISSUE 13)
  races.py      RACE001-004/ENV002 — await-window atomicity (racecheck;
                PR 16)
  jaxir.py      JXP001-005 — jaxpr/IR structural analysis of the device
                entry points (jaxcheck; ISSUE 7)
  hotpath.py    HOT001-004 — host-path performance discipline: sync
                taint in the dispatch->sync window, declared loop
                bounds, unstaged allocs, scalarization (perfcheck;
                ISSUE 20)
  project.py    project loader, per-file AST/mtime cache, orchestration
  cli.py        text/json/SARIF output, --changed-only git mode
  runner.py     unified multi-tool runner (``python -m
                foundationdb_tpu.tools.lint``): one warm cache, per-tool
                counts, merged SARIF, --pragma-inventory

``foundationdb_tpu/tools/fdblint.py`` stays as the CLI shim; the public
API (lint_source/lint_package/main/RULES/...) is re-exported here so both
import paths keep working.  See README "Determinism rules" for the rule
table and pragma grammar."""

from .base import (  # noqa: F401
    DEFAULT_ALLOW,
    Finding,
    LintConfig,
    Pragma,
    RULES,
    parse_pragmas,
)
from .cli import count_by_rule, format_counts, main, to_sarif  # noqa: F401
from .project import (  # noqa: F401
    Project,
    default_cache_path,
    iter_py_files,
    lint_file,
    lint_package,
    lint_source,
)
