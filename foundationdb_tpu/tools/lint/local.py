"""Per-module (intra-procedural) rule pass.

DET001/DET002/DET003, ACT001, JAX001, IO001, TRC001, ERR001 from the
original single-module fdblint, plus ENV001 (FDB_TPU_* environment reads
outside the flow/knobs.py registry) and SPN001 (leaked open spans —
TRC001's span-layer mirror).  Findings are produced UNFILTERED —
the allowlist config and pragmas are applied by project.py after every
pass has run, which keeps per-file results cacheable independent of
config."""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .base import (
    Aliases,
    ClockRefVisitorMixin,
    ENTROPY_MODULES,
    ENV_FLAG_PREFIX,
    ENV_REGISTRY_GLOBS,
    Finding,
    IO_CALLS,
    IO_MODULES,
    SIMPLE_STMTS,
    THREADING_MODULES,
    TRACED_MODULE_GLOBS,
    WALL_CLOCK,
    _match_any,
    innermost_simple_stmt_end,
)

# Attribute calls that force a device->host sync inside a trace.
JAX_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# Builtins that concretize a traced value (or are pure side effects).
JAX_BAD_BUILTINS = {"print", "breakpoint", "input", "float", "int", "bool"}


class ModuleLinter(ClockRefVisitorMixin, ast.NodeVisitor):
    def __init__(self, relpath: str, tree: ast.Module):
        self.relpath = relpath
        self.tree = tree
        self.aliases = Aliases()
        self.findings: List[Finding] = []
        # ACT001 name scoping: a bare `foo()` statement only matches module-
        # level async functions; `self.foo()` / `cls.foo()` only async
        # methods of the ENCLOSING class (per-class spans below).  Matching
        # any attribute call by name alone drowns real bugs in collisions
        # with generic names (`set`, `sync`) on unrelated objects, and a
        # module-wide method set would still cross-fire between classes.
        self.async_funcs: Set[str] = set()
        # (class start line, class end line, async method names) per class
        self.class_spans: List[Tuple[int, int, Set[str]]] = []
        self.traced = _match_any(relpath, TRACED_MODULE_GLOBS)
        self.env_registry = _match_any(relpath, ENV_REGISTRY_GLOBS)
        # Simple-statement line spans: a pragma anywhere on the physical
        # lines of the statement containing a flagged expression counts
        # (multi-line expressions put the node's lineno above the spot
        # where a trailing comment can live).
        self.stmt_spans: List[Tuple[int, int]] = []
        # Names of functions that are jit-traced (decorated, jax.jit(f),
        # partial(jax.jit, ...)(f), or handed to shard_map).
        self.jitted_names: Set[str] = set()
        # Line spans of jitted function bodies (incl. nested defs).
        self.jitted_spans: List[Tuple[int, int]] = []

    # -- emit --
    _SIMPLE_STMTS = SIMPLE_STMTS

    def flag(self, rule: str, node: ast.AST, message: str,
             end_line: Optional[int] = None):
        if end_line is not None:
            # Caller pinned the pragma scope (ERR001: the `except` line
            # only — its node span covers the whole handler body, which
            # must not become one giant suppression region).
            end = end_line
        else:
            # Pragma scope: through the end of the innermost SIMPLE
            # statement containing the node (see SIMPLE_STMTS).
            end = innermost_simple_stmt_end(node, self.stmt_spans)
        self.findings.append(
            Finding(rule, self.relpath, node.lineno, node.col_offset, message,
                    end_line=end)
        )

    # -- prepass: aliases, async defs, jitted functions --
    def prepass(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                self.aliases.add_import(node)
            elif isinstance(node, ast.ImportFrom):
                self.aliases.add_import_from(node)
            if isinstance(node, self._SIMPLE_STMTS):
                self.stmt_spans.append(
                    (node.lineno, node.end_lineno or node.lineno)
                )
        self._collect_async_defs(self.tree, in_class=False)
        if self.traced:
            self._collect_jitted()

    def _collect_async_defs(self, node: ast.AST, in_class: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.AsyncFunctionDef):
                if not in_class:
                    self.async_funcs.add(child.name)
                self._collect_async_defs(child, in_class=False)
            elif isinstance(child, ast.ClassDef):
                names = {
                    m.name for m in child.body
                    if isinstance(m, ast.AsyncFunctionDef)
                }
                self.class_spans.append(
                    (child.lineno, child.end_lineno or child.lineno, names)
                )
                self._collect_async_defs(child, in_class=True)
            else:
                self._collect_async_defs(child, in_class=in_class)

    def _enclosing_class_async_methods(self, lineno: int) -> Set[str]:
        """Async method names of the innermost class containing lineno."""
        best = None
        for start, end, names in self.class_spans:
            if start <= lineno <= end and (best is None or start > best[0]):
                best = (start, names)
        return best[1] if best else set()

    def _is_jit(self, node: ast.AST) -> bool:
        path = self.aliases.resolve(node)
        return path is not None and (path == "jit" or path.endswith(".jit"))

    def _jit_target_name(self, call: ast.Call) -> Optional[str]:
        """Name of the function a jit/shard_map call wraps, unwrapping one
        level of functools.partial around the target."""
        if not call.args:
            return None
        target = call.args[0]
        if isinstance(target, ast.Call):
            fn = self.aliases.resolve(target.func)
            if fn in ("partial", "functools.partial") and target.args:
                target = target.args[0]
        if isinstance(target, ast.Name):
            return target.id
        return None

    def _collect_jitted(self):
        for node in ast.walk(self.tree):
            # @jit / @jax.jit / @partial(jax.jit, ...)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jit(dec):
                        self.jitted_names.add(node.name)
                    elif isinstance(dec, ast.Call):
                        fn = self.aliases.resolve(dec.func)
                        if self._is_jit(dec.func) or (
                            fn in ("partial", "functools.partial")
                            and dec.args
                            and self._is_jit(dec.args[0])
                        ):
                            self.jitted_names.add(node.name)
            elif isinstance(node, ast.Call):
                fn_path = self.aliases.resolve(node.func)
                # jax.jit(step, ...) / shard_map(body, ...)
                if self._is_jit(node.func) or (
                    fn_path is not None
                    and (fn_path == "shard_map" or fn_path.endswith(".shard_map"))
                ):
                    name = self._jit_target_name(node)
                    if name:
                        self.jitted_names.add(name)
                # partial(jax.jit, ...)(detect_core)
                elif (
                    isinstance(node.func, ast.Call)
                    and self.aliases.resolve(node.func.func)
                    in ("partial", "functools.partial")
                    and node.func.args
                    and self._is_jit(node.func.args[0])
                ):
                    name = self._jit_target_name(node)
                    if name:
                        self.jitted_names.add(name)
        # Body spans: a def whose name is jitted, anywhere in the module
        # (nested defs inside a jitted body fall inside its span).
        for node in ast.walk(self.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in self.jitted_names
            ):
                self.jitted_spans.append((node.lineno, node.end_lineno or node.lineno))

    def _in_jitted(self, node: ast.AST) -> bool:
        ln = getattr(node, "lineno", None)
        return ln is not None and any(a <= ln <= b for a, b in self.jitted_spans)

    # -- visitors --
    def visit_Import(self, node: ast.Import):
        for a in node.names:
            top = a.name.split(".")[0]
            full = a.name
            if top in ENTROPY_MODULES:
                self.flag("DET002", node, f"import of entropy module '{a.name}'")
            if top in THREADING_MODULES or full in THREADING_MODULES:
                self.flag("DET003", node, f"import of '{a.name}'")
            if top in IO_MODULES:
                self.flag("IO001", node, f"import of '{a.name}'")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module is not None and not node.level:
            top = node.module.split(".")[0]
            if top in ENTROPY_MODULES:
                self.flag("DET002", node, f"import from entropy module '{node.module}'")
            if top in THREADING_MODULES or node.module in THREADING_MODULES:
                self.flag("DET003", node, f"import from '{node.module}'")
            if top in IO_MODULES:
                self.flag("IO001", node, f"import from '{node.module}'")
            for a in node.names:
                if f"{node.module}.{a.name}" in WALL_CLOCK:
                    self.flag(
                        "DET001", node,
                        f"import of wall-clock '{node.module}.{a.name}'",
                    )
        self.generic_visit(node)

    def _on_clock_ref(self, node: ast.AST, path: str, kind: str):
        # visit_Attribute/visit_Name come from ClockRefVisitorMixin — the
        # same walk (and base.classify_clock_ref) that seeds DET101 taint
        # in graphs.py, so direct flags and taint sources cannot drift.
        if kind == "wall":
            self.flag("DET001", node, f"wall-clock '{path}'")
        else:
            self.flag("DET002", node, f"entropy source '{path}'")

    def visit_Subscript(self, node: ast.Subscript):
        # ENV001: os.environ["FDB_TPU_X"] (the call forms are in visit_Call).
        if not self.env_registry:
            path = self.aliases.resolve(node.value)
            if path == "os.environ":
                self._check_env_key(node, node.slice)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        # ENV001: `"FDB_TPU_X" in os.environ` — presence-gating is a read.
        if not self.env_registry:
            for op, cmp in zip(node.ops, node.comparators):
                if (
                    isinstance(op, (ast.In, ast.NotIn))
                    and self.aliases.resolve(cmp) == "os.environ"
                ):
                    self._check_env_key(node, node.left)
        self.generic_visit(node)

    def _check_env_key(self, node: ast.AST, key: ast.AST):
        if (
            isinstance(key, ast.Constant)
            and isinstance(key.value, str)
            and key.value.startswith(ENV_FLAG_PREFIX)
        ):
            self.flag(
                "ENV001", node,
                f"'{key.value}' read outside flow/knobs.py — register the "
                f"flag there and read it via g_env (config drift otherwise)",
            )

    def visit_Call(self, node: ast.Call):
        path = self.aliases.resolve(node.func)
        if path is not None and path in IO_CALLS and (
            path == "open" or self.aliases.root_bound(node.func)
        ):
            self.flag("IO001", node, f"direct '{path}()' call")
        if (
            not self.env_registry
            and path in ("os.getenv", "os.environ.get",
                         "os.environ.setdefault", "os.environ.pop")
            and node.args
        ):
            self._check_env_key(node, node.args[0])
        if self._in_jitted(node):
            self._check_jax_call(node, path)
        self.generic_visit(node)

    def _check_jax_call(self, node: ast.Call, path: Optional[str]):
        if isinstance(node.func, ast.Name) and node.func.id in JAX_BAD_BUILTINS:
            self.flag(
                "JAX001", node,
                f"'{node.func.id}()' inside a jit-traced function "
                f"(host sync / trace-time side effect)",
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in JAX_SYNC_METHODS
        ):
            self.flag(
                "JAX001", node,
                f"'.{node.func.attr}()' forces device sync inside a "
                f"jit-traced function",
            )
        elif (
            path is not None
            and path.split(".")[0] in ("numpy", "np")
            and self.aliases.root_bound(node.func)
        ):
            self.flag(
                "JAX001", node,
                f"host numpy call '{path}' inside a jit-traced function",
            )

    # -- ERR001: silent broad excepts --
    _BROAD_EXC = {"Exception", "BaseException",
                  "builtins.Exception", "builtins.BaseException"}

    def _is_broad_except(self, t: Optional[ast.AST]) -> bool:
        if t is None:
            return True  # bare `except:`
        if isinstance(t, ast.Tuple):
            return any(self._is_broad_except(e) for e in t.elts)
        return self.aliases.resolve(t) in self._BROAD_EXC

    def _handler_surfaces_error(self, node: ast.excepthandler) -> bool:
        """True when the handler visibly deals with the error: re-raises
        (anywhere in its body, incl. nested cleanup), TraceEvents it,
        forwards it via send_error, or reads the bound exception name
        (passing it on IS handling; what ERR001 hunts is the error
        vanishing without a trace)."""
        for stmt in node.body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Raise):
                    return True
                if (
                    node.name
                    and isinstance(n, ast.Name)
                    and n.id == node.name
                ):
                    return True
                if isinstance(n, ast.Call):
                    if (
                        isinstance(n.func, ast.Attribute)
                        and n.func.attr == "send_error"
                    ):
                        return True
                    path = self.aliases.resolve(n.func)
                    if path is not None and path.split(".")[-1] == "TraceEvent":
                        return True
        return False

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if self._is_broad_except(node.type) and not self._handler_surfaces_error(node):
            caught = "except:" if node.type is None else (
                f"except {self.aliases.resolve(node.type) or '...'}"
            )
            self.flag(
                "ERR001", node,
                f"'{caught}' swallows errors silently "
                f"(re-raise, TraceEvent, or propagate the error)",
                end_line=node.lineno,
            )
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global):
        if self._in_jitted(node):
            self.flag(
                "JAX001", node,
                f"global mutation of {', '.join(node.names)} inside a "
                f"jit-traced function",
            )
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr):
        # ACT001: statement-level call of a module-local async def whose
        # coroutine object is dropped on the floor.
        v = node.value
        if isinstance(v, ast.Call):
            dropped = None
            if isinstance(v.func, ast.Name) and v.func.id in self.async_funcs:
                dropped = v.func.id
            elif (
                isinstance(v.func, ast.Attribute)
                and isinstance(v.func.value, ast.Name)
                and v.func.value.id in ("self", "cls")
                and v.func.attr
                in self._enclosing_class_async_methods(node.lineno)
            ):
                dropped = v.func.attr
            if dropped is not None:
                self.flag(
                    "ACT001", node,
                    f"coroutine '{dropped}()' is neither awaited nor spawned "
                    f"(dropped actor)",
                )
            self._check_dropped_trace_event(node, v)
            self._check_leaked_span(node, v)
        self.generic_visit(node)

    def _check_dropped_trace_event(self, stmt: ast.Expr, call: ast.Call):
        """TRC001: a statement-level TraceEvent(...) builder chain whose
        outermost call is not .log() — the event is constructed, detailed,
        and dropped (the rebuild has no destructor emit)."""
        methods: List[str] = []
        c: ast.AST = call
        while isinstance(c, ast.Call):
            # The root constructor call: its func is a pure Name/Attribute
            # chain resolving to TraceEvent (bare, aliased, or module-
            # qualified); builder methods between it and the statement are
            # Attribute hops over inner Calls, collected in `methods`.
            path = self.aliases.resolve(c.func)
            if path is not None and path.split(".")[-1] == "TraceEvent":
                if "log" not in methods:
                    self.flag(
                        "TRC001", stmt,
                        "TraceEvent built but never .log()ed nor used as "
                        "a context manager (dropped event)",
                    )
                return
            if not isinstance(c.func, ast.Attribute):
                return
            methods.append(c.func.attr)
            c = c.func.value

    def _check_leaked_span(self, stmt: ast.Expr, call: ast.Call):
        """SPN001 (TRC001's span-layer mirror): a statement-level
        begin_span(...) builder chain whose outermost call is not .end()
        — the open span is dropped on the floor, never closes, and never
        reaches a ring.  Stored results (`sp = begin_span(...)`) and the
        context-manager form (`with begin_span(...)`, an ast.With) are
        the legitimate deferred-end shapes and never arrive here."""
        methods: List[str] = []
        c: ast.AST = call
        while isinstance(c, ast.Call):
            path = self.aliases.resolve(c.func)
            if path is not None and path.split(".")[-1] == "begin_span":
                if "end" not in methods:
                    self.flag(
                        "SPN001", stmt,
                        "begin_span(...) result neither context-managed, "
                        ".end()ed, nor stored (leaked open span)",
                    )
                return
            if not isinstance(c.func, ast.Attribute):
                return
            methods.append(c.func.attr)
            c = c.func.value

    def run(self) -> List[Finding]:
        self.prepass()
        self.visit(self.tree)
        return self.findings
