"""DET101: interprocedural determinism taint.

Seeds the DET001/DET002 source set (direct wall-clock / entropy
references per function, from the cached summaries), propagates backward
through the call graph, and flags every CALL SITE in a sim-surface
function whose callee transitively reaches a source — so a helper three
frames below ``Resolver.resolve_batch`` can no longer hide a
``time.time()``.  Real-mode modules (the DET101 allowlist: tools/,
rpc/real_network.py, ...) are never flagged but still CARRY taint into
any sim-surface caller.

Pragma semantics compose: a ``fdblint: ignore[DET001/DET002/DET101]``
pragma on a source line SANCTIONS it (the reason asserts the site is
fine, so its callers are fine too), and a DET101 pragma on a call site
cuts propagation through that edge.  Fixing or pragma-ing the one
offending frame therefore clears the whole upstream cascade on the next
run."""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, LintConfig, Pragma, pragma_sanctions
from .graphs import CallGraph, ModuleSummary

Node = Tuple[str, str]  # (relpath, qualname)

# A pragma for any of these on the source/call line sanctions it for taint.
_SANCTION_RULES = ("DET001", "DET002", "DET101")


def run_det101(
    summaries: Dict[str, ModuleSummary],
    pragmas_by_file: Dict[str, Dict[int, Pragma]],
    config: LintConfig,
    consumed_pragmas: Optional[Dict[str, Set[int]]] = None,
    graph: Optional[CallGraph] = None,
) -> List[Finding]:
    """`consumed_pragmas` (relpath -> line set), when given, collects the
    DET101 pragmas that did their work by CUTTING taint (sanctioning a
    source or a call edge) — those never see a finding to suppress, so
    the caller must mark them used or PRG002 would call them stale."""
    # `graph` lets the orchestrator share ONE CallGraph with the promise
    # pass (both link the same summaries every lint).
    graph = CallGraph(summaries) if graph is None else graph

    def consume(relpath: str, line: int):
        if consumed_pragmas is not None:
            consumed_pragmas.setdefault(relpath, set()).add(line)

    # Per-node unsanctioned direct sources: node -> (dotted, kind).  A
    # sanctioning pragma counts on ANY physical line of the ref's
    # enclosing simple statement — the same scope suppression uses, so a
    # pragma that appeases DET001 always clears the cascade too.
    sources: Dict[Node, Tuple[str, str]] = {}
    for ms in summaries.values():
        pragmas = pragmas_by_file.get(ms.relpath, {})
        for qual, fs in ms.functions.items():
            for dotted, line, kind, span_end in fs.refs:
                span = range(line, span_end + 1)
                if any(pragma_sanctions(pragmas, ln, _SANCTION_RULES)
                       for ln in span):
                    for ln in span:
                        p = pragmas.get(ln)
                        if p is not None and "DET101" in p.rules:
                            consume(ms.relpath, ln)
                    continue
                sources.setdefault((ms.relpath, qual), (dotted, kind))
                break

    # Forward edges, minus pragma-cut call sites (a DET101 pragma on any
    # physical line of the call expression cuts the edge).  Cut pragmas
    # are only CONSUMED if the callee turns out tainted — a pragma on a
    # call to a clean callee did no work and must age into PRG002.
    fwd: Dict[Node, List[Tuple[Tuple[int, int], Node]]] = {}
    rev: Dict[Node, List[Node]] = {}
    cuts: List[Tuple[str, List[int], Node]] = []
    for caller, span, callee in graph.edges():
        pragmas = pragmas_by_file.get(caller[0], {})
        cut_lines = [
            ln for ln in range(span[0], span[1] + 1)
            if pragma_sanctions(pragmas, ln, ("DET101",))
        ]
        if cut_lines:
            cuts.append((caller[0], cut_lines, callee))
            continue
        fwd.setdefault(caller, []).append((span, callee))
        rev.setdefault(callee, []).append(caller)

    # Reverse BFS from sources; `via` records each tainted node's next hop
    # toward a source so findings can print the offending chain.
    tainted: Set[Node] = set(sources)
    via: Dict[Node, Node] = {}
    frontier = sorted(sources)
    while frontier:
        nxt: List[Node] = []
        for node in frontier:
            for caller in rev.get(node, ()):
                if caller not in tainted:
                    tainted.add(caller)
                    via[caller] = node
                    nxt.append(caller)
        frontier = sorted(set(nxt))

    for relpath, cut_lines, callee in cuts:
        if callee in tainted:
            for ln in cut_lines:
                consume(relpath, ln)

    def chain_of(node: Node, limit: int = 6) -> Tuple[List[str], Tuple[str, str]]:
        names: List[str] = []
        cur = node
        while cur in via and len(names) < limit:
            names.append(cur[1])
            cur = via[cur]
        names.append(cur[1])
        return names, sources.get(cur, ("<source>", "wall"))

    findings: List[Finding] = []
    seen: Set[Tuple[str, int, Node]] = set()
    for ms in summaries.values():
        if config.allows("DET101", ms.relpath):
            continue  # real-mode module: carrier, never a root
        for qual, fs in ms.functions.items():
            node = (ms.relpath, qual)
            if node in sources:
                continue  # DET001/DET002 flag the direct site itself
            for (line, end_line), callee in fwd.get(node, ()):
                if callee not in tainted:
                    continue
                key = (ms.relpath, line, callee)
                if key in seen:
                    continue
                seen.add(key)
                names, (dotted, kind) = chain_of(callee)
                what = "wall-clock" if kind == "wall" else "entropy source"
                findings.append(Finding(
                    "DET101", ms.relpath, line, 0,
                    f"'{qual}' calls '{callee[1]}' which transitively "
                    f"reaches {what} '{dotted}' "
                    f"(chain: {' -> '.join([qual] + names)})",
                    end_line=end_line,
                ))
    return findings
