"""Module graph + call graph over per-file summaries.

Each file is reduced to a picklable ModuleSummary (functions, classes,
import table, per-function call sites and direct wall-clock/entropy
references).  Summaries are cheap to cache per content hash; the linker
(CallGraph) re-resolves cross-module edges on every run, so the
interprocedural pass stays correct when OTHER files change while a file's
own summary is reused.

Resolution is name-based and deliberately modest: module-level functions,
classes (instantiation edges go to __init__ through the MRO), self/cls
method calls through single-inheritance bases, `v = ClassName(...)` local
instance types, `self.attr = ClassName(...)` attribute types, and
re-export chains through package __init__ import tables.  Unresolvable
calls contribute no edge — DET101 under-approximates rather than guessing
(dynamic dispatch it cannot see is what the golden corpus pins)."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .base import (
    Aliases,
    ClockRefVisitorMixin,
    SIMPLE_STMTS,
    attr_chain,
    innermost_simple_stmt_end,
)


def _name_chain(node: ast.AST) -> Optional[tuple]:
    """Picklable ('p0', 'p1', ...) for a pure Name/Attribute chain."""
    parts = attr_chain(node)
    return tuple(parts) if parts is not None else None

# Call-site descriptors (picklable):
#   ("name", n)          bare call  n(...)
#   ("chain", (p0, p1, ...))  pure attribute-chain call  p0.p1....(...)
#   ("super", meth)      super().meth(...)
# Import-table entries:
#   ("mod", dotted)      import x / import a.b  (dotted scan-root-relative
#                        when in-project, else the external absolute name)
#   ("sym", module, name)  from module import name


def module_name_of(relpath: str) -> str:
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclass
class FuncSummary:
    qualname: str                      # "f" or "Class.m"
    line: int
    end_line: int
    is_async: bool
    # (dotted, line, kind, span_end) — span_end is the enclosing simple
    # statement's last line, so source-sanctioning pragmas work on any
    # physical line of a multiline statement, exactly like suppression.
    refs: List[Tuple[str, int, str, int]] = field(default_factory=list)
    # ((line, end_line), descriptor) per call site
    calls: List[Tuple[Tuple[int, int], tuple]] = field(default_factory=list)
    var_ctors: Dict[str, tuple] = field(default_factory=dict)


@dataclass
class ClassSummary:
    name: str
    bases: List[tuple] = field(default_factory=list)   # chain parts per base
    methods: Set[str] = field(default_factory=set)
    attr_ctors: Dict[str, tuple] = field(default_factory=dict)


@dataclass
class ModuleSummary:
    relpath: str
    module: str
    imports: Dict[str, tuple] = field(default_factory=dict)
    functions: Dict[str, FuncSummary] = field(default_factory=dict)
    classes: Dict[str, ClassSummary] = field(default_factory=dict)


def _resolve_relative(relpath: str, level: int, module: Optional[str]) -> str:
    """Scan-root-relative dotted target of a relative import."""
    parts = relpath[:-3].split("/")
    # Dropping the last segment is right for BOTH shapes: a module's
    # containing package, and an __init__'s own package.
    pkg = parts[:-1]
    # level=1 is the containing package; each extra level climbs one more.
    base = pkg[: len(pkg) - (level - 1)] if level - 1 <= len(pkg) else []
    tail = module.split(".") if module else []
    return ".".join(base + tail)


class _FuncCollector(ClockRefVisitorMixin, ast.NodeVisitor):
    """Per-function facts: direct wall/entropy refs + call sites + local
    instance types.  Nested defs and lambdas FOLD into the enclosing
    function: their bodies execute (or are scheduled) from its context, so
    their clock reads and calls are its hazards."""

    def __init__(self, aliases: Aliases, func: FuncSummary,
                 stmt_spans: List[Tuple[int, int]] = ()):
        self.aliases = aliases
        self.func = func
        self.stmt_spans = stmt_spans

    def _on_clock_ref(self, node: ast.AST, path: str, kind: str):
        # visit_Attribute/visit_Name come from ClockRefVisitorMixin — the
        # same walk (and base.classify_clock_ref) behind DET001/DET002
        # direct flagging in local.py, so taint sources cannot drift.
        end = innermost_simple_stmt_end(node, self.stmt_spans)
        self.func.refs.append((path, node.lineno, kind, end))

    def visit_Call(self, node: ast.Call):
        f = node.func
        # Span through the enclosing simple statement, matching the
        # suppression scope: a DET101 edge-cut pragma works on any
        # physical line of a multiline call statement.
        span = (node.lineno, innermost_simple_stmt_end(node, self.stmt_spans))
        if isinstance(f, ast.Name):
            self.func.calls.append((span, ("name", f.id)))
        elif (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Call)
            and isinstance(f.value.func, ast.Name)
            and f.value.func.id == "super"
        ):
            self.func.calls.append((span, ("super", f.attr)))
        else:
            chain = _name_chain(f)
            if chain is not None:
                self.func.calls.append((span, ("chain", chain)))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        # v = ClassName(...) / v = mod.Class(...): local instance type.
        if (
            len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
        ):
            chain = _name_chain(node.value.func)
            if chain is not None:
                self.func.var_ctors[node.targets[0].id] = chain
        self.generic_visit(node)


def collect_summary(relpath: str, tree: ast.Module, root_pkg: Optional[str]) -> ModuleSummary:
    aliases = Aliases()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            aliases.add_import(node)
        elif isinstance(node, ast.ImportFrom):
            aliases.add_import_from(node)
    ms = ModuleSummary(relpath=relpath, module=module_name_of(relpath))

    def norm(dotted: str) -> str:
        if root_pkg and (dotted == root_pkg or dotted.startswith(root_pkg + ".")):
            return dotted[len(root_pkg):].lstrip(".")
        return dotted

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                ms.imports[a.asname or a.name.split(".")[0]] = (
                    ("mod", norm(a.name)) if a.asname else ("mod", norm(a.name.split(".")[0]))
                )
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = _resolve_relative(relpath, node.level, node.module)
            else:
                base = norm(node.module) if node.module else ""
            for a in node.names:
                if a.name == "*":
                    continue
                ms.imports[a.asname or a.name] = ("sym", base, a.name)

    def collect_func(node, qualname: str) -> FuncSummary:
        fs = FuncSummary(
            qualname=qualname,
            line=node.lineno,
            end_line=node.end_lineno or node.lineno,
            is_async=isinstance(node, ast.AsyncFunctionDef),
        )
        spans = [
            (s.lineno, s.end_lineno or s.lineno)
            for s in ast.walk(node)
            if isinstance(s, SIMPLE_STMTS)
        ]
        fc = _FuncCollector(aliases, fs, spans)
        for stmt in node.body:
            fc.visit(stmt)
        return fs

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ms.functions[node.name] = collect_func(node, node.name)
        elif isinstance(node, ast.ClassDef):
            cs = ClassSummary(name=node.name)
            for b in node.bases:
                chain = _name_chain(b)
                if chain is not None:
                    cs.bases.append(chain)
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{node.name}.{m.name}"
                    cs.methods.add(m.name)
                    ms.functions[qn] = collect_func(m, qn)
                    # self.attr = ClassName(...) attribute types.
                    for stmt in ast.walk(m):
                        if (
                            isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Attribute)
                            and isinstance(stmt.targets[0].value, ast.Name)
                            and stmt.targets[0].value.id == "self"
                            and isinstance(stmt.value, ast.Call)
                        ):
                            chain = _name_chain(stmt.value.func)
                            if chain is not None:
                                cs.attr_ctors.setdefault(stmt.targets[0].attr, chain)
            ms.classes[node.name] = cs
    return ms


class CallGraph:
    """Links ModuleSummaries into (relpath, qualname) -> callee edges."""

    _MAX_DEPTH = 8

    def __init__(self, summaries: Dict[str, ModuleSummary]):
        # Keyed by module dotted name for import resolution.
        self.by_module: Dict[str, ModuleSummary] = {
            s.module: s for s in summaries.values()
        }
        self.summaries = summaries

    # -- symbol resolution -------------------------------------------------
    def _lookup_symbol(self, module: str, name: str, depth: int = 0):
        """Resolve `name` exported by `module` to ("func", ms, qualname) |
        ("class", ms, classname) | None, chasing re-exports."""
        if depth > self._MAX_DEPTH:
            return None
        ms = self.by_module.get(module)
        if ms is None:
            return None
        if name in ms.classes:
            return ("class", ms, name)
        if name in ms.functions and "." not in name:
            return ("func", ms, name)
        imp = ms.imports.get(name)
        if imp is not None:
            if imp[0] == "sym":
                got = self._lookup_symbol(imp[1], imp[2], depth + 1)
                if got is not None:
                    return got
                if f"{imp[1]}.{imp[2]}" in self.by_module or (
                    not imp[1] and imp[2] in self.by_module
                ):
                    sub = f"{imp[1]}.{imp[2]}" if imp[1] else imp[2]
                    return ("mod", self.by_module[sub], None)
            elif imp[0] == "mod" and imp[1] in self.by_module:
                return ("mod", self.by_module[imp[1]], None)
        # `from pkg import submodule` styled as sym but naming a module.
        sub = f"{module}.{name}" if module else name
        if sub in self.by_module:
            return ("mod", self.by_module[sub], None)
        return None

    def _mro_method(self, ms: ModuleSummary, classname: str, meth: str,
                    depth: int = 0):
        """(ms, qualname) for `meth` on `classname` or its bases."""
        if depth > self._MAX_DEPTH:
            return None
        cs = ms.classes.get(classname)
        if cs is None:
            return None
        if meth in cs.methods:
            return (ms, f"{classname}.{meth}")
        for base in cs.bases:
            got = self._resolve_class_chain(ms, base)
            if got is not None:
                bms, bname = got
                found = self._mro_method(bms, bname, meth, depth + 1)
                if found is not None:
                    return found
        return None

    def _resolve_class_chain(self, ms: ModuleSummary, chain: tuple):
        """(ms, classname) for a chain like ("ClassName",) or
        ("alias", "ClassName") in module `ms`'s namespace."""
        if len(chain) == 1:
            if chain[0] in ms.classes:
                return (ms, chain[0])
            got = self._lookup_symbol(ms.module, chain[0])
            if got is not None and got[0] == "class":
                return (got[1], got[2])
            return None
        got = self._lookup_symbol(ms.module, chain[0])
        if got is None:
            return None
        kind, target, name = got
        if kind == "mod" and len(chain) == 2:
            inner = self._lookup_symbol(target.module, chain[1])
            if inner is not None and inner[0] == "class":
                return (inner[1], inner[2])
        return None

    def _class_node(self, ms: ModuleSummary, classname: str):
        """Instantiation edge target: __init__ through the MRO."""
        return self._mro_method(ms, classname, "__init__")

    # -- call-site resolution ---------------------------------------------
    def resolve_call(self, ms: ModuleSummary, caller_qual: str, desc: tuple):
        """(relpath, qualname) of the callee, or None."""
        cls = caller_qual.split(".")[0] if "." in caller_qual else None
        fs = ms.functions.get(caller_qual)
        kind = desc[0]
        if kind == "name":
            n = desc[1]
            if n in ms.functions and "." not in n:
                return (ms.relpath, n)
            got = self._lookup_symbol(ms.module, n)
            if got is None:
                return None
            if got[0] == "func":
                return (got[1].relpath, got[2])
            if got[0] == "class":
                init = self._class_node(got[1], got[2])
                if init is not None:
                    return (init[0].relpath, init[1])
            return None
        if kind == "super":
            if cls is None:
                return None
            cs = ms.classes.get(cls)
            if cs is None:
                return None
            for base in cs.bases:
                got = self._resolve_class_chain(ms, base)
                if got is not None:
                    found = self._mro_method(got[0], got[1], desc[1])
                    if found is not None:
                        return (found[0].relpath, found[1])
            return None
        chain = desc[1]
        root = chain[0]
        if root in ("self", "cls") and cls is not None:
            if len(chain) == 2:
                found = self._mro_method(ms, cls, chain[1])
                return (found[0].relpath, found[1]) if found else None
            if len(chain) == 3:
                # self.attr.m(): via the class's attribute ctor types.
                ctor = self._attr_ctor(ms, cls, chain[1])
                if ctor is not None:
                    got = self._resolve_class_chain(ctor[0], ctor[1])
                    if got is not None:
                        found = self._mro_method(got[0], got[1], chain[2])
                        if found is not None:
                            return (found[0].relpath, found[1])
            return None
        if fs is not None and root in fs.var_ctors and len(chain) == 2:
            got = self._resolve_class_chain(ms, fs.var_ctors[root])
            if got is not None:
                found = self._mro_method(got[0], got[1], chain[1])
                if found is not None:
                    return (found[0].relpath, found[1])
            return None
        if root in ms.classes and len(chain) == 2:
            found = self._mro_method(ms, root, chain[1])
            return (found[0].relpath, found[1]) if found else None
        got = self._lookup_symbol(ms.module, root)
        if got is None:
            return None
        kind2, target, name = got
        if kind2 == "mod":
            if len(chain) == 2:
                inner = self._lookup_symbol(target.module, chain[1])
                if inner is not None:
                    if inner[0] == "func":
                        return (inner[1].relpath, inner[2])
                    if inner[0] == "class":
                        init = self._class_node(inner[1], inner[2])
                        if init is not None:
                            return (init[0].relpath, init[1])
            elif len(chain) == 3:
                inner = self._lookup_symbol(target.module, chain[1])
                if inner is not None and inner[0] == "class":
                    found = self._mro_method(inner[1], inner[2], chain[2])
                    if found is not None:
                        return (found[0].relpath, found[1])
            return None
        if kind2 == "class" and len(chain) == 2:
            found = self._mro_method(target, name, chain[1])
            return (found[0].relpath, found[1]) if found else None
        return None

    def _attr_ctor(self, ms: ModuleSummary, classname: str, attr: str,
                   depth: int = 0):
        """(defining ModuleSummary, ctor chain) for self.<attr>, walking
        bases for attributes assigned by an inherited __init__."""
        if depth > self._MAX_DEPTH:
            return None
        cs = ms.classes.get(classname)
        if cs is None:
            return None
        if attr in cs.attr_ctors:
            return (ms, cs.attr_ctors[attr])
        for base in cs.bases:
            got = self._resolve_class_chain(ms, base)
            if got is not None:
                found = self._attr_ctor(got[0], got[1], attr, depth + 1)
                if found is not None:
                    return found
        return None

    def edges(self):
        """Yield ((caller_relpath, caller_qual), (line, end_line),
        (callee_relpath, callee_qual)) for every resolvable call site."""
        for ms in self.summaries.values():
            for qual, fs in ms.functions.items():
                for span, desc in fs.calls:
                    callee = self.resolve_call(ms, qual, desc)
                    if callee is not None and in_nodes(self.summaries, callee):
                        yield ((ms.relpath, qual), span, callee)


def in_nodes(summaries: Dict[str, ModuleSummary], node) -> bool:
    ms = summaries.get(node[0])
    return ms is not None and node[1] in ms.functions
