"""Unified lint runner: fdblint + perfcheck + jaxcheck from one load.

``python -m foundationdb_tpu.tools.lint --all`` is the ONE gate
entrypoint (ISSUE 20): the source-level tools (fdblint's determinism/
actor/race families and perfcheck's HOT family) share a single warm
Project cache and CallGraph, jaxcheck traces the registered device
entry points, and the output is per-tool/per-rule counts, one merged
JSON doc, or ONE merged SARIF document with one run per tool —
exactly what CI uploads as a single artifact.

``--pragma-inventory`` lists every suppression across all three pragma
namespaces as a canonical sorted JSON doc (file, line, tool, rules,
reason) — the auditable registry of everything the repo has chosen to
silence."""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from .base import Finding, LintConfig, RULES, parse_pragmas
from .cli import SARIF_SCHEMA, count_by_rule, format_counts, to_sarif
from .hotpath import HOT_RULES
from .project import Project, iter_py_files

# Every pragma namespace the repo uses: tool marker -> rule universe.
PRAGMA_TOOLS: Tuple[str, ...] = ("fdblint", "jaxcheck", "perfcheck")

SOURCE_TOOLS = ("fdblint", "perfcheck")


def _default_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_source_tools(
    root: str,
    config: LintConfig,
    tools=SOURCE_TOOLS,
    use_cache: bool = True,
) -> Dict[str, List[Finding]]:
    """fdblint and/or perfcheck findings per tool, from ONE warm load
    (the Project caches per-file facts for both namespaces together)."""
    proj = Project(root, config, use_cache=use_cache)
    proj.load()
    return {t: proj.lint(tools=(t,)) for t in tools if t in SOURCE_TOOLS}


def run_jax_tool(config: LintConfig) -> List[Finding]:
    """jaxcheck over the default device-entry registry (traces on CPU)."""
    from .jaxir import _ensure_cpu, run_jaxcheck

    _ensure_cpu()
    return run_jaxcheck(config=config)


def pragma_inventory(root: str) -> List[dict]:
    """Every suppression in every namespace, canonically sorted: the
    stale-pragma sweep reads this (a pragma that suppresses nothing is
    ALSO a PRG002 finding, so the gate catches staleness; the inventory
    is the human-auditable registry)."""
    out: List[dict] = []
    for path in iter_py_files(root):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        for tool in PRAGMA_TOOLS:
            for line, p in parse_pragmas(source, tool=tool).items():
                out.append({
                    "file": relpath,
                    "line": line,
                    "tool": tool,
                    "rules": sorted(p.rules),
                    "reason": p.reason,
                })
    out.sort(key=lambda d: (d["file"], d["line"], d["tool"]))
    return out


def merged_sarif(by_tool: Dict[str, List[Finding]],
                 show_suppressed: bool) -> dict:
    """ONE SARIF document, one run per tool (the merge CI uploads)."""
    rule_sets = {"fdblint": RULES, "perfcheck": HOT_RULES}
    runs = []
    for tool, findings in by_tool.items():
        if tool == "jaxcheck":
            from .jaxir import JAX_RULES

            rules = JAX_RULES
        else:
            rules = rule_sets.get(tool, RULES)
        shown = (findings if show_suppressed
                 else [f for f in findings if not f.suppressed])
        runs.extend(to_sarif(shown, rules=rules, tool=tool)["runs"])
    return {"$schema": SARIF_SCHEMA, "version": "2.1.0", "runs": runs}


def format_tool_counts(by_tool: Dict[str, List[Finding]]) -> List[str]:
    lines = []
    for tool in sorted(by_tool):
        findings = by_tool[tool]
        n_un = sum(1 for f in findings if not f.suppressed)
        n_sup = len(findings) - n_un
        lines.append(
            f"[{tool}] {n_un} finding(s), {n_sup} suppressed; "
            + format_counts(findings)
        )
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.tools.lint",
        description="Unified lint gate: fdblint + perfcheck (+ jaxcheck "
                    "with --all) from one warm cache, one merged report.",
    )
    ap.add_argument("root", nargs="?", default=None,
                    help="package dir to lint (default: foundationdb_tpu)")
    ap.add_argument("--all", action="store_true",
                    help="also run jaxcheck (traces device entry points)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--config",
                    help="JSON allowlist config to merge over defaults")
    ap.add_argument("--show-suppressed", action="store_true")
    ap.add_argument("--no-cache", dest="cache", action="store_false",
                    default=True)
    ap.add_argument("--pragma-inventory", action="store_true",
                    help="print every suppression in every namespace as "
                         "canonical sorted JSON and exit 0")
    args = ap.parse_args(argv)

    root = args.root or _default_root()

    if args.pragma_inventory:
        print(json.dumps(pragma_inventory(root), indent=2))
        return 0

    config = LintConfig.load(args.config) if args.config else LintConfig()

    by_tool = run_source_tools(root, config, use_cache=args.cache)
    if args.all:
        by_tool["jaxcheck"] = run_jax_tool(config)

    all_findings = [f for fs in by_tool.values() for f in fs]
    unsuppressed = [f for f in all_findings if not f.suppressed]

    if args.format == "json":
        print(json.dumps(
            {
                "tools": {
                    tool: {
                        "findings": [
                            f.to_dict() for f in fs
                            if args.show_suppressed or not f.suppressed
                        ],
                        "total": len(fs),
                        "unsuppressed": sum(
                            1 for f in fs if not f.suppressed),
                        "counts": count_by_rule(fs),
                    }
                    for tool, fs in sorted(by_tool.items())
                },
                "total": len(all_findings),
                "unsuppressed": len(unsuppressed),
            },
            indent=2,
        ))
    elif args.format == "sarif":
        print(json.dumps(
            merged_sarif(by_tool, args.show_suppressed), indent=2))
    else:
        for tool in sorted(by_tool):
            for f in by_tool[tool]:
                if f.suppressed and not args.show_suppressed:
                    continue
                tag = (" (suppressed: %s)" % f.reason
                       if f.suppressed else "")
                print(f"[{tool}] " + f.format() + tag)
        for line in format_tool_counts(by_tool):
            print(line, file=sys.stderr)
        print(
            f"lint: {len(unsuppressed)} finding(s), "
            f"{len(all_findings) - len(unsuppressed)} suppressed across "
            f"{len(by_tool)} tool(s)",
            file=sys.stderr,
        )
    return 1 if unsuppressed else 0


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    sys.exit(main())
