"""PRM/TSK rule family: interprocedural promise-lifecycle & wait-graph
analysis — the static hang-check the reference gets from Promise
destructor semantics (flow/flow.h: destroying a Promise sends
broken_promise to every waiter; our flow/error.py reserves the code).
The rebuild's Promise has no destructor backstop, so an orphaned future
or a dropped promise is a SILENT park: the waiter never wakes, no error
flows, nothing times out in virtual time.  These rules make that a
static class, the way RPY001 did for reply params:

  PRM001  orphaned wait — a future awaited where no code anywhere in the
          project can send to its paired promise (the static hang)
  PRM002  dropped promise — a control-flow path that abandons a held
          promise without send/send_error/close (RPY001 generalized from
          reply params to all promises, incl. handoff into a callee that
          can drop it)
  PRM003  wait-cycle — SCCs in the actor wait-graph (A awaits a future
          whose only senders live in B, and conversely) with no external
          sender: the static deadlock class
  PRM004  producerless stream loop — a consumer loop over a PromiseStream
          every producer of which can terminate without closing it (the
          pipeline idle-flush/drain shape)
  TSK001  unobserved spawned task — a spawn whose Task is dropped and
          whose coroutine can raise with neither a handler nor a
          TraceEvent (ACT001's mirror at the Task layer: FdbErrors in a
          dropped Task vanish — EventLoop only surfaces non-FdbError
          crashes)

Facts are collected per file into picklable ModulePromiseFacts (cached by
project.py exactly like ModuleSummary); the linking pass re-resolves
cross-file sender/waiter sets and the call graph on every run, so a send
added or removed in a PRODUCER file correctly clears or raises a
consumer-side finding from warm cache.

Everything is three-valued and deliberately conservative: an entity that
ESCAPES tracking (aliased, stored into a container, passed into an
unresolvable call, reached into past its public surface) is assumed to
have senders — the pass under-approximates, never guesses.  What it
cannot see statically is cross-validated by the dynamic loop-teardown
twin in flow/sim_validation.py (expect_no_orphaned_waits)."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, innermost_simple_stmt_end
from .graphs import CallGraph, ModuleSummary, _name_chain, in_nodes
from .rpy import _scan_acquisition

# Constructor names that create a tracked write-side entity.
PROMISE_CTORS = {"Promise": "promise", "PromiseStream": "stream"}
# Ops on the write side; "pop" is the stream read side.
SEND_OPS = ("send", "send_error", "close")
# Reads of an entity that can never conjure a sender (inspection and the
# read-side future handle) — these do NOT void tracking.
HARMLESS_ATTRS = {"future", "future_stream", "is_set", "is_ready", "pop"}

Node = Tuple[str, str]    # (relpath, qualname)
Entity = Tuple[str, str, str]  # (relpath, class, attr)


# ---------------------------------------------------------------------------
# Per-file facts (picklable, cached)
# ---------------------------------------------------------------------------


@dataclass
class FuncFacts:
    qualname: str
    line: int
    is_async: bool
    params: Tuple[str, ...] = ()
    # var -> (kind, line, end_line) for `v = Promise()` / `v = PromiseStream()`
    local_creations: Dict[str, Tuple[str, int, int]] = field(default_factory=dict)
    # (attr, kind, line) for `self.attr = Promise()`
    attr_creations: List[Tuple[str, str, int]] = field(default_factory=list)
    # (chain, op, line, end_line, in_unbroken_infinite_loop)
    sends: List[Tuple[tuple, str, int, int, bool]] = field(default_factory=list)
    # (chain, wkind, line, end_line, in_loop) — wkind "future"|"pop"|"bare"
    waits: List[Tuple[tuple, str, int, int, bool]] = field(default_factory=list)
    # (var, call_desc, arg_index, line, end_line) — bare tracked local
    # passed positionally into a call
    arg_passes: List[Tuple[str, tuple, int, int, int]] = field(default_factory=list)
    # chains used in untracked contexts (alias, store, container, reach-in)
    escapes: List[Tuple[tuple, int]] = field(default_factory=list)
    # var -> count of bare-Name uses beyond the ctor target
    mentions: Dict[str, int] = field(default_factory=dict)
    # (var, kind, ctor_line, ctor_end, ((leak_line, how), ...)) — PRM002
    drop_leaks: List[Tuple[str, str, int, int, tuple]] = field(default_factory=list)
    # param -> ((leak_line, how), ...) — nonempty = this callee can drop it
    param_leaks: Dict[str, tuple] = field(default_factory=dict)
    # (arg_desc|None, line, end_line) for statement-level dropped spawns
    spawn_drops: List[Tuple[Optional[tuple], int, int]] = field(default_factory=list)
    has_handler: bool = False
    has_trace: bool = False
    can_raise: bool = False


@dataclass
class ModulePromiseFacts:
    relpath: str
    funcs: Dict[str, FuncFacts] = field(default_factory=dict)


# The one shared picklable-chain extractor (base.attr_chain tuple-wrapped
# by graphs._name_chain) — the same descriptors the call graph links on.
_chain = _name_chain


def _call_desc(func: ast.AST) -> Optional[tuple]:
    if isinstance(func, ast.Name):
        return ("name", func.id)
    ch = _chain(func)
    return ("chain", ch) if ch is not None else None


def _has_own_break(loop: ast.AST) -> bool:
    """Whether `loop`'s body contains a break that exits LOOP itself —
    breaks inside nested loops/defs leave only the inner construct."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(loop))
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Break):
            return True
        if isinstance(n, (ast.For, ast.AsyncFor, ast.While,
                          ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


def _ctor_kind(call: ast.Call) -> Optional[str]:
    """"promise"/"stream" when the call constructs a tracked entity.
    Name-based on the final segment: `Promise(...)`, `future.Promise(...)`
    both match regardless of import aliasing (an exotic alias costs a
    false negative; a false positive is impossible — nothing else in the
    repo is named Promise/PromiseStream)."""
    ch = _chain(call.func)
    if ch is None:
        return None
    return PROMISE_CTORS.get(ch[-1])


class _FactCollector(ast.NodeVisitor):
    """One function's promise facts.  Nested defs/lambdas are opaque for
    CREATIONS and WAITS (walk_defs gives each nested def its own facts)
    but their SENDS are folded into the enclosing function — a deferred
    send registered from a closure is still a live sender for the
    enclosing frame's entities."""

    def __init__(self, facts: FuncFacts, stmt_spans):
        self.facts = facts
        self.stmt_spans = stmt_spans
        self._loop_depth = 0
        self._inf_loops: List[ast.While] = []
        self._nesting = 0  # >0 inside a nested def/lambda/class

    def _end(self, node) -> int:
        return innermost_simple_stmt_end(node, self.stmt_spans)

    # -- structure ---------------------------------------------------------
    def visit_FunctionDef(self, node):
        self._nesting += 1
        self.generic_visit(node)
        self._nesting -= 1

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _visit_loop(self, node, infinite: bool):
        self._loop_depth += 1
        if infinite:
            self._inf_loops.append(node)
        self.generic_visit(node)
        if infinite:
            self._inf_loops.pop()
        self._loop_depth -= 1

    def visit_For(self, node):
        self._visit_loop(node, False)

    visit_AsyncFor = visit_For

    def visit_While(self, node):
        infinite = isinstance(node.test, ast.Constant) and bool(node.test.value)
        self._visit_loop(node, infinite)

    def _in_unbroken_infinite_loop(self) -> bool:
        """True at a site inside a `while True:` with no break that exits
        THAT loop — a producer here can never terminate normally.  A break
        belonging to a nested loop (or a nested def) does not count: it
        only leaves the inner construct."""
        return any(not _has_own_break(loop) for loop in self._inf_loops)

    # -- sites -------------------------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        if (
            not self._nesting
            and len(node.targets) == 1
            and isinstance(node.value, ast.Call)
        ):
            kind = _ctor_kind(node.value)
            if kind is not None:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    self.facts.local_creations[t.id] = (
                        kind, node.lineno, self._end(node)
                    )
                elif (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    self.facts.attr_creations.append((t.attr, kind, node.lineno))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in SEND_OPS:
            ch = _chain(f.value)
            if ch is not None:
                self.facts.sends.append(
                    (ch, f.attr, node.lineno, self._end(node),
                     self._in_unbroken_infinite_loop())
                )
        self.generic_visit(node)

    def visit_Await(self, node: ast.Await):
        if self._nesting:
            self.generic_visit(node)
            return
        v = node.value
        rec = None
        if isinstance(v, ast.Attribute) and v.attr == "future":
            ch = _chain(v.value)
            if ch is not None:
                rec = (ch, "future")
        elif (
            isinstance(v, ast.Call)
            and isinstance(v.func, ast.Attribute)
            and v.func.attr == "pop"
        ):
            ch = _chain(v.func.value)
            if ch is not None:
                rec = (ch, "pop")
        elif isinstance(v, (ast.Name, ast.Attribute)):
            ch = _chain(v)
            if ch is not None:
                rec = (ch, "bare")
        if rec is not None:
            self.facts.waits.append(
                (rec[0], rec[1], node.lineno, self._end(node),
                 self._loop_depth > 0)
            )
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr):
        # Statement-level spawn with the Task dropped on the floor.
        v = node.value
        if not self._nesting and isinstance(v, ast.Call):
            # Raw `.spawn` only: spawn_observed/spawn_owned attach a death
            # observer by construction, which is exactly the remedy this
            # rule demands.
            if isinstance(v.func, ast.Attribute) and v.func.attr == "spawn":
                arg = v.args[0] if v.args else None
                desc = _call_desc(arg.func) if isinstance(arg, ast.Call) else None
                self.facts.spawn_drops.append(
                    (desc, node.lineno, self._end(node))
                )
        self.generic_visit(node)


class _MentionClassifier:
    """Second pass over a function body: classify every pure Name/Attribute
    chain as harmless, an op already recorded, a bare arg pass, or an
    ESCAPE that voids tracking (for locals, of the var; for attr chains,
    of every non-harmless attribute segment — name-global)."""

    def __init__(self, func_node, facts: FuncFacts, stmt_spans):
        self.func = func_node
        self.facts = facts
        self.stmt_spans = stmt_spans
        self.parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(func_node):
            for child in ast.iter_child_nodes(parent):
                self.parents[id(child)] = parent

    def run(self):
        # Locals created here AND the function's own params: param uses
        # feed the may-send fixpoint (a param forwarded to a sending
        # callee carries "may send" back through the chain).
        tracked = set(self.facts.local_creations) | (
            set(self.facts.params) - {"self", "cls"}
        )
        for node in ast.walk(self.func):
            if isinstance(node, ast.Name) and node.id in tracked:
                self._classify_local(node)
            elif isinstance(node, ast.Attribute):
                parent = self.parents.get(id(node))
                if isinstance(parent, ast.Attribute) and parent.value is node:
                    continue  # not the topmost link of its chain
                ch = _chain(node)
                if ch is not None and len(ch) >= 2 and ch[0] not in tracked:
                    self._classify_chain(node, ch)

    def _escape_chain(self, ch: tuple, line: int):
        self.facts.escapes.append((ch, line))

    def _classify_chain(self, top: ast.Attribute, ch: tuple):
        """An attribute chain NOT rooted at a tracked local.  If its use is
        anything beyond the recorded ops and the harmless read surface,
        every non-harmless attr segment is marked escaped — someone we
        cannot see may send through (or reach into) the entity."""
        parent = self.parents.get(id(top))
        if isinstance(parent, ast.Await):
            return  # recorded as a wait
        if (
            isinstance(parent, ast.Call)
            and parent.func is top
            and ch[-1] in SEND_OPS + ("pop",)
        ):
            return  # recorded as a send op / harmless stream read
        if all(a in HARMLESS_ATTRS for a in ch[1:]):
            return
        if isinstance(parent, ast.Assign) and any(
            t is top for t in parent.targets
        ):
            return  # a (re)bind of the attribute, incl. the creation itself
        if isinstance(parent, (ast.Delete,)):
            return
        self._escape_chain(ch, top.lineno)

    def _classify_local(self, name: ast.Name):
        var = name.id
        parent = self.parents.get(id(name))
        if isinstance(parent, ast.Assign) and any(
            t is name for t in parent.targets
        ):
            return  # the creation itself, or a clean rebind ending tracking
        self.facts.mentions[var] = self.facts.mentions.get(var, 0) + 1
        # Walk up the pure attribute chain rooted at this Name.
        top: ast.AST = name
        p = parent
        while isinstance(p, ast.Attribute) and p.value is top:
            top = p
            p = self.parents.get(id(top))
        if top is not name:
            attrs = _chain(top)[1:]
            if (
                isinstance(p, ast.Call)
                and p.func is top
                and attrs[-1] in SEND_OPS + ("pop",)
            ):
                return  # recorded op
            if all(a in HARMLESS_ATTRS for a in attrs):
                return  # read side only: cannot conjure a sender
            self._escape_chain(_chain(top), name.lineno)
            return
        # Bare var.
        if isinstance(p, ast.Await):
            return  # recorded as a wait
        if isinstance(p, ast.Call) and p.func is not name and any(
            a is name for a in p.args
        ):
            desc = _call_desc(p.func)
            if desc is not None:
                self.facts.arg_passes.append(
                    (var, desc, next(
                        i for i, a in enumerate(p.args) if a is name
                    ), name.lineno,
                     innermost_simple_stmt_end(name, self.stmt_spans))
                )
                return
            self._escape_chain((var,), name.lineno)
            return
        if isinstance(p, (ast.If, ast.While)) and getattr(p, "test", None) is name:
            return  # bare truth test: inspection only
        # Return/yield/store/alias/subscript/kwarg/comprehension/...
        self._escape_chain((var,), name.lineno)


def collect_promise_facts(relpath: str, tree: ast.Module) -> ModulePromiseFacts:
    mf = ModulePromiseFacts(relpath=relpath)

    def collect_func(node, qualname: str) -> FuncFacts:
        spans = [
            (s.lineno, s.end_lineno or s.lineno)
            for s in ast.walk(node)
            if isinstance(s, ast.stmt)
        ]
        ff = FuncFacts(
            qualname=qualname,
            line=node.lineno,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            params=tuple(
                a.arg for a in (
                    node.args.posonlyargs + node.args.args
                    + node.args.kwonlyargs
                )
            ),
        )
        fc = _FactCollector(ff, spans)
        for stmt in node.body:
            fc.visit(stmt)
        _MentionClassifier(node, ff, spans).run()
        # PRM002 locals: RPY001's conservative path walk, acquisition = the
        # constructor statement (a mention anywhere = resolve/handoff; a
        # ctor inside a nested def is that def's own acquisition and walks
        # silent here).
        for stmt in ast.walk(node):
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)
            ):
                kind = _ctor_kind(stmt.value)
                if kind is None:
                    continue
                var = stmt.targets[0].id
                leaks = _scan_acquisition(node, stmt, var)
                if leaks:
                    ff.drop_leaks.append(
                        (var, kind, stmt.lineno,
                         stmt.end_lineno or stmt.lineno,
                         tuple(sorted(set(leaks))[:4]))
                    )
        # PRM002 interprocedural: which params can this function DROP on
        # some path?  Consulted only when a caller hands a tracked promise
        # into the param, so computing it for every param is cheap facts,
        # not findings.
        for p in ff.params:
            if p in ("self", "cls"):
                continue
            leaks = _scan_acquisition(node, None, p)
            if leaks:
                ff.param_leaks[p] = tuple(sorted(set(leaks))[:4])
        for n in ast.walk(node):
            if isinstance(n, ast.ExceptHandler):
                ff.has_handler = True
            elif isinstance(n, (ast.Raise, ast.Await)):
                ff.can_raise = True
            elif isinstance(n, ast.Call):
                ch = _chain(n.func)
                if ch is not None and ch[-1] in ("TraceEvent", "trace_batch"):
                    ff.has_trace = True
        return ff

    def walk_defs(body, prefix: str):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{node.name}"
                mf.funcs[qn] = collect_func(node, qn)
                walk_defs(node.body, f"{qn}.")
            elif isinstance(node, ast.ClassDef):
                walk_defs(node.body, f"{prefix}{node.name}.")

    walk_defs(tree.body, "")
    return mf


# ---------------------------------------------------------------------------
# Linking
# ---------------------------------------------------------------------------


def _class_of(qual: str) -> Optional[str]:
    return qual.split(".")[0] if "." in qual else None


class _Linker:
    """Cross-file resolution shared by all five rules: name-global attr
    indexes (safe over-approximation of senders), class-resolved entity
    attribution through the call graph's MRO machinery (the precision
    PRM003/PRM004 need), and the param may-send fixpoint."""

    def __init__(
        self,
        summaries: Dict[str, ModuleSummary],
        facts: Dict[str, ModulePromiseFacts],
        graph: Optional[CallGraph] = None,
    ):
        self.summaries = summaries
        self.facts = facts
        self.graph = CallGraph(summaries) if graph is None else graph
        self._build_name_indexes()
        self._build_resolved_sites()
        self._fixpoint_param_senders()

    # -- name-global attr indexes (senders over-approximated) --------------
    def _build_name_indexes(self):
        self.attr_creations: Dict[str, List[Tuple[str, str, str, int]]] = {}
        self.attr_sends: Dict[str, List[Tuple[str, str, str, int]]] = {}
        self.attr_closers: Dict[str, List[Tuple[str, str, str, int]]] = {}
        self.attr_escapes: Dict[str, List[Tuple[str, int]]] = {}
        for rp, mf in self.facts.items():
            for qual, ff in mf.funcs.items():
                for attr, kind, line in ff.attr_creations:
                    self.attr_creations.setdefault(attr, []).append(
                        (rp, qual, kind, line)
                    )
                for ch, op, line, _e, _inf in ff.sends:
                    if len(ch) >= 2:
                        slot = (
                            self.attr_sends if op == "send"
                            else self.attr_closers
                        )
                        slot.setdefault(ch[-1], []).append((rp, qual, op, line))
                for ch, line in ff.escapes:
                    for seg in ch[1:]:
                        if seg not in HARMLESS_ATTRS and seg not in SEND_OPS:
                            self.attr_escapes.setdefault(seg, []).append(
                                (rp, line)
                            )

    # -- class-resolved sites (PRM003/PRM004 precision) --------------------
    def _build_resolved_sites(self):
        # Entity -> [(node, op, line, end, in_infinite_loop)]
        self.res_sends: Dict[Entity, List[Tuple[Node, str, int, int, bool]]] = {}
        # Entity -> [(node, wkind, line, end, in_loop)]
        self.res_waits: Dict[Entity, List[Tuple[Node, str, int, int, bool]]] = {}
        # Attr names where some send failed to resolve to an entity —
        # an unseen receiver may satisfy waits on same-named entities.
        self.dirty_attrs: Set[str] = set(self.attr_escapes)
        for rp, mf in self.facts.items():
            for qual, ff in mf.funcs.items():
                node = (rp, qual)
                for ch, op, line, end, inf in ff.sends:
                    if len(ch) < 2:
                        continue
                    ent = self.resolve_entity(rp, qual, ch)
                    if ent is None:
                        self.dirty_attrs.add(ch[-1])
                    else:
                        self.res_sends.setdefault(ent, []).append(
                            (node, op, line, end, inf)
                        )
                for ch, wkind, line, end, in_loop in ff.waits:
                    if len(ch) < 2 or wkind not in ("future", "pop"):
                        continue
                    ent = self.resolve_entity(rp, qual, ch)
                    if ent is not None:
                        self.res_waits.setdefault(ent, []).append(
                            (node, wkind, line, end, in_loop)
                        )

    def resolve_entity(self, rp: str, qual: str, chain: tuple) -> Optional[Entity]:
        """(relpath, class, attr) for the chain's receiver: `self.x` in a
        method (creation class found through the MRO), `var.x` with a
        known local ctor type, `self.field.x` through the class's attr
        ctor types.  All other shapes are unknown."""
        ms = self.summaries.get(rp)
        if ms is None or len(chain) < 2:
            return None
        cls = _class_of(qual)
        attr = chain[-1]
        if chain[0] == "self" and cls is not None:
            if len(chain) == 2:
                return self._creation_class(ms, cls, attr)
            if len(chain) == 3:
                ctor = self.graph._attr_ctor(ms, cls, chain[1])
                if ctor is not None:
                    got = self.graph._resolve_class_chain(ctor[0], ctor[1])
                    if got is not None:
                        return self._creation_class(got[0], got[1], attr)
            return None
        fs = ms.functions.get(qual)
        if fs is not None and chain[0] in fs.var_ctors and len(chain) == 2:
            got = self.graph._resolve_class_chain(ms, fs.var_ctors[chain[0]])
            if got is not None:
                return self._creation_class(got[0], got[1], attr)
        return None

    def _creation_class(self, ms: ModuleSummary, cls: str, attr: str,
                        depth: int = 0) -> Optional[Entity]:
        """Entity of the class (walking bases) whose methods create
        self.<attr> as a tracked promise/stream, or None."""
        if depth > 8:
            return None
        mf = self.facts.get(ms.relpath)
        if mf is not None:
            for qual, ff in mf.funcs.items():
                if _class_of(qual) == cls and any(
                    a == attr for a, _k, _l in ff.attr_creations
                ):
                    return (ms.relpath, cls, attr)
        cs = ms.classes.get(cls)
        if cs is None:
            return None
        for base in cs.bases:
            got = self.graph._resolve_class_chain(ms, base)
            if got is not None:
                found = self._creation_class(got[0], got[1], attr, depth + 1)
                if found is not None:
                    return found
        return None

    def entity_kinds(self, ent: Entity) -> Set[str]:
        kinds: Set[str] = set()
        for rp, qual, kind, _l in self.attr_creations.get(ent[2], ()):
            if rp == ent[0] and _class_of(qual) == ent[1]:
                kinds.add(kind)
        return kinds

    # -- param may-send fixpoint ------------------------------------------
    def _fixpoint_param_senders(self):
        self.may_send: Dict[Node, Dict[str, bool]] = {}
        passes: Dict[Node, List[Tuple[str, Node, str]]] = {}
        for rp, mf in self.facts.items():
            ms = self.summaries.get(rp)
            for qual, ff in mf.funcs.items():
                node = (rp, qual)
                slot = self.may_send.setdefault(node, {})
                pl = passes.setdefault(node, [])
                pset = set(ff.params)
                for ch, _op, _l, _e, _inf in ff.sends:
                    if ch[0] in pset:
                        slot[ch[0]] = True  # direct send on the param
                for ch, _line in ff.escapes:
                    if ch[0] in pset:
                        slot[ch[0]] = True  # untracked use: may send
                for var, desc, idx, _l, _e in ff.arg_passes:
                    if var not in pset:
                        continue
                    got = self._callee_param(ms, qual, desc, idx)
                    if got is None:
                        slot[var] = True  # unresolvable handoff: may send
                    else:
                        pl.append((var, got[0], got[1]))
        changed = True
        while changed:
            changed = False
            for node, pl in passes.items():
                for var, callee, pname in pl:
                    if self.may_send[node].get(var):
                        continue
                    if self.may_send.get(callee, {}).get(pname):
                        self.may_send[node][var] = True
                        changed = True

    def _callee_param(
        self, ms: Optional[ModuleSummary], qual: str, desc: tuple, idx: int
    ) -> Optional[Tuple[Node, str]]:
        """((relpath, qual), param_name) a positional arg lands on, or None
        when the callee/param cannot be pinned down."""
        if ms is None:
            return None
        callee = self.graph.resolve_call(ms, qual, desc)
        if callee is None or not in_nodes(self.summaries, callee):
            return None
        cff = self.facts.get(callee[0], ModulePromiseFacts("")).funcs.get(
            callee[1]
        )
        if cff is None:
            return None
        cparams = list(cff.params)
        if cparams and cparams[0] in ("self", "cls"):
            cparams = cparams[1:]
        if idx >= len(cparams):
            return None
        return (callee, cparams[idx])

    def callee_facts(self, callee: Node) -> Optional[FuncFacts]:
        mf = self.facts.get(callee[0])
        return mf.funcs.get(callee[1]) if mf is not None else None


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


def run_promise_rules(
    summaries: Dict[str, ModuleSummary],
    facts_by_file: Dict[str, ModulePromiseFacts],
    whole_project: bool = True,
    graph: Optional[CallGraph] = None,
) -> List[Finding]:
    """whole_project=False is the standalone-single-module mode (a .py
    outside any package, linted alone): attr-entity rules reason over
    "no code in the PROJECT sends", which is unsound when the project
    isn't loaded — an unseen sibling file may send — so only the
    function-local entity rules (whose entities provably cannot be
    reached from other files) run.  In-package single-file CLI mode
    loads the whole enclosing package and stays in whole_project
    semantics."""
    lk = _Linker(summaries, facts_by_file, graph)
    findings: List[Finding] = []
    findings += _prm001(lk, attrs=whole_project)
    findings += _prm002(lk)
    if whole_project:
        findings += _prm003(lk)
    findings += _prm004(lk, attrs=whole_project)
    findings += _tsk001(lk)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _attr_may_have_sender(lk: _Linker, attr: str) -> bool:
    """Three-valued name-global sender existence for self.<attr> entities:
    any send/send_error/close on a chain ending .attr anywhere, or ANY
    escape touching the attr (aliased, passed, stored, reached into —
    someone we cannot see may send), counts as a potential sender."""
    return bool(
        lk.attr_sends.get(attr)
        or lk.attr_closers.get(attr)
        or lk.attr_escapes.get(attr)
    )


def _local_may_have_sender(
    lk: _Linker, rp: str, qual: str, ff: FuncFacts, var: str
) -> bool:
    """Potential senders for a function-local entity: a direct send, any
    escape, or a handoff whose callee param may send (or could not be
    resolved)."""
    if any(c[0] == var for c, _o, _l, _e, _i in ff.sends):
        return True
    if any(c[0] == var for c, _l in ff.escapes):
        return True
    ms = lk.summaries.get(rp)
    for v, desc, idx, _l, _e in ff.arg_passes:
        if v != var:
            continue
        got = lk._callee_param(ms, qual, desc, idx)
        if got is None:
            return True  # unresolvable handoff: assume it may send
        if lk.may_send.get(got[0], {}).get(got[1]):
            return True
    return False


def _prm001(lk: _Linker, attrs: bool = True) -> List[Finding]:
    out: List[Finding] = []
    for rp, mf in sorted(lk.facts.items()):
        for qual, ff in mf.funcs.items():
            for ch, wkind, line, end, _in_loop in ff.waits:
                if wkind not in ("future", "pop"):
                    continue
                if len(ch) >= 2:
                    if not attrs:
                        continue
                    attr = ch[-1]
                    creations = lk.attr_creations.get(attr)
                    if not creations or _attr_may_have_sender(lk, attr):
                        continue
                    kinds = {k for _r, _q, k, _l in creations}
                    what = "stream" if kinds == {"stream"} else "promise"
                    out.append(Finding(
                        "PRM001", rp, line, 0,
                        f"'{qual}' awaits '{'.'.join(ch)}"
                        f"{'.pop()' if wkind == 'pop' else '.future'}' but "
                        f"no code in the project sends/closes the paired "
                        f"{what} '{attr}' — the wait can never complete "
                        f"(static hang; the reference would deliver "
                        f"broken_promise from the Promise destructor)",
                        end_line=end,
                    ))
                else:
                    var = ch[0]
                    created = ff.local_creations.get(var)
                    if created is None:
                        continue
                    if _local_may_have_sender(lk, rp, qual, ff, var):
                        continue
                    out.append(Finding(
                        "PRM001", rp, line, 0,
                        f"'{qual}' awaits local "
                        f"{'stream' if created[0] == 'stream' else 'promise'}"
                        f" '{var}' which nothing can ever send to (no "
                        f"send/send_error/close reachable — static hang)",
                        end_line=end,
                    ))
    return out


def _prm002(lk: _Linker) -> List[Finding]:
    out: List[Finding] = []
    for rp, mf in sorted(lk.facts.items()):
        ms = lk.summaries.get(rp)
        for qual, ff in mf.funcs.items():
            for var, kind, line, end, leaks in ff.drop_leaks:
                where = "; ".join(f"line {ln} ({how})" for ln, how in leaks)
                out.append(Finding(
                    "PRM002", rp, line, 0,
                    f"{'stream' if kind == 'stream' else 'promise'} '{var}' "
                    f"in '{qual}' can be dropped without send/send_error/"
                    f"close on: {where} — every waiter parks forever "
                    f"(broken-promise class; no destructor backstop)",
                    end_line=end,
                ))
            # Handoff tracking: the promise's ONLY use is handing it to a
            # callee that can itself drop it on some path.
            for var, desc, idx, pline, pend in ff.arg_passes:
                if var not in ff.local_creations:
                    continue
                if ff.mentions.get(var, 0) != 1:
                    continue  # other uses: ownership is shared, not handed
                got = lk._callee_param(ms, qual, desc, idx)
                if got is None:
                    continue
                callee, pname = got
                leaks = lk.callee_facts(callee).param_leaks.get(pname)
                if not leaks:
                    continue
                where = "; ".join(f"line {ln} ({how})" for ln, how in leaks)
                out.append(Finding(
                    "PRM002", rp, pline, 0,
                    f"promise '{var}' handed off to '{callee[1]}' "
                    f"({callee[0]}) which can drop param '{pname}' without "
                    f"send/send_error/close on: {where}",
                    end_line=pend,
                ))
    return out


def _prm003(lk: _Linker) -> List[Finding]:
    # Wait-graph edges: waiter function -> every function that can send
    # the (class-resolved) entity it waits on.  Entities with unresolved
    # same-named sends or escapes are dirty: an unseen sender may wake
    # the cycle, so they contribute no edges.
    edges: Dict[Node, Set[Node]] = {}
    nodes: Set[Node] = set()
    for ent, waits in lk.res_waits.items():
        if ent[2] in lk.dirty_attrs:
            continue
        senders = {s[0] for s in lk.res_sends.get(ent, ())}
        for (wnode, _wk, _l, _e, _il) in waits:
            nodes.add(wnode)
            for s in senders:
                nodes.add(s)
                edges.setdefault(wnode, set()).add(s)

    # Iterative Tarjan SCC.
    index: Dict[Node, int] = {}
    low: Dict[Node, int] = {}
    on_stack: Set[Node] = set()
    stack: List[Node] = []
    sccs: List[Set[Node]] = []
    counter = [0]

    def strongconnect(root: Node):
        work: List[Tuple[Node, iter]] = [(root, iter(sorted(edges.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                scc: Set[Node] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)

    out: List[Finding] = []
    for scc in sccs:
        if len(scc) < 2:
            continue
        # "No external sender": every entity awaited inside the SCC must
        # have ALL its senders inside it — one outside sender can wake
        # the cycle, so the whole SCC is then live.
        blocking: List[Tuple[Entity, Node, int, int]] = []
        external = False
        for ent, waits in lk.res_waits.items():
            if ent[2] in lk.dirty_attrs:
                continue
            in_scc = [w for w in waits if w[0] in scc]
            if not in_scc:
                continue
            senders = {s[0] for s in lk.res_sends.get(ent, ())}
            if not senders:
                continue  # PRM001's case, not a cycle
            if senders - scc:
                external = True
                break
            for (wnode, _wk, line, end, _il) in in_scc:
                blocking.append((ent, wnode, line, end))
        if external or not blocking:
            continue
        names = " <-> ".join(sorted({n[1] for n in scc}))
        for ent, wnode, line, end in sorted(
            blocking, key=lambda b: (b[1][0], b[2])
        ):
            out.append(Finding(
                "PRM003", wnode[0], line, 0,
                f"wait-cycle: '{wnode[1]}' awaits '{ent[1]}.{ent[2]}' whose "
                f"only senders are inside the cycle [{names}] — no "
                f"external sender can break it (static deadlock)",
                end_line=end,
            ))
    return out


def _prm004(lk: _Linker, attrs: bool = True) -> List[Finding]:
    out: List[Finding] = []
    for rp, mf in sorted(lk.facts.items()):
        for qual, ff in mf.funcs.items():
            for ch, wkind, line, end, in_loop in ff.waits:
                if wkind != "pop" or not in_loop:
                    continue
                if len(ch) >= 2:
                    if not attrs:
                        continue
                    ent = lk.resolve_entity(rp, qual, ch)
                    if ent is None or ent[2] in lk.dirty_attrs:
                        continue
                    if lk.entity_kinds(ent) != {"stream"}:
                        continue
                    sites = lk.res_sends.get(ent, ())
                    if any(s[1] in ("send_error", "close") for s in sites):
                        continue  # a closer exists somewhere
                    producers = [s for s in sites if s[1] == "send"]
                    if not producers:
                        continue  # zero senders at all is PRM001's case
                    # Every producer must be able to terminate; a send
                    # inside an unbroken `while True:` never returns.
                    if any(s[4] for s in producers):
                        continue
                    prods = ", ".join(sorted({
                        f"{n[1]} ({n[0]})" for n, _o, _l, _e, _i in producers
                    })[:3])
                    out.append(Finding(
                        "PRM004", rp, line, 0,
                        f"'{qual}' loops over stream '{ent[1]}.{ent[2]}' "
                        f"but every producer [{prods}] can terminate "
                        f"without send_error/close — the consumer parks "
                        f"forever once producers finish (idle-drain hang)",
                        end_line=end,
                    ))
                else:
                    var = ch[0]
                    created = ff.local_creations.get(var)
                    if created is None or created[0] != "stream":
                        continue
                    if any(c[0] == var for c, _l in ff.escapes):
                        continue
                    if any(p[0] == var for p in ff.arg_passes):
                        continue  # handed off: producers unknowable
                    own = [s for s in ff.sends if s[0][0] == var]
                    if any(s[1] in ("send_error", "close") for s in own):
                        continue
                    producers = [s for s in own if s[1] == "send"]
                    if not producers:
                        continue
                    # Same exemption as the attr branch: a producer inside
                    # an unbroken `while True:` never terminates, so the
                    # consumer can always expect more.
                    if any(s[4] for s in producers):
                        continue
                    out.append(Finding(
                        "PRM004", rp, line, 0,
                        f"'{qual}' loops over local stream '{var}' with no "
                        f"send_error/close on any path — the loop can "
                        f"never observe end-of-stream",
                        end_line=end,
                    ))
    return out


def _tsk001(lk: _Linker) -> List[Finding]:
    out: List[Finding] = []
    for rp, mf in sorted(lk.facts.items()):
        ms = lk.summaries.get(rp)
        if ms is None:
            continue
        for qual, ff in mf.funcs.items():
            for desc, line, end in ff.spawn_drops:
                if desc is None:
                    continue  # opaque coroutine expression: cannot judge
                callee = lk.graph.resolve_call(ms, qual, desc)
                if callee is None or not in_nodes(lk.summaries, callee):
                    continue
                if not lk.summaries[callee[0]].functions[callee[1]].is_async:
                    continue
                cff = lk.callee_facts(callee)
                if cff is None:
                    continue
                if not cff.can_raise or cff.has_handler or cff.has_trace:
                    continue
                out.append(Finding(
                    "TSK001", rp, line, 0,
                    f"spawned task '{callee[1]}' ({callee[0]}) is dropped "
                    f"and can raise with neither an except handler nor a "
                    f"TraceEvent — an FdbError in it vanishes silently "
                    f"(the loop only surfaces non-FdbError crashes); hold "
                    f"the Task, handle, or trace",
                    end_line=end,
                ))
    return out
