"""Committed structural fingerprints for registered device entry points.

Each entry in `conflict/engine_jax.py`'s DEVICE_ENTRY_POINTS registry gets
a JSON fingerprint under tests/jax_fingerprints/: a primitive x
size-class eqn histogram (split by compaction-cond membership) plus the
donation and transfer summaries and the canonical abstract signature.
The jaxcheck gate (tests/test_jaxcheck.py, `pytest -m jaxcheck`) diffs
the current CPU traces against the committed files, so any kernel or
sharding PR that changes a compiled program's shape must SAY SO in the
diff by running the explicit update flow and committing the result:

    python -m foundationdb_tpu.tools.lint.jaxfingerprint --update-baselines

Rewrites are deterministic (sorted keys, fixed layout) — same source +
same jax version produce byte-identical files, so the diff is exactly
the structural change.  A registered entry with no baseline is an ERROR
(not a skip: that is how a new entry point ships un-fingerprinted), and
a baseline with no registered entry is flagged stale.

The baseline directory resolves to tests/jax_fingerprints next to the
package, overridable via the registered ``FDB_TPU_JAXCHECK_DIR`` env
flag (flow/knobs.py g_env).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from .jaxir import TRANSFER_PRIMS, _PKG_DIR, _ensure_cpu, default_registry, walk_jaxpr


def size_class(dim: int, size_classes) -> str:
    """Name for a dimension against the entry's descending thresholds."""
    if dim <= 0:
        return "scalar"
    for name, thr in size_classes:
        if dim >= thr:
            return f">={name}"
    return "small"


def fingerprint(entry) -> dict:
    """Structural fingerprint of one entry point's canonical CPU trace."""
    walked = walk_jaxpr(entry.jaxpr())
    counts: Dict[str, int] = {}
    transfers: Dict[str, int] = {}
    for e in walked:
        key = f"{e.prim}|{size_class(e.max_dim, entry.size_classes)}"
        if e.in_cond:
            key += "|cond"
        if e.in_kernel:
            # Pallas kernel-body eqns (ISSUE 14): fingerprinted under
            # their own axis so a kernel rewrite shows in the baseline
            # diff like any other program-shape change.
            key += "|kernel"
        counts[key] = counts.get(key, 0) + 1
        if e.prim in TRANSFER_PRIMS:
            transfers[e.prim] = transfers.get(e.prim, 0) + 1
    don = entry.donation()
    _fn, _jitted, args, statics = entry.built()
    return {
        "entry": entry.name,
        "path": entry.path,
        "static": {
            k: (v if isinstance(v, (int, str, bool)) else str(v))
            for k, v in sorted(statics.items())
        },
        "signature": [
            f"{a.dtype}[{','.join(str(d) for d in a.shape)}]" for a in args
        ],
        "eqn_count": len(walked),
        "eqns": dict(sorted(counts.items())),
        "donation": None if don is None else {
            "donated": sorted(n for n, d in don.items() if d),
            "not_donated": sorted(n for n, d in don.items() if not d),
        },
        "carried": list(entry.carried),
        "pinned": list(entry.pinned),
        "transfers": dict(sorted(transfers.items())),
    }


def render(fp: dict) -> str:
    """Canonical byte-stable serialization (the committed file format)."""
    return json.dumps(fp, indent=2, sort_keys=True) + "\n"


def baseline_dir() -> str:
    from ...flow.knobs import g_env

    override = g_env.get("FDB_TPU_JAXCHECK_DIR")
    if override:
        return override
    return os.path.join(os.path.dirname(_PKG_DIR), "tests",
                        "jax_fingerprints")


def write_baselines(registry=None, dirpath: Optional[str] = None
                    ) -> List[str]:
    """The --update-baselines flow: rewrite every registered entry's
    fingerprint file.  Returns the written paths (sorted by entry)."""
    reg = default_registry() if registry is None else registry
    d = dirpath or baseline_dir()
    os.makedirs(d, exist_ok=True)
    written = []
    for name in sorted(reg):
        path = os.path.join(d, f"{name}.json")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(render(fingerprint(reg[name])))
        written.append(path)
    return written


def _flatten(d: dict, prefix: str = "") -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


def diff_fingerprints(base: dict, cur: dict) -> List[str]:
    """Human-readable field-level diff (empty = identical)."""
    fb, fc = _flatten(base), _flatten(cur)
    lines: List[str] = []
    for k in sorted(set(fb) | set(fc)):
        if k not in fb:
            lines.append(f"+ {k} = {fc[k]!r} (not in baseline)")
        elif k not in fc:
            lines.append(f"- {k} = {fb[k]!r} (gone from current trace)")
        elif fb[k] != fc[k]:
            lines.append(f"~ {k}: baseline {fb[k]!r} -> current {fc[k]!r}")
    return lines


def check_baselines(registry=None, dirpath: Optional[str] = None
                    ) -> List[str]:
    """Diff every registered entry against its committed baseline.
    Returns problem lines (empty = clean).  Missing baselines and stale
    baseline files are both errors."""
    reg = default_registry() if registry is None else registry
    d = dirpath or baseline_dir()
    problems: List[str] = []
    expected = set()
    for name in sorted(reg):
        expected.add(f"{name}.json")
        path = os.path.join(d, f"{name}.json")
        if not os.path.exists(path):
            problems.append(
                f"{name}: MISSING baseline {path} — a registered entry "
                f"point must ship a committed fingerprint "
                f"(--update-baselines, then commit)")
            continue
        with open(path, "r", encoding="utf-8") as fh:
            base = json.load(fh)
        for line in diff_fingerprints(base, fingerprint(reg[name])):
            problems.append(f"{name}: {line}")
    if os.path.isdir(d):
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".json") and fn not in expected:
                problems.append(
                    f"{fn}: STALE baseline (no registered entry point — "
                    f"delete it or re-register the entry)")
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxfingerprint",
        description="Check or rewrite the committed structural "
                    "fingerprints of registered device entry points.",
    )
    ap.add_argument("--update-baselines", action="store_true")
    ap.add_argument("--dir", dest="dirpath",
                    help="baseline directory (default: "
                         "tests/jax_fingerprints, or $FDB_TPU_JAXCHECK_DIR)")
    args = ap.parse_args(argv)
    _ensure_cpu()
    if args.update_baselines:
        for p in write_baselines(dirpath=args.dirpath):
            print(f"wrote {p}")
        return 0
    problems = check_baselines(dirpath=args.dirpath)
    for line in problems:
        print(line)
    if problems:
        print("fingerprints diverged — if intentional, rerun with "
              "--update-baselines and commit the diff", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":  # pragma: no cover - exercised via -m
    sys.exit(main())
