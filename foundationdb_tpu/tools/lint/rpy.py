"""RPY001: dropped reply promises — the broken-promise hang class.

The reference's ReplyPromise destructor sends broken_promise when a
handler drops a reply (fdbrpc.h:94-120); the rebuild mirrors that in
``Reply.__del__``, but a destructor backstop depends on prompt refcount
collection (cycles, PyPy, a held closure all defeat it) and the reference
treats the pattern as a STATIC defect regardless.  This pass flags any
control-flow path through a handler on which a received reply is neither
``send()``/``send_error()``ed, handed off (passed to a call — e.g. a
spawned per-request actor — stored, returned, or yielded), nor abandoned
by a RAISE (an escaping error is the visible path: the owning task dies
and teardown breaks the promise loudly, which ERR001 polices separately).

Reply acquisition points:
  * a function parameter named ``reply`` (the handler-callee idiom),
  * the second target of ``a, b = await <stream>.pop()`` (any names),
  * a local bound from a ``Reply(...)`` constructor call.

Conservative three-valued path walk: branches fork, ``try`` handlers are
entered with the state at try ENTRY (the body may fail before its send),
loop bodies may run zero times, an acquisition inside a loop body is
scoped to one iteration (the back edge rebinds a fresh reply, so falling
off the loop body with the reply unresolved IS the leak).  Mentioning the
reply anywhere outside a bare branch test counts as resolution/handoff —
the hang class this rule hunts is the path that forgets the reply
entirely (early return, swallowed exception)."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, RPY_MODULE_GLOBS, _match_any

U, R = "U", "R"  # unresolved / resolved-or-handed-off


def _mentions(node: ast.AST, var: str) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id == var:
            return True
    return False


class _PathScan:
    """Per-variable path walk.  States are sets over {U, R}; a leak is any
    scope exit reachable with U."""

    def __init__(self, var: str):
        self.var = var
        self.leaks: List[Tuple[int, str]] = []  # (line, how)

    # -- statements --------------------------------------------------------
    def block(self, body: List[ast.stmt], states: Set[str]) -> Dict[str, Set[str]]:
        """Returns {"fall": .., "brk": .., "cont": ..} state sets."""
        out = {"brk": set(), "cont": set()}
        cur = set(states)
        for s in body:
            if not cur:
                break  # unreachable
            res = self.stmt(s, cur)
            out["brk"] |= res["brk"]
            out["cont"] |= res["cont"]
            cur = res["fall"]
        out["fall"] = cur
        return out

    def _resolve_in(self, node: Optional[ast.AST], states: Set[str]) -> Set[str]:
        if node is not None and _mentions(node, self.var):
            return {R} if states else set()
        return set(states)

    def stmt(self, s: ast.stmt, states: Set[str]) -> Dict[str, Set[str]]:
        t = type(s)
        none = {"fall": set(), "brk": set(), "cont": set()}
        if t is ast.Return:
            if s.value is not None and _mentions(s.value, self.var):
                return none
            if U in states:
                self.leaks.append((s.lineno, "return"))
            return none
        if t is ast.Raise:
            return none  # error escapes: visible path, teardown breaks it
        if t in (ast.Break,):
            return {"fall": set(), "brk": set(states), "cont": set()}
        if t in (ast.Continue,):
            return {"fall": set(), "brk": set(), "cont": set(states)}
        if t is ast.If:
            then = self.block(s.body, states)
            els = self.block(s.orelse, states)
            return {k: then[k] | els[k] for k in ("fall", "brk", "cont")}
        if t is ast.Match:
            # N-way branch over the case arms; the no-match fallthrough
            # path joins in unless some arm is irrefutable (bare `case _:`
            # / capture-name with no guard).  A mention in a pattern or
            # guard resolves like any other use.
            states = self._resolve_in(s.subject, states)
            out = {"fall": set(), "brk": set(), "cont": set()}
            irrefutable = False
            for case in s.cases:
                st = self._resolve_in(case.pattern, states)
                st = self._resolve_in(case.guard, st)
                if (case.guard is None
                        and isinstance(case.pattern, ast.MatchAs)
                        and case.pattern.pattern is None):
                    irrefutable = True
                res = self.block(case.body, st)
                for k in ("fall", "brk", "cont"):
                    out[k] |= res[k]
            if not irrefutable:
                out["fall"] |= set(states)
            return out
        if t in (ast.For, ast.AsyncFor):
            states = self._resolve_in(s.iter, states)
            body = self.block(s.body, states)
            # 0..n iterations: fall-through may skip the body entirely.
            fall = set(states) | body["fall"] | body["brk"] | body["cont"]
            els = self.block(s.orelse, fall)
            return {"fall": els["fall"], "brk": els["brk"], "cont": els["cont"]}
        if t is ast.While:
            infinite = (
                isinstance(s.test, ast.Constant) and bool(s.test.value)
            )
            # The loop test is a bare branch test, same as If's: a
            # mention there (`while reply.pending():`) inspects the
            # reply without resolving it.
            states = set(states)
            body = self.block(s.body, states)
            if infinite:
                # Only break exits; the back edge re-runs the body, which
                # the single pass already covered.
                fall = body["brk"]
            else:
                fall = set(states) | body["fall"] | body["brk"] | body["cont"]
            els = self.block(s.orelse, fall)
            return {"fall": els["fall"], "brk": els["brk"], "cont": els["cont"]}
        if t is ast.Try:
            body = self.block(s.body, states)
            merged = {k: set(v) for k, v in body.items()}
            for h in s.handlers:
                # The body may raise BEFORE its sends: pessimistic entry.
                hres = self.block(h.body, states)
                for k in ("fall", "brk", "cont"):
                    merged[k] |= hres[k]
            els = self.block(s.orelse, merged["fall"])
            merged["fall"] = els["fall"]
            merged["brk"] |= els["brk"]
            merged["cont"] |= els["cont"]
            if s.finalbody:
                fin_states = merged["fall"] | merged["brk"] | merged["cont"]
                fin = self.block(s.finalbody, fin_states or set(states))
                if fin["fall"] == {R} and fin_states:
                    # finally resolves on every path it covers
                    merged = {k: ({R} if v else set()) for k, v in merged.items()}
            return merged
        if t in (ast.With, ast.AsyncWith):
            for item in s.items:
                states = self._resolve_in(item.context_expr, states)
            return self.block(s.body, states)
        if t in (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef):
            # A nested def CAPTURING the var is a handoff (deferred send).
            if any(_mentions(n, self.var) for n in ast.walk(s)):
                return {"fall": {R} if states else set(), "brk": set(), "cont": set()}
            return {"fall": set(states), "brk": set(), "cont": set()}
        # Simple statements: Expr/Assign/AugAssign/Assert/Delete/...
        # Any mention outside a bare test resolves (send or handoff).
        if _mentions(s, self.var):
            return {"fall": {R} if states else set(), "brk": set(), "cont": set()}
        return {"fall": set(states), "brk": set(), "cont": set()}

    # -- walking FROM an acquisition nested inside compound statements -----
    def block_from(self, body: List[ast.stmt], acq: ast.stmt) -> Dict[str, Set[str]]:
        """States leaving `body` given the reply is acquired at `acq`
        somewhere inside it.  Statements before the acquiring one carry no
        reply (the empty state set); the one containing it is entered via
        stmt_from; the suffix is the ordinary walk."""
        idx = next((i for i, s in enumerate(body) if _contains(s, acq)), None)
        if idx is None:
            return {"fall": set(), "brk": set(), "cont": set()}
        first = (
            {"fall": {U}, "brk": set(), "cont": set()}
            if body[idx] is acq
            else self.stmt_from(body[idx], acq)
        )
        rest = self.block(body[idx + 1:], first["fall"])
        return {
            "fall": rest["fall"],
            "brk": first["brk"] | rest["brk"],
            "cont": first["cont"] | rest["cont"],
        }

    def stmt_from(self, s: ast.stmt, acq: ast.stmt) -> Dict[str, Set[str]]:
        t = type(s)
        if t is ast.If:
            arm = s.body if any(_contains(x, acq) for x in s.body) else s.orelse
            return self.block_from(arm, acq)
        if t is ast.Try:
            if any(_contains(x, acq) for x in s.body):
                bi = next(i for i, x in enumerate(s.body) if _contains(x, acq))
                merged = {
                    k: set(v) for k, v in self.block_from(s.body, acq).items()
                }
                # A raise AFTER the acquisition (anything running past the
                # acquiring statement can throw — the swallowed-except
                # leak) enters handlers holding the unresolved reply; a
                # bare pop as the try's LAST statement cannot fail after
                # binding, so its handlers never see one.
                post_acq = s.body[bi] is not acq or bi + 1 < len(s.body)
                for h in s.handlers:
                    hres = self.block(h.body, {U} if post_acq else set())
                    for k in ("fall", "brk", "cont"):
                        merged[k] |= hres[k]
                els = self.block(s.orelse, merged["fall"])
                merged["fall"] = els["fall"]
                merged["brk"] |= els["brk"]
                merged["cont"] |= els["cont"]
                if s.finalbody:
                    fin_states = merged["fall"] | merged["brk"] | merged["cont"]
                    fin = self.block(s.finalbody, fin_states)
                    if fin["fall"] == {R} and fin_states:
                        merged = {
                            k: ({R} if v else set()) for k, v in merged.items()
                        }
                return merged
            for region in ([h.body for h in s.handlers]
                           + [s.orelse, s.finalbody]):
                if any(_contains(x, acq) for x in region):
                    return self.block_from(region, acq)
            return {"fall": set(), "brk": set(), "cont": set()}
        if t in (ast.With, ast.AsyncWith):
            return self.block_from(s.body, acq)
        if t is ast.Match:
            for case in s.cases:
                if any(_contains(x, acq) for x in case.body):
                    return self.block_from(case.body, acq)
            return {"fall": set(), "brk": set(), "cont": set()}
        if t in (ast.For, ast.AsyncFor, ast.While):
            # Only reachable for an acquisition in the loop's ELSE block —
            # straight-line code that runs once after the loop completes
            # (a body acquisition re-scoped to the loop body upstream).
            if any(_contains(x, acq) for x in s.orelse):
                return self.block_from(s.orelse, acq)
            return {"fall": set(), "brk": set(), "cont": set()}
        # Anything else is opaque — carry nothing.
        return {"fall": set(), "brk": set(), "cont": set()}


def _is_pop_unpack(s: ast.stmt) -> Optional[str]:
    """Var name of the reply half of `a, b = await <x>.pop()`."""
    if (
        isinstance(s, ast.Assign)
        and len(s.targets) == 1
        and isinstance(s.targets[0], ast.Tuple)
        and len(s.targets[0].elts) == 2
        and all(isinstance(e, ast.Name) for e in s.targets[0].elts)
        and isinstance(s.value, ast.Await)
        and isinstance(s.value.value, ast.Call)
        and isinstance(s.value.value.func, ast.Attribute)
        and s.value.value.func.attr == "pop"
    ):
        return s.targets[0].elts[1].id
    return None


def _is_reply_ctor(s: ast.stmt) -> Optional[str]:
    if (
        isinstance(s, ast.Assign)
        and len(s.targets) == 1
        and isinstance(s.targets[0], ast.Name)
        and isinstance(s.value, ast.Call)
    ):
        f = s.value.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if name == "Reply":
            return s.targets[0].id
    return None


def _scan_acquisition(
    func, acq_stmt: Optional[ast.stmt], var: str
) -> List[Tuple[int, str]]:
    """Leaks for one acquisition.  acq_stmt None = parameter (whole body).
    An acquisition inside a loop body is scoped to ONE iteration: falling
    off the loop body (or `continue`) with U is a leak; `break` paths are
    checked against the code after the loop by the enclosing walk
    approximation (treated as an iteration exit here)."""
    scan = _PathScan(var)
    if acq_stmt is None:
        res = scan.block(func.body, {U})
        _leak_exits(scan, res, func)
        return scan.leaks

    # Find the innermost loop body (or the function body) containing the
    # acquisition, then walk FROM the acquisition — through the remainder
    # of its containing statement (a try's except arms, an if's sibling
    # suffix) and on through the scope's statement suffix.
    loop_node, scope = _innermost_scope(func, acq_stmt)
    res = scan.block_from(scope, acq_stmt)
    if loop_node is not None and U in res["brk"]:
        # A break carries the reply OUT of the loop: check the code after
        # the loop before calling it a leak (break-then-send shutdown is
        # legitimate).  For a loop nested inside another loop the walk
        # below goes silent (∅ states) — conservative toward no finding.
        after = _PathScan(var)
        ares = after.block_from(func.body, loop_node)
        scan.leaks.extend(after.leaks)
        if U in ares["fall"]:
            scan.leaks.append(
                (getattr(func, "end_lineno", func.lineno),
                 "falls off the end after break")
            )
        res = {**res, "brk": set()}
    _leak_exits(scan, res, func, loop_scoped=loop_node is not None,
                anchor=acq_stmt)
    return scan.leaks


def _leak_exits(scan, res, func, loop_scoped: bool = False, anchor=None):
    end_line = getattr(func, "end_lineno", func.lineno)
    if U in res["fall"]:
        scan.leaks.append(
            (end_line if not loop_scoped else (anchor or func).lineno,
             "next iteration rebinds" if loop_scoped else "falls off the end")
        )
    if loop_scoped and U in res["cont"]:
        scan.leaks.append(((anchor or func).lineno, "continue"))
    if loop_scoped and U in res["brk"]:
        scan.leaks.append(((anchor or func).lineno, "break"))


def _contains(node: ast.AST, target: ast.stmt) -> bool:
    return any(n is target for n in ast.walk(node))


def _innermost_scope(func, acq_stmt: ast.stmt):
    """(loop node, its body) for the innermost loop whose BODY contains
    acq_stmt, else (None, the function body).  Innermost = the last loop
    found descending (ast.walk is breadth-first)."""
    best_node = None
    best: List[ast.stmt] = func.body
    for node in ast.walk(func):
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            if any(_contains(s, acq_stmt) for s in node.body):
                best_node, best = node, node.body
    return best_node, best


def run_rpy001(relpath: str, tree: ast.Module) -> List[Finding]:
    if not _match_any(relpath, RPY_MODULE_GLOBS):
        return []
    findings: List[Finding] = []

    def own_stmts(func):
        """Statements of func excluding nested function/class bodies."""
        stack: List[ast.AST] = list(func.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(n, ast.stmt):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    def scan_func(func):
        # Parameter named `reply`.
        params = [
            a.arg
            for a in (func.args.posonlyargs + func.args.args + func.args.kwonlyargs)
        ]
        acquisitions: List[Tuple[Optional[ast.stmt], str, int, int]] = []
        if "reply" in params:
            acquisitions.append((None, "reply", func.lineno, func.lineno))
        for node in own_stmts(func):
            v = _is_pop_unpack(node)
            if v is None:
                v = _is_reply_ctor(node)
            if v is not None:
                acquisitions.append(
                    (node, v, node.lineno,
                     getattr(node, "end_lineno", node.lineno))
                )
        for acq_stmt, var, line, end_line in acquisitions:
            leaks = _scan_acquisition(func, acq_stmt, var)
            if leaks:
                where = "; ".join(
                    f"line {ln} ({how})" for ln, how in sorted(set(leaks))[:4]
                )
                findings.append(Finding(
                    "RPY001", relpath, line, 0,
                    f"reply '{var}' in '{func.name}' can exit without "
                    f"send/send_error/handoff on: {where} — the caller "
                    f"hangs until teardown (broken-promise class)",
                    end_line=end_line,
                ))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_func(node)
    return findings
