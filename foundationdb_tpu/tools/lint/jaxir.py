"""jaxcheck — jaxpr/IR structural analysis for registered device programs.

flowcheck (the rest of tools/lint/) gates the SOURCE level; the programs
that actually run on the TPU are jaxprs, and the regressions that matter
there — H-sized work leaking out of the compaction cond, host callbacks
baked into traced code, carried state silently not donated (the
HBM-doubling class), dtype widenings, un-bucketed static shapes
(recompile storms) — are invisible to AST analysis.  jaxcheck traces
every entry point in `conflict/engine_jax.py`'s DEVICE_ENTRY_POINTS
registry (flat + tiered blob steps, the sharded shard_map step,
grow/rebase/compaction bodies) ON CPU — no device needed — walks the
full eqn tree including sub-jaxprs of cond/while/scan/shard_map/pjit
with ONE shared visitor (`walk_jaxpr`, also used by
tests/test_perf_smoke.py so the perf gate and jaxcheck cannot drift),
and enforces the JXP rule family with the same
Finding/pragma/allowlist/SARIF machinery as flowcheck.

Rules:

  JXP001  work primitive (sort/cumsum/concatenate/scatter/reduce) at or
          above the entry's H threshold outside the compaction cond
          (compaction-gated entries), or above the entry's declared
          width bound anywhere (full-width entries; inside shard_map
          this catches per-shard code touching globally-sized operands).
          Applies INSIDE pallas_call kernel jaxprs too — kernel work
          must stay tile-bounded, and pl.when's lowered cond does not
          count as the compaction cond (see walk_jaxpr)
  JXP002  host callback/transfer primitive inside traced code
          (pure_callback/io_callback/debug prints/infeed — every one is
          a per-batch device stall)
  JXP003  carried engine state not donated across steps, or pinned
          (reused read-only) state donated
  JXP004  64-bit widening on an H-sized buffer when the entry is traced
          under x64 — dtype-less index math (bare `jnp.arange(H)`,
          `cumsum(bool_mask)`) that silently stays 32-bit in the default
          config but doubles HBM the moment x64 is enabled
  JXP005  static-signature dimension outside the registered shape-bucket
          table (every un-bucketed dim is a fresh jit cache key — a
          recompile storm caught before runtime)

Pragmas use the `# jaxcheck: ignore[JXP...]: reason` namespace —
distinct from fdblint's marker so neither pass polices the other's
pragmas as stale — and attach to the entry's BUILDER function: a pragma
anywhere on the builder's def lines suppresses, scoped to exactly that
entry.  Structural fingerprints are the companion gate
(tools/lint/jaxfingerprint.py): committed baselines under
tests/jax_fingerprints/ are diffed on every run, with an explicit
``--update-baselines`` flow.

CLI: ``python -m foundationdb_tpu.tools.lint.jaxir
[--format=text|json|sarif] [--show-suppressed] [--update-baselines]
[--no-fingerprints] [--list-rules]``; exit 0 iff no unsuppressed
findings and every fingerprint matches its committed baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .base import Finding, LintConfig, apply_pragmas, parse_pragmas

JAX_RULES: Dict[str, str] = {
    "JXP001": "H-sized work primitive outside the compaction cond / above the entry's width bound",
    "JXP002": "host callback/transfer primitive inside a traced device program",
    "JXP003": "carried state not donated across steps (or pinned state donated)",
    "JXP004": "64-bit widening on an H-sized buffer under x64 tracing",
    "JXP005": "static-signature dimension outside the registered shape-bucket table",
    "PRG001": "jaxcheck ignore pragma carries no reason string",
    "PRG002": "jaxcheck ignore pragma suppresses nothing (stale)",
}

# Primitives that do O(n) COMPUTE over their operands (vs read-only
# gathers, which are how phase 1 legitimately touches the base tier).
# THE one definition: test_perf_smoke.py's structural gate imports it too.
WORK_PRIMS = frozenset({
    "sort", "cumsum", "concatenate", "scatter", "scatter-add",
    "reduce_max", "reduce_min", "reduce_sum",
})

# Primitives that move data/control between host and device from inside
# traced code.
TRANSFER_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed", "device_put", "copy_to_host",
})

_64BIT = frozenset({"int64", "uint64", "float64", "complex128"})

_PKG_DIR = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


# ---------------------------------------------------------------------------
# The shared jaxpr visitor
# ---------------------------------------------------------------------------


@dataclass
class EqnEntry:
    """One flattened equation: primitive name, the largest dimension it
    touches (operands AND results — a concat BUILDING an H-sized array
    from small pieces is H-sized work), and where it sits in the control
    tree."""

    prim: str
    max_dim: int
    in_cond: bool          # inside any lax.cond branch
    in_while: bool         # inside a while_loop body/cond
    depth: int             # sub-jaxpr nesting depth
    out_dtypes: Tuple[str, ...]
    wide64_dim: int        # max dim over 64-bit results (0 = none)
    wide64_dtypes: Tuple[str, ...]
    in_kernel: bool = False  # inside a pallas_call kernel jaxpr


def _sub_jaxprs(params):
    """Every (Closed)Jaxpr reachable from an eqn's params: cond carries
    `branches`, while `cond_jaxpr`/`body_jaxpr`, scan/pjit a ClosedJaxpr
    under `jaxpr`, shard_map a raw Jaxpr under `jaxpr`."""
    for p in params.values():
        vals = p if isinstance(p, (list, tuple)) else [p]
        for v in vals:
            inner = getattr(v, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner
            elif hasattr(v, "eqns"):
                yield v


def walk_jaxpr(jaxpr, *, in_cond: bool = False, in_while: bool = False,
               in_kernel: bool = False, depth: int = 0,
               out: Optional[List[EqnEntry]] = None) -> List[EqnEntry]:
    """Flatten a Jaxpr or ClosedJaxpr into EqnEntry rows, descending into
    every sub-jaxpr (cond/while/scan/shard_map/pjit AND pallas_call
    kernel jaxprs) and tracking compaction-cond membership.

    Inside a pallas_call kernel, `cond` stops counting as the compaction
    cond: pl.when predication lowers to lax.cond, and letting it confer
    compaction-gating would let an H-sized work primitive hide inside
    any kernel's predicated region.  Kernel eqns keep the in_cond state
    of the pallas_call SITE (a kernel invoked from the real compaction
    branch is still gated) and carry in_kernel=True so the width rules
    and fingerprints can see kernel structure explicitly."""
    if out is None:
        out = []
    inner = getattr(jaxpr, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        jaxpr = inner
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        sub_cond = in_cond or (name == "cond" and not in_kernel)
        sub_while = in_while or name == "while"
        sub_kernel = in_kernel or name == "pallas_call"
        for sub in _sub_jaxprs(eqn.params):
            walk_jaxpr(sub, in_cond=sub_cond, in_while=sub_while,
                       in_kernel=sub_kernel, depth=depth + 1, out=out)
        dims = [
            max(v.aval.shape)
            for v in list(eqn.invars) + list(eqn.outvars)
            if hasattr(v, "aval") and getattr(v.aval, "shape", ())
        ]
        outs = [
            v for v in eqn.outvars
            if hasattr(v, "aval") and getattr(v.aval, "shape", None) is not None
        ]
        wide = sorted({
            str(v.aval.dtype) for v in outs if str(v.aval.dtype) in _64BIT
        })
        wide_dims = [
            max(v.aval.shape) for v in outs
            if v.aval.shape and str(v.aval.dtype) in _64BIT
        ]
        out.append(EqnEntry(
            prim=name,
            max_dim=max(dims, default=0),
            in_cond=in_cond,
            in_while=in_while,
            depth=depth,
            out_dtypes=tuple(str(v.aval.dtype) for v in outs),
            wide64_dim=max(wide_dims, default=0),
            wide64_dtypes=tuple(wide),
            in_kernel=in_kernel,
        ))
    return out


# ---------------------------------------------------------------------------
# The JXP rule family
# ---------------------------------------------------------------------------


def _finding(entry, rule: str, msg: str) -> Finding:
    return Finding(rule, entry.path, entry.line, 0,
                   f"[{entry.name}] {msg}", end_line=entry.end_line)


def run_jxp_rules(entries) -> List[Finding]:
    """Trace each registered entry point and apply JXP001-005.  Raw
    findings (pragma/allowlist application happens in run_jaxcheck)."""
    # THE engine's bucketing rule, not a copy: JXP005's alignment check
    # must follow PackedBatch's real policy if it ever changes.  Lazy so
    # importing jaxir (e.g. for walk_jaxpr alone) stays jax-free.
    from ...conflict.engine_jax import _next_pow2

    out: List[Finding] = []
    for entry in entries:
        walked = walk_jaxpr(entry.jaxpr())

        # JXP001 — H-sized work placement.
        for e in walked:
            if e.prim not in WORK_PRIMS:
                continue
            if (entry.compaction_gated and not e.in_cond
                    and e.max_dim >= entry.h_threshold):
                out.append(_finding(
                    entry, "JXP001",
                    f"H-sized work outside the compaction cond: {e.prim} "
                    f"over dim {e.max_dim} (H threshold "
                    f"{entry.h_threshold})"))
            elif (entry.work_bound is not None
                    and e.max_dim > entry.work_bound):
                out.append(_finding(
                    entry, "JXP001",
                    f"work primitive above the entry's width bound: "
                    f"{e.prim} over dim {e.max_dim} (bound "
                    f"{entry.work_bound})"))

        # JXP002 — host transfers/callbacks.
        seen: Dict[str, int] = {}
        for e in walked:
            if e.prim in TRANSFER_PRIMS:
                seen[e.prim] = seen.get(e.prim, 0) + 1
        for prim, n in sorted(seen.items()):
            out.append(_finding(
                entry, "JXP002",
                f"host transfer/callback primitive in traced code: "
                f"{prim} x{n}"))

        # JXP003 — donation discipline (SNIPPETS pjit donation internals:
        # carried state must alias in place or HBM holds old+new copies).
        don = entry.donation()
        if don is not None:
            for nm in entry.carried:
                if not don.get(nm, False):
                    out.append(_finding(
                        entry, "JXP003",
                        f"carried state {nm!r} is not donated across "
                        f"steps (HBM holds old+new copies)"))
            for nm in entry.pinned:
                if don.get(nm, False):
                    out.append(_finding(
                        entry, "JXP003",
                        f"pinned state {nm!r} is donated (it is reused "
                        f"on the next step after invalidation)"))

        # JXP004 — x64 widenings on H-sized buffers.
        agg: Dict[Tuple[str, Tuple[str, ...]], List[int]] = {}
        for e in walk_jaxpr(entry.jaxpr_x64()):
            if e.wide64_dim >= entry.h_threshold:
                slot = agg.setdefault((e.prim, e.wide64_dtypes), [0, 0])
                slot[0] += 1
                slot[1] = max(slot[1], e.wide64_dim)
        for (prim, dts), (n, dim) in sorted(agg.items()):
            out.append(_finding(
                entry, "JXP004",
                f"64-bit widening under x64: {prim} -> {','.join(dts)} "
                f"over dim {dim} (x{n}) — give the index math an "
                f"explicit 32-bit dtype"))

        # JXP005 — shape-bucket table membership.  Two halves: the
        # registered static dims must be bucket-aligned (pow2 >= floor:
        # the PackedBatch bucketing that bounds the jit cache key space),
        # AND each declared dim must actually appear in the traced
        # signature or static kwargs — a declaration the trace no longer
        # uses is the registry drifting from the real program, and a
        # green check against stale constants guarantees nothing.
        _fn2, _j2, args2, statics2 = entry.built()
        sig_dims = {d for a in args2 for d in a.shape}
        sig_dims |= {v for v in statics2.values() if isinstance(v, int)}
        for nm, (val, floor) in sorted(entry.bucket_dims.items()):
            if _next_pow2(val, floor) != val:
                out.append(_finding(
                    entry, "JXP005",
                    f"static dim {nm}={val} is outside the shape-bucket "
                    f"table (pow2 >= {floor}); every distinct value is a "
                    f"fresh XLA trace+compile"))
            elif val not in sig_dims:
                out.append(_finding(
                    entry, "JXP005",
                    f"declared bucket dim {nm}={val} appears nowhere in "
                    f"the entry's traced signature {sorted(sig_dims)} — "
                    f"the registry has drifted from the real program"))
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


# ---------------------------------------------------------------------------
# Pass orchestration
# ---------------------------------------------------------------------------


def default_registry():
    """The real registry: importing the modules registers their entries."""
    from ...conflict.engine_jax import DEVICE_ENTRY_POINTS
    from ...parallel import sharded_resolver  # noqa: F401  (sharded_step)

    return DEVICE_ENTRY_POINTS


def run_jaxcheck(registry=None, config: Optional[LintConfig] = None,
                 sources: Optional[Dict[str, str]] = None) -> List[Finding]:
    """Full jaxcheck pass over a registry: trace, apply JXP rules, filter
    through the allowlist, then apply `# jaxcheck:` pragmas (and police
    them: PRG001/PRG002) per source file.  `sources` optionally overrides
    file contents by finding path (tests)."""
    reg = default_registry() if registry is None else registry
    config = config or LintConfig(allow={})
    entries = [reg[k] for k in sorted(reg)]
    findings = [
        f for f in run_jxp_rules(entries)
        if not config.allows(f.rule, f.path)
    ]
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    # Every file that DEFINES an entry gets its pragmas policed, even when
    # it produced no findings — that is how a stale pragma ages into
    # PRG002 instead of lingering forever.
    for path in sorted({e.path for e in entries} | set(by_path)):
        src = (sources or {}).get(path)
        if src is None:
            full = path if os.path.isabs(path) else os.path.join(
                _PKG_DIR, path)
            try:
                with open(full, "r", encoding="utf-8") as fh:
                    src = fh.read()
            except OSError:
                src = ""
        pragmas = parse_pragmas(src, tool="jaxcheck")
        out.extend(apply_pragmas(by_path.get(path, []), pragmas, path,
                                 rules=JAX_RULES))
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _ensure_cpu(n: int = 8) -> None:
    """Trace on CPU with enough virtual devices for the sharded entry.
    Must run before the first backend touch (tests/conftest.py does the
    equivalent; this host's sitecustomize would otherwise pick the axon
    TPU plugin for a pure static-analysis run)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxcheck",
        description="jaxpr/IR structural analyzer for registered device "
                    "entry points (JXP rules + committed fingerprints).",
    )
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--show-suppressed", action="store_true")
    ap.add_argument("--config",
                    help="JSON allowlist config {'allow': {'JXP00x': [globs]}}")
    ap.add_argument("--no-fingerprints", action="store_true",
                    help="skip the baseline fingerprint diff")
    ap.add_argument("--update-baselines", action="store_true",
                    help="rewrite the committed fingerprints from the "
                         "current traces instead of diffing")
    ap.add_argument("--baseline-dir",
                    help="fingerprint directory (default: "
                         "tests/jax_fingerprints, or $FDB_TPU_JAXCHECK_DIR)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in JAX_RULES.items():
            print(f"{rule}  {desc}")
        return 0

    _ensure_cpu()
    from . import jaxfingerprint as jfp

    config = (
        LintConfig.load(args.config, use_defaults=False, rules=JAX_RULES)
        if args.config else LintConfig(allow={})
    )
    findings = run_jaxcheck(config=config)
    unsuppressed = [f for f in findings if not f.suppressed]
    shown = findings if args.show_suppressed else unsuppressed

    rc = 1 if unsuppressed else 0
    if args.format == "json":
        from .cli import count_by_rule

        print(json.dumps(
            {
                "findings": [f.to_dict() for f in shown],
                "total": len(findings),
                "unsuppressed": len(unsuppressed),
                "counts": count_by_rule(findings),
            },
            indent=2,
        ))
    elif args.format == "sarif":
        from .cli import to_sarif

        print(json.dumps(
            to_sarif(shown, rules=JAX_RULES, tool="jaxcheck"), indent=2))
    else:
        from .cli import format_counts

        for f in shown:
            tag = " (suppressed: %s)" % f.reason if f.suppressed else ""
            print(f.format() + tag)
        print(
            f"jaxcheck: {len(unsuppressed)} finding(s), "
            f"{len(findings) - len(unsuppressed)} suppressed; "
            + format_counts(findings),
            file=sys.stderr,
        )

    if args.update_baselines:
        for p in jfp.write_baselines(dirpath=args.baseline_dir):
            print(f"jaxcheck: wrote {p}", file=sys.stderr)
    elif not args.no_fingerprints:
        problems = jfp.check_baselines(dirpath=args.baseline_dir)
        for line in problems:
            print(f"jaxcheck fingerprint: {line}", file=sys.stderr)
        if problems:
            print(
                "jaxcheck: fingerprint baselines diverged — if the program "
                "change is intentional, rerun with --update-baselines and "
                "commit the diff",
                file=sys.stderr,
            )
            rc = 1
    return rc


if __name__ == "__main__":  # pragma: no cover - exercised via -m
    sys.exit(main())
