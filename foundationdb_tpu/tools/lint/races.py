"""RACE rule family: atomicity violations & lost updates across awaits —
the write-write half of the actor compiler's state-across-wait rejection
(WAIT001/002 cover the read half).  While an actor is suspended every
other actor runs: a value read from shared ``self.*`` state before an
await and written back after it silently overwrites concurrent updates
(the canonical MVCC lost-update, reintroduced inside our own runtime),
and a guard checked before an await may no longer hold when the guarded
action finally executes.

  RACE001  read-modify-write spanning an await: the read feeding
           ``self.x = f(...)`` / ``self.d[k] += ...`` is separated from
           the write by a suspension — including interprocedurally, when
           the read or the write happens inside a resolvable helper
           method (the call graph's may-await summary per callee)
  RACE002  check-then-act: a guard on shared state, an await, then an
           action whose soundness depended on the guard (creation /
           registration / singleton shapes); re-checking the guard after
           the await clears it
  RACE003  torn invariant: two attrs co-written atomically everywhere
           else get split across an await on some path — other actors
           observe the half-updated pair
  RACE004  multi-writer attr: >= 2 distinct actor (async) functions
           write the same resolved (class, attr) and at least one write
           is await-separated from its read — writer sets resolved
           through the MRO/base machinery, voided by dynamic-attribute
           escapes (three-valued, under-approximate like PRM)

Plus ENV002 (satellite): an FDB_TPU_* flag declared in the flow/knobs.py
registry with no call-time read anywhere in the project is dead config —
the converse of ENV001.

Facts are collected per file into picklable ModuleRaceFacts (cached by
project.py beside ModuleSummary/ModulePromiseFacts); intra-procedural
findings (RACE001-intra/002/003) land in the per-file raw findings, and
the linking pass (run_race_rules) re-resolves interprocedural RACE001,
RACE004 writer sets, and ENV002 on every run through the shared
CallGraph.  Unresolvable calls contribute nothing — the pass
under-approximates, never guesses.  The dynamic twin is the sim-mode
state sanitizer (flow/state_sanitizer.py, FDB_TPU_STATE_SANITIZER) plus
scheduler perturbation (FDB_TPU_SCHED_FUZZ)."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .base import ENV_FLAG_PREFIX, ENV_REGISTRY_GLOBS, Finding, _match_any
from .graphs import CallGraph, ModuleSummary, _name_chain
from .waitrules import (
    MUTATOR_METHODS,
    _falls_through,
    _pragma_span_end,
    _self_attr,
    mutable_attrs,
)

# Wrapping shared state in one of these still snapshots the VALUE: writing
# a merge of the snapshot back after an await is the same lost update.
_SNAPSHOT_FUNCS = {"dict", "list", "set", "tuple", "sorted", "frozenset"}


# ---------------------------------------------------------------------------
# Per-file facts (picklable, cached)
# ---------------------------------------------------------------------------


@dataclass
class RaceFuncFacts:
    qualname: str                 # "Class.method" (graph-compatible)
    line: int
    is_async: bool
    cls: str
    reads: Tuple[str, ...] = ()   # self attrs read anywhere
    writes: Tuple[str, ...] = ()  # self attrs written (assign/del/mutator)
    returns_attrs: Tuple[str, ...] = ()   # self attrs a return expr exposes
    writes_after_await: Tuple[str, ...] = ()  # written at epoch > 0
    # (attr, line, end_line): write await-separated from the latest read of
    # the same attr in this function (RACE004 anchor sites)
    gap_sites: Tuple[Tuple[str, int, int], ...] = ()
    # (call_desc, attr, line, end_line): `v = [await] self.helper()` feeds a
    # later await-separated write of self.<attr> — fires iff the resolved
    # callee returns that attr (interprocedural RMW, read side)
    ipc_reads: Tuple[tuple, ...] = ()
    # (call_desc, attr, cap_line, line, end_line, caller_separated):
    # `v = self.<attr>` later handed to a helper call — fires iff the
    # resolved callee writes that attr and either the caller awaited in
    # between or the callee itself writes it after an await of its own
    ipc_writes: Tuple[tuple, ...] = ()


@dataclass
class ModuleRaceFacts:
    relpath: str
    funcs: Dict[str, RaceFuncFacts] = field(default_factory=dict)
    # Classes using setattr(self, <dynamic>)/self.__dict__/vars(self):
    # writer sets are unknowable, RACE004 stands down (three-valued).
    escaped_classes: Tuple[str, ...] = ()
    env_declares: Tuple[Tuple[str, int, int], ...] = ()  # registry files only
    env_reads: Tuple[str, ...] = ()  # FDB_TPU_* literals, non-registry files


# ---------------------------------------------------------------------------
# The epoch walker
# ---------------------------------------------------------------------------


class _Cap:
    """A local holding a value captured from self.<attr>."""
    __slots__ = ("attr", "epoch", "line")

    def __init__(self, attr: str, epoch: int, line: int):
        self.attr = attr
        self.epoch = epoch
        self.line = line


class _CallCap:
    """A local holding the result of a resolvable self-rooted helper call."""
    __slots__ = ("desc", "epoch", "line")

    def __init__(self, desc: tuple, epoch: int, line: int):
        self.desc = desc
        self.epoch = epoch
        self.line = line


def _join(arms):
    """Pessimistic join of (env, calls, reads, epoch) states, rebasing each
    entry so it keeps the widest await gap it had in any arm (the same
    discipline as waitrules._join_states — the racy path exists, so the
    join must not let a clean sibling arm mask it)."""
    epoch = max(a[3] for a in arms)
    env: Dict[str, _Cap] = {}
    calls: Dict[str, _CallCap] = {}
    reads: Dict[str, Tuple[int, int]] = {}
    for aenv, acalls, areads, aep in arms:
        for n, c in aenv.items():
            gap = aep - c.epoch
            prev = env.get(n)
            if prev is None or epoch - prev.epoch < gap:
                env[n] = _Cap(c.attr, epoch - gap, c.line)
        for n, c in acalls.items():
            gap = aep - c.epoch
            prev = calls.get(n)
            if prev is None or epoch - prev.epoch < gap:
                calls[n] = _CallCap(c.desc, epoch - gap, c.line)
        for a, (rep, rline) in areads.items():
            gap = aep - rep
            prev = reads.get(a)
            if prev is None or epoch - prev[0] < gap:
                reads[a] = (epoch - gap, rline)
    return env, calls, reads, epoch


class _RaceScope:
    """Walks one async method body in source order tracking await epochs,
    shared-state captures, the latest read epoch per attr, and guard
    frames; flags RACE001-intra/RACE002 and accumulates the facts the
    link pass needs.  Nested function/lambda bodies are opaque; nested
    ClassDefs are scopes of their own."""

    def __init__(self, relpath: str, cls_mutable: Set[str],
                 findings: List[Finding], func: RaceFuncFacts):
        self.relpath = relpath
        self.mutable = cls_mutable
        self.findings = findings
        self.func = func
        self.epoch = 0
        self.env: Dict[str, _Cap] = {}
        self.calls: Dict[str, _CallCap] = {}
        self.reads: Dict[str, Tuple[int, int]] = {}  # attr -> (epoch, line)
        self.guards: List[Dict[str, Tuple[int, int]]] = []  # attr -> (epoch, line)
        self.stmt_end = 0
        self.flagged: Set[Tuple[int, str]] = set()
        self.race_lines: Set[int] = set()  # RACE001/002-anchored lines
        # fact accumulators (sets: the two-pass loop walk revisits sites)
        self.f_reads: Set[str] = set()
        self.f_writes: Set[str] = set()
        self.f_returns: Set[str] = set()
        self.f_waw: Set[str] = set()
        self.f_gaps: Set[Tuple[str, int, int]] = set()
        self.f_ipc_reads: Set[tuple] = set()
        self.f_ipc_writes: Set[tuple] = set()
        # per-rhs scratch (valid only between _rhs_begin/_rhs_end)
        self._rhs_names: Set[str] = set()
        self._rhs_self: Dict[str, Tuple[int, int]] = {}
        self._rhs_on = False

    # -- state snapshots ---------------------------------------------------
    def _snap(self):
        return dict(self.env), dict(self.calls), dict(self.reads), self.epoch

    def _restore(self, st):
        self.env, self.calls, self.reads, self.epoch = (
            dict(st[0]), dict(st[1]), dict(st[2]), st[3]
        )

    def _join_into(self, arms):
        self.env, self.calls, self.reads, self.epoch = _join(arms)

    # -- flagging ----------------------------------------------------------
    def _flag(self, rule: str, node: ast.AST, msg: str):
        key = (node.lineno, rule)
        if key in self.flagged:
            return
        self.flagged.add(key)
        self.race_lines.add(node.lineno)
        self.findings.append(Finding(
            rule, self.relpath, node.lineno, node.col_offset, msg,
            end_line=max(self.stmt_end, getattr(node, "end_lineno", 0) or 0),
        ))

    # -- shared-state classification ---------------------------------------
    def _mut_attr(self, node: ast.AST) -> Optional[str]:
        a = _self_attr(node)
        return a if a is not None and a in self.mutable else None

    def _capture_of(self, value: ast.AST) -> Optional[Tuple[str, bool]]:
        """(attr, is_plain) when `value` captures self.<attr> state: the
        attr itself, an element, or a value snapshot (dict()/.copy())."""
        a = self._mut_attr(value)
        if a is not None:
            return (a, True)
        if isinstance(value, ast.Subscript):
            a = self._mut_attr(value.value)
            if a is not None:
                return (a, True)
        if isinstance(value, ast.Call):
            f = value.func
            if (isinstance(f, ast.Name) and f.id in _SNAPSHOT_FUNCS
                    and len(value.args) == 1):
                a = self._mut_attr(value.args[0])
                if a is not None:
                    return (a, False)
            if isinstance(f, ast.Attribute) and f.attr == "copy":
                a = self._mut_attr(f.value)
                if a is not None:
                    return (a, False)
        return None

    def _helper_desc(self, value: ast.AST) -> Optional[tuple]:
        """Picklable call descriptor for `[await] self...helper(...)`."""
        if isinstance(value, ast.Await):
            value = value.value
        if not isinstance(value, ast.Call):
            return None
        chain = _name_chain(value.func)
        if chain is not None and len(chain) >= 2 and chain[0] in ("self", "cls"):
            return ("chain", chain)
        return None

    # -- the write event (all RACE001/002/004 anchors funnel here) ---------
    def _on_write(self, attr: str, node: ast.AST, rhs_names: Set[str],
                  rhs_self: Dict[str, Tuple[int, int]], pre_epoch: int,
                  kind: str = "assign"):
        """A write to self.<attr> just executed at self.epoch.  rhs_names /
        rhs_self describe what the written value was computed FROM (empty
        for mutator calls); pre_epoch is the epoch when an AugAssign read
        its own target (== self.epoch for plain writes).  kind is
        "assign" | "aug" | "mutator" | "del"."""
        self.f_writes.add(attr)
        if self.epoch > 0:
            self.f_waw.add(attr)
        end = max(self.stmt_end, getattr(node, "end_lineno", 0) or 0)
        # RACE001-intra: the value written was computed from a read of the
        # SAME attr on the other side of a suspension.
        fed_stale = None
        if pre_epoch < self.epoch:
            fed_stale = (node.lineno, pre_epoch)  # aug target read pre-await
        got = self._rhs_stale_read(attr, rhs_names, rhs_self)
        if got is not None and (fed_stale is None or got[1] < fed_stale[1]):
            fed_stale = got
        if fed_stale is not None:
            self._flag(
                "RACE001", node,
                f"read-modify-write of self.{attr} spans an await: the value "
                f"read at line {fed_stale[0]} feeds this write after a "
                f"suspension — concurrent updates by other actors are "
                f"silently overwritten (lost update); re-read after the "
                f"await or make the update atomic",
            )
        elif self._guard_hit(attr, node):
            pass  # RACE002 flagged by _guard_hit
        elif kind in ("assign", "del"):
            # RACE004 anchor: a value-replacing write (or removal)
            # await-separated from the latest read.  An atomic AugAssign
            # or mutator call reads-and-updates at ONE epoch — no window —
            # so earlier unrelated reads never make those gap sites.
            r = self.reads.get(attr)
            if r is not None and r[0] < self.epoch:
                self.f_gaps.add((attr, node.lineno, end))
        # Interprocedural read side: a helper-call result from before the
        # suspension feeds this write.
        for v in rhs_names:
            cc = self.calls.get(v)
            if cc is not None and cc.epoch < self.epoch:
                self.f_ipc_reads.add((cc.desc, attr, node.lineno, end))
        # The write refreshes this function's knowledge of the attr.
        self.reads.pop(attr, None)

    def _rhs_stale_read(self, attr: str, rhs_names: Set[str],
                        rhs_self: Dict[str, Tuple[int, int]]):
        best = None
        for v in rhs_names:
            cap = self.env.get(v)
            if cap is not None and cap.attr == attr and cap.epoch < self.epoch:
                if best is None or cap.line < best[0]:
                    best = (cap.line, cap.epoch)
        got = rhs_self.get(attr)
        if got is not None and got[0] < self.epoch:
            # direct `self.x = await f(self.x)` shape
            if best is None or got[0] < best[1]:
                best = (got[1], got[0])
        return best

    def _guard_hit(self, attr: str, node: ast.AST) -> bool:
        for frame in reversed(self.guards):
            g = frame.get(attr)
            if g is not None:
                if g[0] < self.epoch:
                    self._flag(
                        "RACE002", node,
                        f"check-then-act on self.{attr}: the guard at line "
                        f"{g[1]} was evaluated before an await — other "
                        f"actors ran during the suspension and the guarded "
                        f"condition may no longer hold; re-check self."
                        f"{attr} after the await",
                    )
                    return True
                return False  # innermost guard is fresh: sanctioned
        return False

    # -- expression walk ---------------------------------------------------
    def expr(self, node: ast.AST):
        if node is None:
            return
        t = type(node)
        if t is ast.Name:
            if isinstance(node.ctx, ast.Load) and self._rhs_on:
                self._rhs_names.add(node.id)
            return
        if t is ast.Await:
            self.expr(node.value)
            self.epoch += 1
            return
        if t is ast.NamedExpr:
            self.expr(node.value)
            self._bind(node.target, node.value, node.lineno)
            return
        if t is ast.Attribute:
            a = self._mut_attr(node)
            if a is not None and isinstance(node.ctx, ast.Load):
                self.f_reads.add(a)
                self.reads[a] = (self.epoch, node.lineno)
                if self._rhs_on and a not in self._rhs_self:
                    self._rhs_self[a] = (self.epoch, node.lineno)
                return
            self.expr(node.value)
            return
        if t is ast.Call:
            f = node.func
            # Mutator method on shared state = a write event.
            if isinstance(f, ast.Attribute) and f.attr in MUTATOR_METHODS:
                a = self._mut_attr(f.value)
                if a is not None:
                    for arg in node.args:
                        self.expr(arg)
                    for kw in node.keywords:
                        self.expr(kw.value)
                    self._on_write(a, node, set(), {}, self.epoch,
                                   kind="mutator")
                    return
            self.expr(f)
            # Interprocedural write side: a pre-await capture handed to a
            # resolvable helper that may write the attr it came from.
            desc = self._helper_desc(node)
            for arg in node.args:
                if desc is not None and isinstance(arg, ast.Name):
                    cap = self.env.get(arg.id)
                    if cap is not None:
                        end = max(self.stmt_end,
                                  getattr(node, "end_lineno", 0) or 0)
                        self.f_ipc_writes.add((
                            desc, cap.attr, cap.line, node.lineno, end,
                            cap.epoch < self.epoch,
                        ))
                self.expr(arg)
            for kw in node.keywords:
                self.expr(kw.value)
            return
        if t in (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef):
            return  # opaque deferred scope
        for child in ast.iter_child_nodes(node):
            self.expr(child)

    def _walk_rhs(self, value: ast.AST) -> Tuple[Set[str], Dict[str, Tuple[int, int]]]:
        """Walk a value expression collecting the names and self-attr loads
        that feed it (awaits inside bump the epoch as usual)."""
        self._rhs_names, self._rhs_self, self._rhs_on = set(), {}, True
        self.expr(value)
        self._rhs_on = False
        return self._rhs_names, self._rhs_self

    # -- binding/kill ------------------------------------------------------
    def _kill(self, t: ast.AST):
        if isinstance(t, ast.Name):
            self.env.pop(t.id, None)
            self.calls.pop(t.id, None)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._kill(e)
        elif isinstance(t, ast.Starred):
            self._kill(t.value)

    def _bind(self, target: ast.AST, value: ast.AST, line: int):
        if isinstance(target, (ast.Tuple, ast.List)):
            if (isinstance(value, (ast.Tuple, ast.List))
                    and len(target.elts) == len(value.elts)
                    and not any(isinstance(e, ast.Starred)
                                for e in list(target.elts) + list(value.elts))):
                for te, ve in zip(target.elts, value.elts):
                    self._bind(te, ve, line)
                return
            self._kill(target)
            return
        if not isinstance(target, ast.Name):
            return
        self._kill(target)
        got = self._capture_of(value)
        if got is not None:
            self.env[target.id] = _Cap(got[0], self.epoch, line)
            return
        desc = self._helper_desc(value)
        if desc is not None:
            self.calls[target.id] = _CallCap(desc, self.epoch, line)

    # -- guard frames ------------------------------------------------------
    def _test_attrs(self, test: ast.AST) -> Dict[str, Tuple[int, int]]:
        out: Dict[str, Tuple[int, int]] = {}
        for n in ast.walk(test):
            if isinstance(n, (ast.Lambda, ast.FunctionDef,
                              ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
                a = self._mut_attr(n)
                if a is not None:
                    out[a] = (self.epoch, test.lineno)
        return out

    def _refresh_guards(self, attrs: Dict[str, Tuple[int, int]]):
        """A nested re-check of a guarded attr refreshes the outer guard:
        the re-check's truth is what now sanctions the action."""
        for frame in self.guards:
            for a in attrs:
                if a in frame:
                    frame[a] = attrs[a]

    # -- statement walk ----------------------------------------------------
    def stmts(self, body: List[ast.stmt]):
        for s in body:
            self.stmt(s)

    def _write_targets(self, target: ast.AST) -> List[str]:
        out = []
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                out += self._write_targets(e)
            return out
        a = _self_attr(target)
        if a is not None and a in self.mutable:
            out.append(a)
        elif isinstance(target, ast.Subscript):
            a = self._mut_attr(target.value)
            if a is not None:
                out.append(a)
        return out

    def stmt(self, s: ast.stmt):
        self.stmt_end = _pragma_span_end(s)
        t = type(s)
        if t is ast.Assign:
            names, selfs = self._walk_rhs(s.value)
            for target in s.targets:
                for attr in self._write_targets(target):
                    self._on_write(attr, s, names, selfs, self.epoch)
                self._bind(target, s.value, s.lineno)
        elif t is ast.AnnAssign:
            if s.value is not None:
                names, selfs = self._walk_rhs(s.value)
                for attr in self._write_targets(s.target):
                    self._on_write(attr, s, names, selfs, self.epoch)
                self._bind(s.target, s.value, s.lineno)
        elif t is ast.AugAssign:
            pre = self.epoch
            attrs = self._write_targets(s.target)
            for a in attrs:
                self.f_reads.add(a)
            names, selfs = self._walk_rhs(s.value)
            for attr in attrs:
                self._on_write(attr, s, names, selfs, pre, kind="aug")
            if isinstance(s.target, ast.Name):
                self._kill(s.target)
        elif t is ast.Return:
            if s.value is not None:
                for n in ast.walk(s.value):
                    if isinstance(n, ast.Attribute) and isinstance(
                            n.ctx, ast.Load):
                        a = _self_attr(n)
                        if a is not None:
                            self.f_returns.add(a)
                self.expr(s.value)
        elif t is ast.Expr:
            self.expr(s.value)
        elif t is ast.Delete:
            for target in s.targets:
                for attr in self._write_targets(target):
                    self._on_write(attr, s, set(), {}, self.epoch, kind="del")
                self._kill(target)
        elif t is ast.If:
            guard = self._test_attrs(s.test)
            self.expr(s.test)
            self._refresh_guards(guard)
            self.guards.append(dict(guard))
            saved = self._snap()
            self.stmts(s.body)
            then_falls = _falls_through(s.body)
            after_then = self._snap()
            self._restore(saved)
            self.stmts(s.orelse)
            self.guards.pop()
            else_falls = _falls_through(s.orelse)
            if then_falls and else_falls:
                self._join_into([after_then, self._snap()])
            elif then_falls:
                self._restore(after_then)
        elif t in (ast.For, ast.AsyncFor):
            self.expr(s.iter)
            if t is ast.AsyncFor:
                self.epoch += 1
            pre = self._snap()
            self._kill(s.target)
            for _ in range(2):  # back-edge staleness needs a second pass
                self.stmts(s.body)
                self._kill(s.target)
            self._join_into([pre, self._snap()])
            self.stmts(s.orelse)
        elif t is ast.While:
            guard = self._test_attrs(s.test)
            self.expr(s.test)
            self._refresh_guards(guard)
            self.guards.append(dict(guard))
            infinite = isinstance(s.test, ast.Constant) and bool(s.test.value)
            pre = self._snap()
            for _ in range(2):
                self.stmts(s.body)
                self.stmt_end = _pragma_span_end(s)
                g2 = self._test_attrs(s.test)
                self.expr(s.test)
                self._refresh_guards(g2)
            self.guards.pop()
            if not infinite:
                self._join_into([pre, self._snap()])
            self.stmts(s.orelse)
        elif t is ast.Try:
            # The body may raise at ANY statement boundary — in particular
            # after an await — so handlers walk from the join of every
            # boundary state (same discipline as waitrules).
            states = [self._snap()]
            for st in s.body:
                self.stmt(st)
                states.append(self._snap())
            after = self._snap()
            joined = _join(states)
            exits = []
            for h in s.handlers:
                self._restore(joined)
                if h.name is not None:
                    self.env.pop(h.name, None)
                    self.calls.pop(h.name, None)
                self.stmts(h.body)
                if _falls_through(h.body):
                    exits.append(self._snap())
            self._restore(after)
            self.stmts(s.orelse)
            if _falls_through(s.body) and _falls_through(s.orelse):
                exits.append(self._snap())
            if exits:
                self._join_into(exits)
            self.stmts(s.finalbody)
        elif t in (ast.With, ast.AsyncWith):
            for item in s.items:
                self.expr(item.context_expr)
                if item.optional_vars is not None:
                    self._kill(item.optional_vars)
            if t is ast.AsyncWith:
                self.epoch += 1
            self.stmts(s.body)
        elif t is ast.Match:
            self.expr(s.subject)
            saved = self._snap()
            exits = []
            irrefutable = False
            for case in s.cases:
                self._restore(saved)
                for p in ast.walk(case.pattern):
                    nm = getattr(p, "name", None) or getattr(p, "rest", None)
                    if isinstance(nm, str):
                        self.env.pop(nm, None)
                        self.calls.pop(nm, None)
                if case.guard is not None:
                    self.expr(case.guard)
                if (case.guard is None
                        and isinstance(case.pattern, ast.MatchAs)
                        and case.pattern.pattern is None):
                    irrefutable = True
                self.stmts(case.body)
                if _falls_through(case.body):
                    exits.append(self._snap())
            if not irrefutable:
                exits.append(saved)
            if exits:
                self._join_into(exits)
        elif t in (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef):
            return  # nested scopes analyzed separately / opaque
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.expr(child)
                elif isinstance(child, ast.stmt):
                    self.stmt(child)

    def finish(self):
        f = self.func
        f.reads = tuple(sorted(self.f_reads))
        f.writes = tuple(sorted(self.f_writes))
        f.returns_attrs = tuple(sorted(self.f_returns))
        f.writes_after_await = tuple(sorted(self.f_waw))
        f.gap_sites = tuple(sorted(
            g for g in self.f_gaps if g[1] not in self.race_lines
        ))
        f.ipc_reads = tuple(sorted(self.f_ipc_reads))
        f.ipc_writes = tuple(sorted(self.f_ipc_writes))


# ---------------------------------------------------------------------------
# Sync-method light facts (no findings: sync methods run atomically under
# the cooperative loop, but they serve as read/write helpers and RACE003
# co-write evidence)
# ---------------------------------------------------------------------------


def _sync_facts(node: ast.AST, func: RaceFuncFacts, mutable: Set[str]):
    reads: Set[str] = set()
    writes: Set[str] = set()
    returns: Set[str] = set()
    stack: List[ast.AST] = list(node.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                          ast.ClassDef)):
            continue
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for tgt in targets:
                a = _self_attr(tgt)
                if a is None and isinstance(tgt, ast.Subscript):
                    a = _self_attr(tgt.value)
                if a is not None:
                    writes.add(a)
        elif isinstance(n, ast.Delete):
            for tgt in n.targets:
                a = _self_attr(tgt)
                if a is None and isinstance(tgt, ast.Subscript):
                    a = _self_attr(tgt.value)
                if a is not None:
                    writes.add(a)
        elif isinstance(n, ast.Call):
            if (isinstance(n.func, ast.Attribute)
                    and n.func.attr in MUTATOR_METHODS):
                a = _self_attr(n.func.value)
                if a is not None:
                    writes.add(a)
        elif isinstance(n, ast.Return) and n.value is not None:
            for m in ast.walk(n.value):
                if isinstance(m, ast.Attribute) and isinstance(
                        m.ctx, ast.Load):
                    a = _self_attr(m)
                    if a is not None:
                        returns.add(a)
        elif isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
            a = _self_attr(n)
            if a is not None:
                reads.add(a)
        stack.extend(ast.iter_child_nodes(n))
    func.reads = tuple(sorted(reads))
    func.writes = tuple(sorted(writes))
    func.returns_attrs = tuple(sorted(returns))


def _class_escapes(cls: ast.ClassDef) -> bool:
    for n in ast.walk(cls):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
            if (n.func.id == "setattr" and n.args
                    and isinstance(n.args[0], ast.Name)
                    and n.args[0].id == "self"
                    and len(n.args) >= 2
                    and not isinstance(n.args[1], ast.Constant)):
                return True
            if (n.func.id == "vars" and n.args
                    and isinstance(n.args[0], ast.Name)
                    and n.args[0].id == "self"):
                return True
        if (isinstance(n, ast.Attribute) and n.attr == "__dict__"
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"):
            return True
    return False


# ---------------------------------------------------------------------------
# RACE003: torn invariants, aggregated per class at collect time
# ---------------------------------------------------------------------------


def _race003(relpath: str, cls_name: str,
             sites_by_func: Dict[str, List[Tuple[str, int, int, int]]],
             findings: List[Finding]):
    """sites_by_func: func -> [(attr, epoch, line, end_line)] assign-level
    write sites.  For each attr pair, a function that splits the pair
    across an await is flagged only when it is the SOLE splitter and >= 2
    other functions co-write the pair atomically (the 'always co-written
    elsewhere' bar, strictly — under-approximate)."""
    pair_gap: Dict[str, Dict[Tuple[str, str], Tuple[int, int, int]]] = {}
    for fn, sites in sites_by_func.items():
        by_attr: Dict[str, List[Tuple[int, int, int]]] = {}
        for attr, epoch, line, end in sites:
            by_attr.setdefault(attr, []).append((epoch, line, end))
        attrs = sorted(by_attr)
        out: Dict[Tuple[str, str], Tuple[int, int, int]] = {}
        for i, a in enumerate(attrs):
            for b in attrs[i + 1:]:
                best = None
                for ea, la, ena in by_attr[a]:
                    for eb, lb, enb in by_attr[b]:
                        gap = abs(ea - eb)
                        # anchor at the LATER write (the second half of the
                        # torn pair — that's where the window closes)
                        anchor = (la, ena) if (ea, la) >= (eb, lb) else (lb, enb)
                        cand = (gap, anchor[0], anchor[1])
                        if best is None or cand[0] < best[0]:
                            best = cand
                out[(a, b)] = best
        pair_gap[fn] = out
    all_pairs: Set[Tuple[str, str]] = set()
    for out in pair_gap.values():
        all_pairs |= set(out)
    for pair in sorted(all_pairs):
        splitters = [(fn, pair_gap[fn][pair]) for fn in sorted(pair_gap)
                     if pair in pair_gap[fn] and pair_gap[fn][pair][0] > 0]
        cowriters = [fn for fn in sorted(pair_gap)
                     if pair in pair_gap[fn] and pair_gap[fn][pair][0] == 0]
        if len(splitters) == 1 and len(cowriters) >= 2:
            fn, (_gap, line, end) = splitters[0]
            findings.append(Finding(
                "RACE003", relpath, line, 0,
                f"torn invariant in {cls_name}.{fn}: self.{pair[0]} and "
                f"self.{pair[1]} are co-written atomically in "
                f"{len(cowriters)} other methods ({', '.join(cowriters)}) "
                f"but split across an await here — other actors observe "
                f"the half-updated pair during the suspension",
                end_line=end,
            ))


# ---------------------------------------------------------------------------
# Collect pass (per file, cached)
# ---------------------------------------------------------------------------


def collect_race(relpath: str, tree: ast.Module):
    """(intra-procedural findings, ModuleRaceFacts) for one module."""
    findings: List[Finding] = []
    facts = ModuleRaceFacts(relpath=relpath)
    is_registry = _match_any(relpath, ENV_REGISTRY_GLOBS)

    # -- ENV002 facts ------------------------------------------------------
    if is_registry:
        declares: List[Tuple[str, int, int]] = []
        for n in ast.walk(tree):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "declare"
                    and n.args
                    and isinstance(n.args[0], ast.Constant)
                    and isinstance(n.args[0].value, str)
                    and n.args[0].value.startswith(ENV_FLAG_PREFIX)):
                declares.append((
                    n.args[0].value, n.lineno,
                    getattr(n, "end_lineno", n.lineno) or n.lineno,
                ))
        facts.env_declares = tuple(sorted(declares))
    else:
        # ANY mention of the literal counts as a read site — generous on
        # purpose: ENV002 claims a flag is DEAD, so false negatives are
        # cheap and false positives (a flag read via getenv helpers,
        # subprocess env dicts, test monkeypatches) would be corrosive.
        reads = {
            n.value for n in ast.walk(tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
            and n.value.startswith(ENV_FLAG_PREFIX)
        }
        facts.env_reads = tuple(sorted(reads))

    # -- per-class walks ---------------------------------------------------
    def own_defs(cls: ast.ClassDef):
        stack: List[ast.AST] = list(cls.body)
        while stack:
            n = stack.pop()
            if isinstance(n, ast.ClassDef):
                continue
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    top_level = {n for n in tree.body if isinstance(n, ast.ClassDef)}
    escaped: List[str] = []
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        mut = mutable_attrs(cls)
        sites_by_func: Dict[str, List[Tuple[str, int, int, int]]] = {}
        for node in own_defs(cls):
            if node.name == "__init__":
                continue
            ff = RaceFuncFacts(
                qualname=f"{cls.name}.{node.name}", line=node.lineno,
                is_async=isinstance(node, ast.AsyncFunctionDef),
                cls=cls.name,
            )
            if ff.is_async:
                scope = _RaceScope(relpath, mut, findings, ff)
                scope.stmts(node.body)
                scope.finish()
                # assign-level write sites for RACE003 (with their epochs)
                sites: List[Tuple[str, int, int, int]] = []
                _collect_assign_sites(node, mut, sites)
                # re-anchor epochs from a dedicated cheap pass
                sites_by_func[node.name] = sites
            else:
                _sync_facts(node, ff, mut)
                sites = []
                _collect_assign_sites(node, mut, sites)
                sites_by_func[node.name] = sites
            if cls in top_level:
                facts.funcs[ff.qualname] = ff
        _race003(relpath, cls.name, sites_by_func, findings)
        if cls in top_level and _class_escapes(cls):
            escaped.append(cls.name)
    facts.escaped_classes = tuple(sorted(escaped))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, facts


def _collect_assign_sites(node: ast.AST, mutable: Set[str],
                          out: List[Tuple[str, int, int, int]]):
    """Linear await-epoch scan for RACE003: assign/augassign writes to
    mutable self attrs with the count of awaits textually before them.
    Source order approximates program order well enough for a gap=0 /
    gap>0 split (branches re-joining are handled by the strict sole-
    splitter bar in _race003)."""
    epoch = 0
    events: List[Tuple[int, str, int, int]] = []  # (lineno, attr, end, epoch)
    def walk(n: ast.AST):
        nonlocal epoch
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                          ast.ClassDef)):
            return
        if isinstance(n, ast.Await):
            walk(n.value)
            epoch += 1
            return
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for child in ast.iter_child_nodes(n):
                if child not in targets:
                    walk(child)
            for tgt in targets:
                a = _self_attr(tgt)
                if a is None and isinstance(tgt, ast.Subscript):
                    a = _self_attr(tgt.value)
                if a is not None and a in mutable:
                    end = getattr(n, "end_lineno", n.lineno) or n.lineno
                    events.append((n.lineno, a, end, epoch))
            return
        if isinstance(n, (ast.AsyncFor, ast.AsyncWith)):
            epoch += 1
        for child in ast.iter_child_nodes(n):
            walk(child)
    for child in ast.iter_child_nodes(node):
        walk(child)
    for lineno, attr, end, ep in events:
        out.append((attr, ep, lineno, end))


# ---------------------------------------------------------------------------
# Link pass: interprocedural RACE001, RACE004, ENV002
# ---------------------------------------------------------------------------


class _Components:
    """Union-find over (relpath, class) linked by resolved base-class
    edges, so `(class, attr)` unifies across an inheritance chain."""

    def __init__(self):
        self.parent: Dict[Tuple[str, str], Tuple[str, str]] = {}

    def find(self, x):
        self.parent.setdefault(x, x)
        while self.parent[x] != x:
            self.parent[x] = self.parent[self.parent[x]]
            x = self.parent[x]
        return x

    def union(self, a, b):
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[ra] = rb


def run_race_rules(
    summaries: Dict[str, ModuleSummary],
    race_facts: Dict[str, ModuleRaceFacts],
    whole_project: bool = True,
    graph: Optional[CallGraph] = None,
) -> List[Finding]:
    """The linking half: resolves helper calls through the shared
    CallGraph for interprocedural RACE001, aggregates writer sets across
    the MRO for RACE004, and cross-references the env-flag registry for
    ENV002.  whole_project=False (standalone single-file mode) skips
    ENV002 — 'no read anywhere in the project' is a universal claim the
    restricted view cannot make."""
    graph = graph or CallGraph(summaries)
    findings: List[Finding] = []

    def callee_facts(node) -> Optional[RaceFuncFacts]:
        if node is None:
            return None
        mf = race_facts.get(node[0])
        return mf.funcs.get(node[1]) if mf is not None else None

    # -- interprocedural RACE001 ------------------------------------------
    for relpath in sorted(race_facts):
        ms = summaries.get(relpath)
        if ms is None:
            continue
        for qual, ff in sorted(race_facts[relpath].funcs.items()):
            for desc, attr, line, end in ff.ipc_reads:
                cf = callee_facts(graph.resolve_call(ms, qual, desc))
                if cf is not None and attr in cf.returns_attrs:
                    findings.append(Finding(
                        "RACE001", relpath, line, 0,
                        f"read-modify-write of self.{attr} spans an await "
                        f"(interprocedural): the value comes from "
                        f"{cf.cls}.{cf.qualname.split('.')[-1]}() — which "
                        f"reads self.{attr} — on the other side of a "
                        f"suspension; concurrent updates are overwritten "
                        f"(lost update)",
                        end_line=end,
                    ))
            for desc, attr, cap_line, line, end, sep in ff.ipc_writes:
                cf = callee_facts(graph.resolve_call(ms, qual, desc))
                if cf is None or attr not in cf.writes:
                    continue
                if sep or attr in cf.writes_after_await:
                    where = (
                        "the caller awaited between the read and this call"
                        if sep else
                        f"the helper writes self.{attr} after an await of "
                        f"its own"
                    )
                    findings.append(Finding(
                        "RACE001", relpath, line, 0,
                        f"read-modify-write of self.{attr} spans an await "
                        f"(interprocedural): the value captured at line "
                        f"{cap_line} is written back by "
                        f"{cf.cls}.{cf.qualname.split('.')[-1]}() and "
                        f"{where} — concurrent updates are overwritten "
                        f"(lost update)",
                        end_line=end,
                    ))

    # -- RACE004: multi-writer attrs --------------------------------------
    comp = _Components()
    for relpath, ms in summaries.items():
        for cname, cs in ms.classes.items():
            comp.find((relpath, cname))
            for base in cs.bases:
                got = graph._resolve_class_chain(ms, base)
                if got is not None:
                    comp.union((relpath, cname), (got[0].relpath, got[1]))
    escaped_roots = set()
    for relpath, mf in race_facts.items():
        for cname in mf.escaped_classes:
            escaped_roots.add(comp.find((relpath, cname)))
    # root -> attr -> writers: [(relpath, qualname)], gaps: [(relpath, attr, line, end)]
    writers: Dict[tuple, Dict[str, List[Tuple[str, str]]]] = {}
    gaps: Dict[tuple, Dict[str, List[Tuple[str, int, int]]]] = {}
    for relpath in sorted(race_facts):
        for qual, ff in sorted(race_facts[relpath].funcs.items()):
            if not ff.is_async:
                continue
            root = comp.find((relpath, ff.cls))
            for attr in ff.writes:
                writers.setdefault(root, {}).setdefault(attr, []).append(
                    (relpath, qual))
            for attr, line, end in ff.gap_sites:
                gaps.setdefault(root, {}).setdefault(attr, []).append(
                    (relpath, line, end))
    for root in sorted(writers):
        if root in escaped_roots:
            continue
        for attr in sorted(writers[root]):
            ws = writers[root][attr]
            if len(ws) < 2:
                continue
            sites = gaps.get(root, {}).get(attr)
            if not sites:
                continue
            relpath, line, end = min(sites, key=lambda s: (s[0], s[1]))
            others = sorted({q for rp, q in ws})
            findings.append(Finding(
                "RACE004", relpath, line, 0,
                f"multi-writer attr self.{attr} ({root[1]}): "
                f"{len(ws)} actor functions write it "
                f"({', '.join(others)}) and this write is await-separated "
                f"from its read — interleavings can interleave "
                f"read/write pairs (lost update window); funnel writes "
                f"through one owner or re-read after the await",
                end_line=end,
            ))

    # -- ENV002: dead flags ------------------------------------------------
    if whole_project:
        read_flags: Set[str] = set()
        for mf in race_facts.values():
            read_flags.update(mf.env_reads)
        for relpath in sorted(race_facts):
            for flag, line, end in race_facts[relpath].env_declares:
                if flag not in read_flags:
                    findings.append(Finding(
                        "ENV002", relpath, line, 0,
                        f"env flag {flag} is declared in the registry but "
                        f"never read anywhere in the project — dead config "
                        f"(orphaned by a refactor?); delete the "
                        f"declaration or wire the read back up",
                        end_line=end,
                    ))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
