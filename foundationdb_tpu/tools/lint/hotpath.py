"""perfcheck: host-path performance discipline (HOT001-HOT004, ISSUE 20).

PR 19 drove the resolver's host fraction 0.237 -> 0.06 (columnar mirror
apply + zero-copy batch encode); this pass family ENFORCES those wins.
The hazards are host-side and invisible to the determinism/actor/race
families: an implicit device->host sync inside the pipelined
dispatch->sync window serializes the pipeline, a per-row Python loop
over history/mirror columns breaks the Jiffy O(touched-chunks)
contract, and an unstaged per-batch allocation bypasses the
FDB_TPU_ENCODE_STAGING ring.

Rules (pragma namespace ``# perfcheck: ignore[RULE]: reason``):

HOT001  implicit device->host transfer/blocking sync (np.asarray /
        .item() / .tolist() / int() / float() / bool() / len() /
        iteration) on values taint-flowing from DEVICE_ENTRY_POINTS
        dispatch returns or DispatchTicket fields, outside the declared
        sync points (sync_ticket / store_to / breaker replay).
        DET101-style: the finding names the dispatch->sync call chain
        through the shared CallGraph.  Dynamic twin:
        FDB_TPU_TRANSFER_GUARD (flow/hotpath.py GuardedDeviceValue).
HOT002  Python loop whose iteration space exceeds the function's
        declared ``@hot_path(bound=...)``: loops over history/mirror
        row columns (.keys/.vers/ek/va/pfx) under ANY bound; any
        data-dependent loop under bound="const".
HOT003  unstaged per-batch numpy allocation (np.empty/zeros/ones/full/
        concatenate/frombuffer) in a ``@hot_path`` function — hot-path
        buffers ride the PR-19 staging ring or carry a reasoned pragma.
HOT004  per-row Python scalarization in a ``@hot_path`` function:
        .tolist() round-trips and python-int indexing loops where a
        vectorized op exists.

Facts are per-file and picklable (cached out-of-repo by project.py);
only the CallGraph linking and rule evaluation re-run per lint, so the
warm full-repo budget holds."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, LintConfig
from .base import attr_chain
from .graphs import CallGraph, ModuleSummary

# ---------------------------------------------------------------------------
# Rule registry (perfcheck's own universe: pragma policing validates
# against THIS dict, like jaxcheck's JAX_RULES)
# ---------------------------------------------------------------------------

HOT_RULES: Dict[str, str] = {
    "HOT001": "implicit device->host sync on in-flight dispatch state outside a sanctioned sync point",
    "HOT002": "python loop exceeds the function's declared @hot_path bound",
    "HOT003": "unstaged per-batch numpy allocation in a @hot_path function (ride the FDB_TPU_ENCODE_STAGING ring)",
    "HOT004": "per-row python scalarization (.tolist() / python-int indexing loop) in a @hot_path function",
    "PRG001": "perfcheck ignore pragma carries no reason string",
    "PRG002": "perfcheck ignore pragma suppresses nothing (stale)",
}

# Dispatch entry points whose return values are in-flight device state:
# the window opens at a call to one of these.
DEVICE_ENTRY_POINTS = ("dispatch_txns", "dispatch_packed")

# DispatchTicket device fields (engine_jax.DispatchTicket): reading
# `<...>.ticket.<field>` taints, reading the ticket itself only forwards.
TICKET_FIELDS = {"statuses", "undecided", "iters", "hcount", "dcount",
                 "witness"}

# History/mirror row columns: iterating one of these is O(H) by
# definition (the Jiffy chunk columns + the legacy flat views).
O_ROWS = {"keys", "vers", "ek", "va", "pfx"}

ALLOC_FNS = {"empty", "zeros", "ones", "full", "concatenate", "frombuffer"}
NP_ROOTS = {"np", "numpy"}
SCALAR_FNS = {"int", "float", "bool", "len"}

# The declared sync points: functions whose job IS the blocking
# device->host readback (each enters the engine's _sanctioned_sync scope
# at runtime, HOT001's dynamic twin).  Matched on the qualname's last
# segment, mirroring how the runtime guard sanctions whole scopes.
SANCTIONED_FNS = {
    "sync_ticket", "_sync_ticket_body",
    "_readback_packed", "_readback_packed_body",
    "detect_packed", "detect",
    "store_to", "load_from",
    "_merged_host_state", "_merged_host_state_body",
    "_fallback_cpu", "_witness_host",
    "_pipeline_replay_on_mirror",
    "_sanctioned_sync",
}

_HOT_BOUNDS = ("batch", "chunks", "const")


# ---------------------------------------------------------------------------
# Picklable per-file facts
# ---------------------------------------------------------------------------


@dataclass
class HotFuncFacts:
    qualname: str
    line: int
    end_line: int
    bound: Optional[str] = None   # @hot_path(bound=...) or None
    bound_line: int = 0
    # (line, end_line) spans of dispatch-entry call sites (window roots)
    dispatches: List[Tuple[int, int]] = field(default_factory=list)
    # (line, end_line, op, target) unsanctioned tainted host syncs
    syncs: List[Tuple[int, int, str, str]] = field(default_factory=list)
    # (line, end_line, kind, desc); kind in rows|chunks|const|other —
    # recorded only for decorated functions (HOT002 facts)
    loops: List[Tuple[int, int, str, str]] = field(default_factory=list)
    # (line, end_line, fn) numpy allocation sites (HOT003 facts)
    allocs: List[Tuple[int, int, str]] = field(default_factory=list)
    # (line, end_line, desc) scalarization sites (HOT004 facts)
    scalars: List[Tuple[int, int, str]] = field(default_factory=list)


@dataclass
class ModuleHotFacts:
    relpath: str
    functions: Dict[str, HotFuncFacts] = field(default_factory=dict)


def _desc(node: ast.AST) -> str:
    ch = attr_chain(node)
    if ch is not None:
        return ".".join(ch)
    try:
        s = ast.unparse(node)
    except Exception:
        return "<expr>"
    return s if len(s) <= 48 else s[:45] + "..."


def _stmt_span(node: ast.AST, parents: Dict[int, ast.AST]) -> Tuple[int, int]:
    """(line, end_line) of the innermost SIMPLE statement containing
    `node` — the pragma suppression scope — else the node's own span."""
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                            ast.Expr, ast.Return, ast.Raise, ast.Assert,
                            ast.Delete)):
            return (cur.lineno, cur.end_lineno or cur.lineno)
        cur = parents.get(id(cur))
    return (node.lineno, getattr(node, "end_lineno", None) or node.lineno)


def _decorator_bound(node) -> Tuple[Optional[str], int]:
    """(declared bound, decorator line) from a @hot_path decoration, or
    (None, 0).  Matched by NAME (hot_path / x.hot_path): the static pass
    must not import the runtime module, and corpus cases stub it."""
    for d in node.decorator_list:
        if isinstance(d, ast.Call):
            ch = attr_chain(d.func)
            if ch is None or ch[-1] != "hot_path":
                continue
            bound = "batch"
            for kw in d.keywords:
                if kw.arg == "bound" and isinstance(kw.value, ast.Constant):
                    bound = str(kw.value.value)
            if d.args and isinstance(d.args[0], ast.Constant):
                bound = str(d.args[0].value)
            if bound not in _HOT_BOUNDS:
                bound = "batch"
            return bound, d.lineno
        ch = attr_chain(d)
        if ch is not None and ch[-1] == "hot_path":
            return "batch", d.lineno
    return None, 0


def _classify_iter(it: ast.AST) -> Tuple[str, str]:
    """(kind, description) of a for-loop iterable.  rows = O(history
    rows) (always over-bound in hot code), chunks = O(touched chunks),
    const = provably O(1) literals, other = data-dependent but not a
    known row column (over-bound only under bound="const")."""
    if isinstance(it, (ast.Tuple, ast.List, ast.Set, ast.Dict)):
        return "const", "literal"
    if isinstance(it, ast.Call):
        ch = attr_chain(it.func)
        last = ch[-1] if ch else None
        if last in ("enumerate", "sorted", "reversed", "iter", "list",
                    "tuple") and it.args:
            return _classify_iter(it.args[0])
        if last == "zip":
            kinds = [_classify_iter(a) for a in it.args]
            for want in ("rows", "chunks", "other"):
                for k, d in kinds:
                    if k == want:
                        return k, d
            return "const", "zip(literals)"
        if last == "range":
            if all(isinstance(a, ast.Constant) for a in it.args):
                return "const", "range(<const>)"
            if len(it.args) >= 1 and isinstance(it.args[0], ast.Call):
                inner = it.args[0]
                ich = attr_chain(inner.func)
                if ich and ich[-1] == "len" and inner.args:
                    k, d = _classify_iter(inner.args[0])
                    return k, f"range(len({d}))"
            return "other", _desc(it)
        if last == "take_fresh_chunks":
            return "chunks", _desc(it.func) + "()"
        return "other", _desc(it)
    ch = attr_chain(it)
    if ch is not None:
        if ch[-1] in O_ROWS:
            return "rows", ".".join(ch)
        if ch[-1] == "chunks":
            return "chunks", ".".join(ch)
        return "other", ".".join(ch)
    if isinstance(it, ast.Subscript):
        return _classify_iter(it.value)
    return "other", _desc(it)


class _FuncAnalysis:
    """Single-function fact extraction: decorator bound, local taint
    fixpoint for HOT001 sync sites, dispatch window roots, and (for
    decorated functions) loop/alloc/scalarization facts.  Nested defs
    fold into the enclosing function, like graphs._FuncCollector."""

    def __init__(self, node, qualname: str):
        self.node = node
        bound, bline = _decorator_bound(node)
        self.facts = HotFuncFacts(
            qualname=qualname,
            line=node.lineno,
            end_line=node.end_lineno or node.lineno,
            bound=bound,
            bound_line=bline,
        )
        self.parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(node):
            for child in ast.iter_child_nodes(parent):
                self.parents[id(child)] = parent
        self.taint: Set[str] = set()
        self._seed_params()
        self._taint_fixpoint()
        self._scan()

    # -- taint -------------------------------------------------------------
    def _seed_params(self):
        a = self.node.args
        for p in (a.posonlyargs + a.args + a.kwonlyargs):
            ann = p.annotation
            ann_name = None
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                ann_name = ann.value.split(".")[-1].strip("\"'")
            elif ann is not None:
                ch = attr_chain(ann)
                if ch:
                    ann_name = ch[-1]
            if p.arg == "ticket" or ann_name == "DispatchTicket":
                self.taint.add(p.arg)

    def _tainted(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.taint
        if isinstance(e, (ast.Subscript, ast.Starred, ast.Await)):
            return self._tainted(e.value)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self._tainted(x) for x in e.elts)
        if isinstance(e, ast.Call):
            ch = attr_chain(e.func)
            return bool(ch) and ch[-1] in DEVICE_ENTRY_POINTS
        if isinstance(e, ast.Attribute):
            ch = attr_chain(e)
            if (ch and e.attr in TICKET_FIELDS and "ticket" in ch[:-1]):
                return True
            return self._tainted(e.value)
        if isinstance(e, ast.IfExp):
            return self._tainted(e.body) or self._tainted(e.orelse)
        if isinstance(e, ast.BinOp):
            return self._tainted(e.left) or self._tainted(e.right)
        return False

    @staticmethod
    def _target_names(t: ast.AST) -> List[str]:
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            out: List[str] = []
            for e in t.elts:
                out.extend(_FuncAnalysis._target_names(e))
            return out
        if isinstance(t, ast.Starred):
            return _FuncAnalysis._target_names(t.value)
        return []

    def _taint_fixpoint(self):
        for _ in range(8):
            changed = False
            for st in ast.walk(self.node):
                if isinstance(st, ast.Assign):
                    targets, value = st.targets, st.value
                elif isinstance(st, ast.AnnAssign) and st.value is not None:
                    targets, value = [st.target], st.value
                elif isinstance(st, ast.AugAssign):
                    targets, value = [st.target], st.value
                else:
                    continue
                if not self._tainted(value):
                    continue
                for t in targets:
                    for name in self._target_names(t):
                        if name not in self.taint:
                            self.taint.add(name)
                            changed = True
            if not changed:
                return

    # -- fact scan ---------------------------------------------------------
    def _scan(self):
        f = self.facts
        hot = f.bound is not None
        for sub in ast.walk(self.node):
            if isinstance(sub, ast.Call):
                span = _stmt_span(sub, self.parents)
                ch = attr_chain(sub.func)
                if ch is not None:
                    last = ch[-1]
                    if last in DEVICE_ENTRY_POINTS:
                        f.dispatches.append(span)
                    if (len(ch) == 1 and last in SCALAR_FNS and sub.args
                            and self._tainted(sub.args[0])):
                        f.syncs.append(span + (f"{last}()",
                                               _desc(sub.args[0])))
                    elif (len(ch) == 2 and ch[0] in NP_ROOTS
                          and last in ("asarray", "array") and sub.args
                          and self._tainted(sub.args[0])):
                        f.syncs.append(span + (f"np.{last}()",
                                               _desc(sub.args[0])))
                    elif (ch[0] == "jax" and last == "device_get"
                          and sub.args and self._tainted(sub.args[0])):
                        f.syncs.append(span + ("jax.device_get()",
                                               _desc(sub.args[0])))
                    if (hot and len(ch) == 2 and ch[0] in NP_ROOTS
                            and last in ALLOC_FNS):
                        f.allocs.append(span + (f"np.{last}",))
                fn = sub.func
                if isinstance(fn, ast.Attribute) and fn.attr in (
                        "item", "tolist"):
                    span = _stmt_span(sub, self.parents)
                    if self._tainted(fn.value):
                        f.syncs.append(span + (f".{fn.attr}()",
                                               _desc(fn.value)))
                    if hot and fn.attr == "tolist":
                        f.scalars.append(span + (
                            f"{_desc(fn.value)}.tolist()",))
            elif isinstance(sub, ast.For):
                span = (sub.lineno, sub.iter.end_lineno or sub.lineno)
                if self._tainted(sub.iter):
                    f.syncs.append(span + ("iteration", _desc(sub.iter)))
                if hot:
                    kind, desc = _classify_iter(sub.iter)
                    f.loops.append(span + (kind, desc))
                    self._scalar_index_loop(sub, span)

    def _scalar_index_loop(self, loop: ast.For, span):
        """for i in range(...): ... x[i] ... — a per-row python indexing
        sweep where a vectorized slice/gather exists (HOT004)."""
        if not (isinstance(loop.target, ast.Name)
                and isinstance(loop.iter, ast.Call)):
            return
        ch = attr_chain(loop.iter.func)
        if not ch or ch[-1] != "range":
            return
        ivar = loop.target.id
        for sub in ast.walk(loop):
            if (isinstance(sub, ast.Subscript)
                    and isinstance(sub.slice, ast.Name)
                    and sub.slice.id == ivar):
                self.facts.scalars.append(
                    span + (f"python-int indexing loop over '{ivar}'",))
                return


def collect_hotpath(relpath: str, tree: ast.Module) -> ModuleHotFacts:
    """Per-file perfcheck facts (picklable, cached by project.py)."""
    mh = ModuleHotFacts(relpath=relpath)

    def add(node, qualname: str):
        mh.functions[qualname] = _FuncAnalysis(node, qualname).facts

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add(node, node.name)
        elif isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add(m, f"{node.name}.{m.name}")
    return mh


# ---------------------------------------------------------------------------
# Rule evaluation (per lint, over cached facts + the shared CallGraph)
# ---------------------------------------------------------------------------


def _last(qual: str) -> str:
    return qual.rsplit(".", 1)[-1]


def run_hotpath_rules(
    summaries: Dict[str, ModuleSummary],
    hot_facts: Dict[str, ModuleHotFacts],
    config: LintConfig,
    graph: Optional[CallGraph] = None,
) -> List[Finding]:
    """HOT001-HOT004 over per-file facts.  HOT001 is interprocedural:
    forward reachability from dispatch call sites through the shared
    CallGraph (never descending into a sanctioned sync function) names
    the dispatch->sync window chain each flagged sync sits inside."""
    graph = CallGraph(summaries) if graph is None else graph

    roots = []
    for mh in hot_facts.values():
        for qual, ff in mh.functions.items():
            if ff.dispatches and _last(qual) not in SANCTIONED_FNS:
                roots.append((mh.relpath, qual))

    fwd: Dict[tuple, List[tuple]] = {}
    for caller, _span, callee in graph.edges():
        fwd.setdefault(caller, []).append(callee)

    reach = set(roots)
    via: Dict[tuple, tuple] = {}
    frontier = sorted(roots)
    while frontier:
        nxt = []
        for node in frontier:
            for callee in fwd.get(node, ()):
                if _last(callee[1]) in SANCTIONED_FNS:
                    continue  # window closes at the sanctioned boundary
                if callee not in reach:
                    reach.add(callee)
                    via[callee] = node
                    nxt.append(callee)
        frontier = sorted(set(nxt))

    def chain_of(node, limit: int = 8) -> List[str]:
        names = [node[1]]
        cur = node
        while cur in via and len(names) < limit:
            cur = via[cur]
            names.append(cur[1])
        return list(reversed(names))

    findings: List[Finding] = []
    for rp, mh in sorted(hot_facts.items()):
        for qual, ff in sorted(mh.functions.items()):
            if _last(qual) in SANCTIONED_FNS:
                continue
            node = (rp, qual)
            for line, end, op, target in ff.syncs:
                if node in reach:
                    where = ("inside the dispatch->sync window (chain: "
                             + " -> ".join(chain_of(node)) + ")")
                else:
                    where = "on in-flight dispatch state"
                findings.append(Finding(
                    "HOT001", rp, line, 0,
                    f"'{qual}': {op} on '{target}' blocks the host "
                    f"{where}; readbacks belong in a sanctioned sync "
                    f"point (sync_ticket / store_to / breaker replay)",
                    end_line=end,
                ))
            if ff.bound is None:
                continue
            for line, end, kind, desc in ff.loops:
                over = (kind == "rows"
                        or (ff.bound == "const" and kind != "const"))
                if not over:
                    continue
                cost = ("O(history rows)" if kind == "rows"
                        else "data-dependent")
                findings.append(Finding(
                    "HOT002", rp, line, 0,
                    f"'{qual}' declares @hot_path(bound=\"{ff.bound}\") "
                    f"but loops over '{desc}' ({cost}); vectorize it or "
                    f"widen the declared bound",
                    end_line=end,
                ))
            for line, end, fn in ff.allocs:
                findings.append(Finding(
                    "HOT003", rp, line, 0,
                    f"'{qual}' is @hot_path(bound=\"{ff.bound}\") but "
                    f"allocates per call via {fn}; ride the "
                    f"FDB_TPU_ENCODE_STAGING ring or justify with a "
                    f"pragma",
                    end_line=end,
                ))
            for line, end, desc in ff.scalars:
                findings.append(Finding(
                    "HOT004", rp, line, 0,
                    f"'{qual}' is @hot_path(bound=\"{ff.bound}\") but "
                    f"scalarizes per row ({desc}); use a vectorized "
                    f"numpy op",
                    end_line=end,
                ))
    return findings
