"""Shared lint infrastructure: rule registry, findings, pragmas, aliases.

The per-rule passes (local.py, waitrules.py, rpy.py, det101.py) all build
on the primitives here; project.py orchestrates them over a whole scan
root.  Nothing in this package is simulator-executed (SKIP_MODULE_GLOBS).
"""

from __future__ import annotations

import ast
import fnmatch
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

RULES: Dict[str, str] = {
    "DET001": "wall-clock read in simulator-executed code (use loop.now())",
    "DET002": "global entropy source (use the loop's DeterministicRandom, flow/rng.py)",
    "DET003": "threading/asyncio/multiprocessing primitive in simulator-executed code",
    "DET101": "function reachable from sim-executed code transitively hits wall clock/entropy",
    "ACT001": "actor coroutine called but neither awaited nor spawned (dropped future)",
    "JAX001": "host sync or Python side effect inside a jit-traced function",
    "IO001": "direct open()/socket outside the real I/O backends",
    "TRC001": "TraceEvent constructed but never .log()ed nor used as a context manager (dropped event)",
    "SPN001": "begin_span() result neither context-managed, .end()ed, nor stored (leaked open span)",
    "ERR001": "broad except that neither re-raises, TraceEvents, nor propagates the error (silent swallow)",
    "WAIT001": "shared state captured before an await and dereferenced after it without re-read",
    "WAIT002": "iteration over shared mutable state whose loop body awaits (reference across wait)",
    "RPY001": "reply promise path that neither sends, errors, nor hands the reply off (broken-promise hang)",
    "PRM001": "future awaited where no reachable code can send to its paired promise (orphaned wait / static hang)",
    "PRM002": "promise abandoned on some path without send/send_error/close (dropped promise, interprocedural)",
    "PRM003": "wait-cycle in the actor wait-graph with no external sender (static deadlock)",
    "PRM004": "consumer loop over a stream whose producers can all terminate without closing it",
    "TSK001": "spawned Task dropped while its coroutine can raise with neither handler nor TraceEvent",
    "ENV001": "FDB_TPU_* environment flag read outside the flow/knobs.py registry (config drift)",
    "ENV002": "FDB_TPU_* flag declared in the registry but never read anywhere in the project (dead config)",
    "RACE001": "read-modify-write of shared state spanning an await (lost update)",
    "RACE002": "check-then-act: guard on shared state evaluated before an await that the guarded action outlives",
    "RACE003": "two attrs co-written atomically elsewhere split across an await (torn invariant)",
    "RACE004": "attr written by >=2 actor functions with >=1 write await-separated from its read (multi-writer race)",
    # HOT family (perfcheck, tools/lint/hotpath.py): host-path performance
    # discipline.  Own pragma namespace (# perfcheck: ignore[...]), listed
    # here so shared configs may allowlist them and --list-rules shows the
    # full registry.
    "HOT001": "implicit device->host sync on in-flight dispatch state outside a sanctioned sync point",
    "HOT002": "python loop exceeds the function's declared @hot_path bound",
    "HOT003": "unstaged per-batch numpy allocation in a @hot_path function (ride the FDB_TPU_ENCODE_STAGING ring)",
    "HOT004": "per-row python scalarization (.tolist() / python-int indexing loop) in a @hot_path function",
    "PRG001": "fdblint ignore pragma carries no reason string",
    "PRG002": "fdblint ignore pragma suppresses nothing (stale)",
}

# Canonical dotted names considered wall-clock reads.  Referencing one as a
# value (e.g. ``clock = time.monotonic``) is flagged like calling it: binding
# the function is how wall time gets smuggled past a call-site-only check.
WALL_CLOCK = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
    "time.sleep",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# Entropy: exact names plus whole-module prefixes.
ENTROPY_EXACT = {"os.urandom", "uuid.uuid1", "uuid.uuid4"}
ENTROPY_MODULES = {"random", "secrets"}


def classify_clock_ref(path: str) -> Optional[str]:
    """'wall' / 'entropy' / None for a canonical dotted path.  THE one
    classifier behind both DET001/DET002 direct-site flagging (local.py)
    and DET101 taint sources (graphs.py): a name added or removed here
    changes both passes together, so a clock can never be flagged at its
    direct site yet carry no interprocedural taint (or vice versa)."""
    if path in WALL_CLOCK:
        return "wall"
    if path in ENTROPY_EXACT or path.split(".")[0] in ENTROPY_MODULES:
        return "entropy"
    return None


class ClockRefVisitorMixin:
    """Shared visit_Attribute/visit_Name discipline for spotting
    wall-clock/entropy references whose chain is rooted at an actual
    import binding.  Subclasses provide ``self.aliases`` (an Aliases) and
    ``_on_clock_ref(node, path, kind)``; mix in BEFORE ast.NodeVisitor."""

    def visit_Attribute(self, node: ast.Attribute):
        path = self.aliases.resolve(node)
        if path is not None:
            # Pure Name/Attribute chain: check it once, don't recurse
            # (recursing would re-report each prefix of a.b.c).
            if self.aliases.root_bound(node):
                kind = classify_clock_ref(path)
                if kind is not None:
                    self._on_clock_ref(node, path, kind)
        else:
            # Chain contains calls/subscripts — keep walking to reach them.
            self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        # A bare name bound by `from time import monotonic` style imports.
        path = self.aliases.resolve(node)
        if path is not None and path != node.id and self.aliases.root_bound(node):
            kind = classify_clock_ref(path)
            if kind is not None:
                self._on_clock_ref(node, path, kind)

THREADING_MODULES = {
    "threading", "_thread", "asyncio", "multiprocessing", "concurrent.futures",
}

IO_CALLS = {"open", "os.open", "os.fdopen", "io.open"}
IO_MODULES = {"socket", "ssl"}

# Modules where JAX001 applies (the jit-traced surface of the repo).
TRACED_MODULE_GLOBS = ("conflict/engine_jax.py", "ops/*.py", "parallel/*.py")

# Modules where RPY001 applies: the RequestStream-serving layers.
RPY_MODULE_GLOBS = ("server/*.py", "rpc/*.py")

# The one module allowed to read FDB_TPU_* environment flags (ENV001):
# the registration point every other module must consult.
ENV_REGISTRY_GLOBS = ("flow/knobs.py",)
ENV_FLAG_PREFIX = "FDB_TPU_"

# Modules that run outside the simulator by identity (real-mode backends
# with OS-thread concurrency + operational programs): the shared
# exemption set for the cooperative-actor rule families.
_REAL_MODE_MODULES = (
    "rpc/real_network.py", "fileio/blobstore.py", "fileio/realfile.py",
    "flow/profiler.py", "tools/*.py", "utils/procutil.py",
)

# Per-rule allowlist: package-relative posix globs for modules that are
# real-deployment components by identity, where the rule does not apply.
# The IO001 set mirrors the rule text: fileio/ real backends +
# rpc/real_network.py; tools/ are operational programs (fdbcli, fdbmonitor,
# real_node) that never run under the simulator.
DEFAULT_ALLOW: Dict[str, Tuple[str, ...]] = {
    "DET001": (
        "rpc/real_network.py",   # wall-anchored loop driver IS its purpose
        "tools/*.py",            # operational programs (fdbcli/fdbmonitor/
        #                          real_node analogs) never run under sim
        "utils/procutil.py",     # OS process plumbing
    ),
    "DET002": (),
    "DET003": (
        "rpc/real_network.py",
        "fileio/blobstore.py",   # threaded blocking-socket client/server
        "fileio/realfile.py",
        "flow/profiler.py",      # sampling thread = the SIGPROF analog
        "tools/*.py",
        "utils/procutil.py",
    ),
    # DET101 roots: functions in SIM-SURFACE modules only.  Real-mode
    # modules may hit wall clocks freely (they still CARRY taint to any
    # sim-surface caller).  The set is the union of the per-site DET001 /
    # DET003 real-mode exemptions: those modules run outside the simulator
    # by identity.
    "DET101": (
        "rpc/real_network.py",
        "fileio/blobstore.py",
        "fileio/realfile.py",
        "flow/profiler.py",
        "tools/*.py",
        "utils/procutil.py",
    ),
    "ACT001": (),
    "JAX001": (),
    "TRC001": (),
    "SPN001": (),
    "ERR001": (
        "rpc/real_network.py",   # teardown paths on real sockets: close()
        #                          best-effort by design
        "tools/*.py",            # operational programs, not sim-executed
        "utils/procutil.py",     # post-fork/pre-exec: may not even print
    ),
    "IO001": (
        "fileio/realfile.py",
        "fileio/blobstore.py",
        "rpc/real_network.py",
        "tools/*.py",
        "utils/procutil.py",
    ),
    # WAIT rules police cooperative actors; the real-mode backends with
    # OS-thread concurrency (already DET003-exempt) have genuinely
    # different suspension semantics and are triaged by inspection.
    "WAIT001": ("rpc/real_network.py", "tools/*.py"),
    "WAIT002": ("rpc/real_network.py", "tools/*.py"),
    "RPY001": (),
    # The PRM/TSK promise-lifecycle rules police cooperative-actor
    # ownership; the real-mode, OS-threaded backends (already DET003-
    # exempt) hand promises across threads with genuinely different
    # suspension semantics, and tools/ are operational programs.
    "PRM001": _REAL_MODE_MODULES,
    "PRM002": _REAL_MODE_MODULES,
    "PRM003": _REAL_MODE_MODULES,
    "PRM004": _REAL_MODE_MODULES,
    "TSK001": _REAL_MODE_MODULES,
    "ENV001": (),
    "ENV002": (),
    # RACE rules police cooperative-actor atomicity; the OS-threaded
    # real-mode backends have genuinely different suspension semantics
    # (locks, not awaits) and are triaged by inspection like WAIT/PRM.
    "RACE001": _REAL_MODE_MODULES,
    "RACE002": _REAL_MODE_MODULES,
    "RACE003": _REAL_MODE_MODULES,
    "RACE004": _REAL_MODE_MODULES,
}

# The linter's own modules are never simulator-executed.
SKIP_MODULE_GLOBS = ("tools/fdblint.py", "tools/lint/*.py")


def _match_any(relpath: str, globs) -> bool:
    """Glob match against the relpath or any of its trailing sub-paths, so
    'rpc/real_network.py' matches whether the scan root was the package dir
    (relpath 'rpc/real_network.py') or an ancestor (relpath
    'foundationdb_tpu/rpc/real_network.py', the single-file CLI mode)."""
    parts = relpath.split("/")
    tails = ["/".join(parts[i:]) for i in range(len(parts))]
    return any(fnmatch.fnmatch(t, g) for t in tails for g in globs)


@dataclass
class Finding:
    rule: str
    path: str          # package-relative posix path
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str = ""   # pragma reason when suppressed
    end_line: int = 0  # last physical line of the flagged node (pragma scope)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
            "suppressed": self.suppressed, "reason": self.reason,
        }


@dataclass
class LintConfig:
    allow: Dict[str, Tuple[str, ...]] = field(
        default_factory=lambda: {k: tuple(v) for k, v in DEFAULT_ALLOW.items()}
    )

    @classmethod
    def load(
        cls, path: str, use_defaults: bool = True,
        rules: Optional[Dict[str, str]] = None,
    ) -> "LintConfig":
        """JSON config {"allow": {"RULE": ["glob", ...]}}, merged over (or
        replacing, with use_defaults=False) the built-in allowlist.
        `rules` is the rule universe to validate against (default: the
        source-level RULES registry; jaxcheck passes JAX_RULES)."""
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        base: Dict[str, Tuple[str, ...]] = (
            {k: tuple(v) for k, v in DEFAULT_ALLOW.items()} if use_defaults else {}
        )
        known = set(RULES if rules is None else rules)
        for rule, globs in raw.get("allow", {}).items():
            if rule not in known:
                raise ValueError(f"config allowlists unknown rule {rule!r}")
            base[rule] = tuple(base.get(rule, ())) + tuple(globs)
        return cls(allow=base)

    def allows(self, rule: str, relpath: str) -> bool:
        return _match_any(relpath, self.allow.get(rule, ()))


# ---------------------------------------------------------------------------
# Pragmas
# ---------------------------------------------------------------------------

# One pragma grammar, two tool namespaces: source-level findings use
# `# fdblint: ignore[...]`, jaxpr-level findings (tools/lint/jaxir.py) use
# `# jaxcheck: ignore[...]`.  Separate markers keep the two passes from
# policing each other's pragmas as stale (each pass only parses its own).
_PRAGMA_RES: Dict[str, "re.Pattern"] = {}


def _pragma_re(tool: str) -> "re.Pattern":
    pat = _PRAGMA_RES.get(tool)
    if pat is None:
        pat = re.compile(
            r"#\s*" + re.escape(tool)
            + r":\s*ignore\[(?P<rules>[A-Z0-9,\s]+)\](?:\s*:\s*(?P<reason>.*\S))?"
        )
        _PRAGMA_RES[tool] = pat
    return pat


@dataclass
class Pragma:
    line: int
    rules: Set[str]
    reason: str
    used: bool = False


def parse_pragmas(source: str, tool: str = "fdblint") -> Dict[int, Pragma]:
    """Pragmas from REAL comment tokens only: a pragma example quoted in a
    docstring or string literal must not register (it would then be
    reported as stale PRG002 with no way to appease it)."""
    pat = _pragma_re(tool)
    pragmas: Dict[int, Pragma] = {}
    for tok in tokenize.generate_tokens(io.StringIO(source).readline):
        if tok.type != tokenize.COMMENT:
            continue
        m = pat.search(tok.string)
        if not m:
            continue
        line = tok.start[0]
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        pragmas[line] = Pragma(line, rules, (m.group("reason") or "").strip())
    return pragmas


def pragma_sanctions(
    pragmas: Dict[int, Pragma], line: int, rules: Tuple[str, ...]
) -> bool:
    """True when `line` carries a pragma for any of `rules` — used by the
    interprocedural pass to treat pragma'd sites as sanctioned boundaries
    (a reasoned suppression of a source must also stop its taint: the
    reason asserts the site is fine, so callers are fine too)."""
    p = pragmas.get(line)
    return p is not None and bool(p.rules & set(rules))


def apply_pragmas(
    findings: List[Finding], pragmas: Dict[int, Pragma], relpath: str,
    rules: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Mark findings suppressed by same-line (or same-statement-span)
    pragmas, then police the pragmas themselves: PRG001 (no reason) and
    PRG002 (suppresses nothing / unknown rule) are never suppressible.
    Must run ONCE per file over the findings of EVERY pass, or a pragma
    that only suppresses an interprocedural finding would look stale.
    `rules` is the rule universe the unknown-rule check validates against
    (default: the source-level RULES registry; jaxcheck passes its own)."""
    known = set(RULES if rules is None else rules)
    out: List[Finding] = []
    for f in findings:
        # A pragma anywhere on the flagged statement's physical lines
        # suppresses it (a multi-line expression puts the node's lineno on
        # a different line than the trailing comment).
        for ln in range(f.line, max(f.end_line, f.line) + 1):
            p = pragmas.get(ln)
            if p is not None and f.rule in p.rules:
                p.used = True
                f.suppressed = True
                f.reason = p.reason
                break
        out.append(f)
    for p in pragmas.values():
        unknown = p.rules - known
        if unknown:
            out.append(Finding(
                "PRG002", relpath, p.line, 0,
                f"pragma names unknown rule(s) {sorted(unknown)}",
            ))
        if not p.reason:
            out.append(Finding(
                "PRG001", relpath, p.line, 0,
                "ignore pragma carries no reason (append ': why')",
            ))
        if not p.used and not unknown:
            out.append(Finding(
                "PRG002", relpath, p.line, 0,
                f"pragma for {sorted(p.rules)} suppresses nothing here",
            ))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# ---------------------------------------------------------------------------
# Symbol resolution: map names/attribute chains to canonical dotted paths
# ---------------------------------------------------------------------------


class Aliases:
    """Tracks module-level import bindings so ``t.monotonic`` resolves to
    ``time.monotonic`` regardless of aliasing.  Function-local imports are
    folded into the same table — a rename collision between scopes could in
    principle misattribute, which for a linter errs on the loud side."""

    def __init__(self):
        self.map: Dict[str, str] = {}

    def add_import(self, node: ast.Import):
        for a in node.names:
            self.map[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )

    def add_import_from(self, node: ast.ImportFrom):
        if node.module is None or node.level:
            return  # relative import: package-internal, never a stdlib clock
        for a in node.names:
            if a.name == "*":
                continue
            self.map[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted canonical path for a Name/Attribute chain, or None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.map.get(node.id, node.id)
        return ".".join([root] + list(reversed(parts)))

    def root_bound(self, node: ast.AST) -> bool:
        """True iff the chain's root name is an import binding.  A local
        variable that merely *shares* a module name (e.g. a parameter
        named `random` holding a DeterministicRandom — this repo's core
        idiom) must not light up module-prefix rules."""
        while isinstance(node, ast.Attribute):
            node = node.value
        return isinstance(node, ast.Name) and node.id in self.map


# Simple (non-compound) statements: the unit of pragma suppression scope —
# a pragma on any physical line of one covers it, and a def/if body must
# never become one giant suppression region.
SIMPLE_STMTS = (
    ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr, ast.Return,
    ast.Import, ast.ImportFrom, ast.Raise, ast.Assert, ast.Delete,
    ast.Global, ast.Nonlocal,
)


def innermost_simple_stmt_end(
    node: ast.AST, stmt_spans: List[Tuple[int, int]]
) -> int:
    """End line of the innermost simple statement containing `node`, or
    the node's own span outside any (decorators, if/while tests)."""
    end = getattr(node, "end_lineno", None) or node.lineno
    best = None
    for s, e in stmt_spans:
        if s <= node.lineno <= e:
            if best is None or s > best[0] or (s == best[0] and e < best[1]):
                best = (s, e)
    return max(end, best[1]) if best is not None else end


def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """['self', 'x', 'y'] for a pure Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    parts.reverse()
    return parts
