"""Project loader: per-file AST/summary cache + pass orchestration.

A file is reduced once per content version to (raw findings from every
per-file pass, pragmas, ModuleSummary) and cached — keyed by
(mtime_ns, size) with a content-sha1 fallback, invalidated wholesale when
the linter's own sources change.  The interprocedural DET101 pass and all
config/pragma application run on EVERY lint from the cached per-file
facts, so a warm full-repo lint does no parsing at all (the tier-1 gate's
<=5s budget) while cross-file taint stays correct when one file changes.

The cache lives OUTSIDE the repo (a per-user 0700 tempdir subdirectory
keyed by scan-root path, or $FDBLINT_CACHE) so linting never dirties the
working tree."""

from __future__ import annotations

import ast
import copy
import hashlib
import os
import pickle
import tempfile
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .base import (
    Finding,
    LintConfig,
    Pragma,
    SKIP_MODULE_GLOBS,
    _match_any,
    apply_pragmas,
    parse_pragmas,
)
from .det101 import run_det101
from .graphs import CallGraph, ModuleSummary, collect_summary
from .hotpath import (
    HOT_RULES,
    ModuleHotFacts,
    collect_hotpath,
    run_hotpath_rules,
)
from .local import ModuleLinter
from .promises import (
    ModulePromiseFacts,
    collect_promise_facts,
    run_promise_rules,
)
from .races import ModuleRaceFacts, collect_race, run_race_rules
from .rpy import run_rpy001
from .waitrules import run_wait_rules

CACHE_ENV = "FDBLINT_CACHE"


@dataclass
class FileRecord:
    sig: Tuple[int, int]            # (mtime_ns, size)
    digest: str
    raw_findings: List[Finding]     # all per-file passes, unfiltered
    pragmas: Dict[int, Pragma]
    summary: ModuleSummary
    facts: ModulePromiseFacts       # promise-lifecycle facts (PRM/TSK)
    races: ModuleRaceFacts          # atomicity/lost-update facts (RACE/ENV002)
    hot: ModuleHotFacts             # host-path perf facts (HOT, perfcheck)
    perf_pragmas: Dict[int, Pragma]  # the `# perfcheck:` namespace


_FINGERPRINT: Optional[str] = None


def _linter_fingerprint() -> str:
    """sha1 over this package's sources: any linter change invalidates.
    Memoized per process — the sources cannot change under a running
    lint, and load+save would otherwise hash them twice per run."""
    global _FINGERPRINT
    if _FINGERPRINT is None:
        here = os.path.dirname(os.path.abspath(__file__))
        h = hashlib.sha1()
        for fn in sorted(os.listdir(here)):
            if fn.endswith(".py"):
                with open(os.path.join(here, fn), "rb") as f:
                    h.update(f.read())
        _FINGERPRINT = h.hexdigest()
    return _FINGERPRINT


def iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def default_cache_path(root: str) -> str:
    """Per-user PRIVATE cache location.  The cache is a pickle, so it must
    never load from a path another local user could pre-plant: a
    predictable name directly in the shared tempdir would be arbitrary
    code execution at load time on a multi-user host.  The per-uid
    subdirectory is created 0700 and verified owned-and-private; on any
    doubt we fall back to a fresh mkdtemp (cold cache, never unsafe)."""
    uid = getattr(os, "getuid", lambda: None)()
    cache_dir = os.path.join(
        tempfile.gettempdir(), f"fdblint-{'u' if uid is None else uid}"
    )
    try:
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        st = os.stat(cache_dir)
        owned = uid is None or getattr(st, "st_uid", uid) == uid
        if not owned or (st.st_mode & 0o022):
            cache_dir = tempfile.mkdtemp(prefix="fdblint-")
    except OSError:
        cache_dir = tempfile.mkdtemp(prefix="fdblint-")
    key = hashlib.sha1(os.path.abspath(root).encode()).hexdigest()[:12]
    return os.path.join(cache_dir, f"{key}.pkl")


class Project:
    def __init__(
        self,
        root: str,
        config: Optional[LintConfig] = None,
        cache_path: Optional[str] = None,
        use_cache: bool = True,
    ):
        self.root = root
        self.config = config or LintConfig()
        self.use_cache = use_cache
        self.cache_path = (
            cache_path
            or os.environ.get(CACHE_ENV)
            or default_cache_path(root)
        )
        # Root package name for normalizing in-package absolute imports.
        self.root_pkg = (
            os.path.basename(os.path.abspath(root))
            if os.path.exists(os.path.join(root, "__init__.py"))
            else None
        )
        self.records: Dict[str, FileRecord] = {}
        self.stats = {"files": 0, "parsed": 0, "cache_hits": 0}

    # -- cache -------------------------------------------------------------
    def _load_cache(self) -> Dict[str, FileRecord]:
        if not self.use_cache:
            return {}
        try:
            with open(self.cache_path, "rb") as f:
                payload = pickle.load(f)
            if payload.get("fingerprint") != _linter_fingerprint():
                return {}
            return payload.get("records", {})
        except Exception:
            # Missing/corrupt/stale-format cache: silently rebuild — the
            # cache is a pure accelerator, never a correctness input.
            return {}

    def _save_cache(self):
        if not self.use_cache:
            return
        try:
            tmp = self.cache_path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(
                    {
                        "fingerprint": _linter_fingerprint(),
                        "records": self.records,
                    },
                    f,
                    protocol=pickle.HIGHEST_PROTOCOL,
                )
            os.replace(tmp, self.cache_path)
        except Exception:
            pass  # read-only tempdir etc.: run uncached

    # -- loading -----------------------------------------------------------
    def _analyze_file(self, path: str, relpath: str, sig, digest, source) -> FileRecord:
        tree = ast.parse(source, filename=relpath)
        findings = ModuleLinter(relpath, tree).run()
        findings += run_wait_rules(relpath, tree)
        findings += run_rpy001(relpath, tree)
        race_findings, races = collect_race(relpath, tree)
        findings += race_findings
        pragmas = parse_pragmas(source)
        summary = collect_summary(relpath, tree, self.root_pkg)
        facts = collect_promise_facts(relpath, tree)
        hot = collect_hotpath(relpath, tree)
        perf_pragmas = parse_pragmas(source, tool="perfcheck")
        self.stats["parsed"] += 1
        return FileRecord(sig, digest, findings, pragmas, summary, facts,
                          races, hot, perf_pragmas)

    def load(self):
        cached = self._load_cache()
        dirty = False  # anything parsed or sig-refreshed -> rewrite cache
        for path in iter_py_files(self.root):
            relpath = os.path.relpath(path, self.root).replace(os.sep, "/")
            if _match_any(relpath, SKIP_MODULE_GLOBS):
                continue
            self.stats["files"] += 1
            st = os.stat(path)
            sig = (st.st_mtime_ns, st.st_size)
            rec = cached.get(relpath)
            if rec is not None and rec.sig == sig:
                self.stats["cache_hits"] += 1
                self.records[relpath] = rec
                continue
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            digest = hashlib.sha1(source.encode()).hexdigest()
            dirty = True
            if rec is not None and rec.digest == digest:
                # Touched but unchanged (checkout, formatter no-op): reuse
                # the analysis, refresh the fast-path signature.
                rec.sig = sig
                self.stats["cache_hits"] += 1
                self.records[relpath] = rec
                continue
            self.records[relpath] = self._analyze_file(
                path, relpath, sig, digest, source
            )
        # A pure-hit warm run (the tier-1 gate's steady state) learned
        # nothing: skip the pickle rewrite.  Note a file DELETED since the
        # last run leaves its stale record in the file, harmlessly — every
        # lookup is keyed by the files that exist NOW.
        if dirty or set(self.records) != set(cached):
            self._save_cache()

    # -- linting -----------------------------------------------------------
    def lint(self, tools: Tuple[str, ...] = ("fdblint", "perfcheck")) -> List[Finding]:
        """Run the selected source-level tools over one warm load.
        `tools` may name "fdblint" (the determinism/actor/race families)
        and/or "perfcheck" (the HOT family) — both share the cached
        per-file facts and ONE CallGraph, but apply their own pragma
        namespaces so neither polices the other's suppressions."""
        if not self.records:
            self.load()
        summaries = {rp: r.summary for rp, r in self.records.items()}
        graph = CallGraph(summaries)  # ONE linker shared by every pass
        run_fdb = "fdblint" in tools
        run_perf = "perfcheck" in tools
        consumed: Dict[str, set] = {}
        det_by_file: Dict[str, List[Finding]] = {}
        if run_fdb:
            facts = {rp: r.facts for rp, r in self.records.items()}
            pragmas_by_file = {rp: r.pragmas for rp, r in self.records.items()}
            det = run_det101(
                summaries, pragmas_by_file, self.config,
                consumed_pragmas=consumed, graph=graph,
            )
            det += run_promise_rules(summaries, facts, graph=graph)
            races = {rp: r.races for rp, r in self.records.items()}
            det += run_race_rules(summaries, races, graph=graph)
            for f in det:
                det_by_file.setdefault(f.path, []).append(f)
        perf_by_file: Dict[str, List[Finding]] = {}
        if run_perf:
            hot = {rp: r.hot for rp, r in self.records.items()}
            for f in run_hotpath_rules(summaries, hot, self.config, graph=graph):
                perf_by_file.setdefault(f.path, []).append(f)
        out: List[Finding] = []
        for rp, rec in sorted(self.records.items()):
            # Work on copies: cached records must stay pristine (pragma
            # `used` flags and suppression marks are per-run state).
            if run_fdb:
                findings = [copy.copy(f) for f in rec.raw_findings]
                findings += [copy.copy(f) for f in det_by_file.get(rp, [])]
                findings = [
                    f for f in findings if not self.config.allows(f.rule, rp)
                ]
                pragmas = {
                    ln: Pragma(p.line, set(p.rules), p.reason,
                               used=ln in consumed.get(rp, ()))
                    for ln, p in rec.pragmas.items()
                }
                out.extend(apply_pragmas(findings, pragmas, rp))
            if run_perf:
                pf = [copy.copy(f) for f in perf_by_file.get(rp, [])]
                pf = [f for f in pf if not self.config.allows(f.rule, rp)]
                perf_pragmas = {
                    ln: Pragma(p.line, set(p.rules), p.reason)
                    for ln, p in rec.perf_pragmas.items()
                }
                out.extend(
                    apply_pragmas(pf, perf_pragmas, rp, rules=HOT_RULES)
                )
        out.sort(key=lambda f: (f.path, f.line, f.rule))
        return out


# ---------------------------------------------------------------------------
# Single-source / single-file / package entry points (stable public API)
# ---------------------------------------------------------------------------


def lint_source(
    source: str, relpath: str, config: Optional[LintConfig] = None,
    whole_project: bool = True,
    tools: Tuple[str, ...] = ("fdblint", "perfcheck"),
) -> List[Finding]:
    """Lint one module's source with every per-file pass plus DET101
    restricted to the module's own call graph; findings suppressed by
    same-line pragmas are returned with suppressed=True.  PRG001/PRG002
    police the pragmas themselves and are never suppressible.

    `whole_project` controls the PRM attr-entity rules' frame: True (the
    default, right for self-contained sources) treats this module as the
    entire project, so "no code in the project sends" can fire; False
    (the standalone-FILE path, lint_file) assumes unseen sibling files
    may send and runs only the function-local entity rules."""
    config = config or LintConfig()
    if _match_any(relpath, SKIP_MODULE_GLOBS):
        return []
    tree = ast.parse(source, filename=relpath)
    summary = collect_summary(relpath, tree, None)
    graph = CallGraph({relpath: summary})
    out: List[Finding] = []
    if "fdblint" in tools:
        findings = ModuleLinter(relpath, tree).run()
        findings += run_wait_rules(relpath, tree)
        findings += run_rpy001(relpath, tree)
        race_findings, races = collect_race(relpath, tree)
        findings += race_findings
        pragmas = parse_pragmas(source)
        consumed: Dict[str, set] = {}
        findings += run_det101(
            {relpath: summary}, {relpath: pragmas}, config,
            consumed_pragmas=consumed, graph=graph,
        )
        findings += run_promise_rules(
            {relpath: summary}, {relpath: collect_promise_facts(relpath, tree)},
            whole_project=whole_project, graph=graph,
        )
        findings += run_race_rules(
            {relpath: summary}, {relpath: races},
            whole_project=whole_project, graph=graph,
        )
        findings = [f for f in findings if not config.allows(f.rule, relpath)]
        for ln in consumed.get(relpath, ()):
            pragmas[ln].used = True
        out += apply_pragmas(findings, pragmas, relpath)
    if "perfcheck" in tools:
        hot = {relpath: collect_hotpath(relpath, tree)}
        perf = run_hotpath_rules({relpath: summary}, hot, config, graph=graph)
        perf = [f for f in perf if not config.allows(f.rule, relpath)]
        perf_pragmas = parse_pragmas(source, tool="perfcheck")
        out += apply_pragmas(perf, perf_pragmas, relpath, rules=HOT_RULES)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_file(
    path: str, root: str, config: Optional[LintConfig] = None
) -> List[Finding]:
    relpath = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    # A real file linted alone: sibling files exist but are not loaded,
    # so the project-global PRM attr rules must not claim "no code in
    # the project sends" from this restricted view.
    return lint_source(source, relpath, config, whole_project=False)


def lint_package(
    root: str,
    config: Optional[LintConfig] = None,
    use_cache: bool = False,
    cache_path: Optional[str] = None,
) -> List[Finding]:
    """Lint every .py under root (root is the package directory; paths in
    findings are relative to it).  A single .py file is reported relative
    to its outermost enclosing package, so that allowlist / traced-module
    globs like 'rpc/real_network.py' keep matching (via _match_any's
    trailing-sub-path semantics) in single-file mode.

    A file INSIDE a package is linted with the whole enclosing package
    loaded (cache-warm) and the result filtered to that file — the same
    trick as --changed-only — so interprocedural DET101 context is
    complete and a pragma cutting a cross-module taint edge is consumed
    exactly as in a package scan instead of aging into a bogus PRG002
    (editor/pre-commit integrations lint one file at a time)."""
    if os.path.isfile(root):
        path = os.path.abspath(root)
        d = os.path.dirname(path)
        pkg_root = None
        while os.path.exists(os.path.join(d, "__init__.py")):
            pkg_root = d
            d = os.path.dirname(d)
        if pkg_root is None:
            # Standalone module: no package to load, single-module DET101.
            return lint_file(root, d, config)
        rel_in_pkg = os.path.relpath(path, pkg_root).replace(os.sep, "/")
        prefix = os.path.basename(pkg_root)
        proj = Project(
            pkg_root, config, cache_path=cache_path, use_cache=use_cache
        )
        out = []
        for f in proj.lint():
            if f.path == rel_in_pkg:
                f = copy.copy(f)
                f.path = f"{prefix}/{f.path}"
                out.append(f)
        return out
    return Project(
        root, config, cache_path=cache_path, use_cache=use_cache
    ).lint()
