"""fdblint CLI: text/json/SARIF output, incremental --changed-only mode.

``python -m foundationdb_tpu.tools.fdblint [paths] [--format=text|json|sarif]
[--changed-only] [--cache/--no-cache] [--config FILE] [--list-rules]``;
exit 0 iff no unsuppressed findings survive the filters."""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional

from .base import Finding, LintConfig, RULES
from .project import Project, lint_package

SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def count_by_rule(findings: List[Finding]) -> Dict[str, Dict[str, int]]:
    """{rule: {"flagged": n, "suppressed": m}} for every rule that fired."""
    out: Dict[str, Dict[str, int]] = {}
    for f in findings:
        slot = out.setdefault(f.rule, {"flagged": 0, "suppressed": 0})
        slot["suppressed" if f.suppressed else "flagged"] += 1
    return {r: out[r] for r in sorted(out)}


# Always shown in the counts line, zero or not: a RACE/ENV002/HOT count
# that silently vanished from the tier-1 output is how a burned-down
# family quietly regrows (the racecheck PR's explicit gate; ISSUE 20
# extends it to perfcheck's HOT family).
_ALWAYS_COUNTED = ("ENV002", "RACE001", "RACE002", "RACE003", "RACE004",
                   "HOT001", "HOT002", "HOT003", "HOT004")


def format_counts(findings: List[Finding]) -> str:
    counts = count_by_rule(findings)
    for rule in _ALWAYS_COUNTED:
        counts.setdefault(rule, {"flagged": 0, "suppressed": 0})
    counts = {r: counts[r] for r in sorted(counts)}
    if not counts:
        return "per-rule: (none)"
    cells = [
        f"{rule}={c['flagged']}+{c['suppressed']}s" for rule, c in counts.items()
    ]
    return "per-rule (flagged+suppressed): " + " ".join(cells)


_TOOL_DOCS = {
    "fdblint": "README.md#determinism-rules-fdblint",
    "jaxcheck": "README.md#jaxpr-structural-rules-jaxcheck",
    "perfcheck": "README.md#host-path-performance-rules-perfcheck",
}


def to_sarif(
    shown: List[Finding],
    rules: Optional[Dict[str, str]] = None,
    tool: str = "fdblint",
) -> dict:
    rules = RULES if rules is None else rules
    results = []
    for f in shown:
        res = {
            "ruleId": f.rule,
            "level": "note" if f.suppressed else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": f.line,
                        "startColumn": max(1, f.col + 1),
                    },
                }
            }],
        }
        if f.suppressed:
            res["suppressions"] = [{
                "kind": "inSource",
                "justification": f.reason,
            }]
        results.append(res)
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": tool,
                "informationUri": _TOOL_DOCS.get(
                    tool, _TOOL_DOCS["fdblint"]),
                "rules": [
                    {"id": rule, "shortDescription": {"text": desc}}
                    for rule, desc in sorted(rules.items())
                ],
            }},
            "results": results,
        }],
    }


def changed_files(repo_dir: str) -> Optional[List[str]]:
    """Absolute paths of files changed vs HEAD plus untracked, or None when
    not in a git checkout (callers then fall back to a full scan)."""
    def git(cwd, *args):
        return subprocess.run(
            ["git", *args], cwd=cwd, capture_output=True, text=True
        )
    try:
        top = git(repo_dir, "rev-parse", "--show-toplevel")
    except OSError:
        return None  # no git binary on this host: full scan
    if top.returncode != 0:
        return None
    root = top.stdout.strip()
    # Both commands from the TOPLEVEL: `ls-files --others` is CWD-relative
    # while `diff --name-only` is root-relative — mixing them from a
    # subdirectory silently mis-joins the untracked paths.
    names: List[str] = []
    diff = git(root, "diff", "--name-only", "HEAD", "--")
    if diff.returncode == 0:
        names += diff.stdout.splitlines()
    others = git(root, "ls-files", "--others", "--exclude-standard")
    if others.returncode == 0:
        names += others.stdout.splitlines()
    return [os.path.join(root, n) for n in names if n.endswith(".py")]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdblint",
        description="Multi-pass determinism & actor-hygiene analyzer "
                    "(the actor compiler's static-gate role).",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="package dirs or .py files (default: foundationdb_tpu)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--config", help="JSON allowlist config to merge over defaults")
    ap.add_argument("--no-default-config", action="store_true",
                    help="ignore the built-in allowlist")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print pragma-suppressed findings")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only in files changed vs git HEAD "
                         "(+ untracked); the whole project is still loaded "
                         "so interprocedural taint stays correct")
    ap.add_argument("--cache", action="store_true", default=None,
                    help="per-file analysis cache (default for directory "
                         "scans; stored in tempdir or $FDBLINT_CACHE)")
    ap.add_argument("--no-cache", dest="cache", action="store_false")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in RULES.items():
            print(f"{rule}  {desc}")
        return 0

    if args.config:
        config = LintConfig.load(args.config, use_defaults=not args.no_default_config)
    elif args.no_default_config:
        config = LintConfig(allow={})
    else:
        config = LintConfig()

    paths = args.paths or [
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    ]
    use_cache = args.cache if args.cache is not None else True
    # (root-or-None, argument, findings) per argument: --changed-only
    # filters each directory scan against ITS git checkout; explicit file
    # arguments and non-git roots fall back to the full result rather than
    # silently dropping every finding.
    groups: List[tuple] = []
    for p in paths:
        if os.path.isdir(p):
            groups.append((p, p, Project(p, config, use_cache=use_cache).lint()))
        else:
            groups.append((None, p, lint_package(p, config, use_cache=use_cache)))
    findings = [f for _, _, fs in groups for f in fs]

    if args.changed_only:
        kept: List[Finding] = []
        for root, _, fs in groups:
            got = changed_files(root) if root is not None else None
            if got is None:
                kept.extend(fs)  # file arg / not a git checkout: full scan
                continue
            keep = set()
            for c in got:
                rel = os.path.relpath(os.path.abspath(c), root)
                rel = rel.replace(os.sep, "/")
                if not rel.startswith(".."):
                    keep.add(rel)
            # Finding paths and `keep` are both root-relative: exact match
            # only (a suffix fallback would adopt same-named files from
            # deeper directories).
            kept.extend(f for f in fs if f.path in keep)
        findings = kept

    unsuppressed = [f for f in findings if not f.suppressed]
    shown = findings if args.show_suppressed else unsuppressed
    if args.format == "json":
        print(json.dumps(
            {
                "findings": [f.to_dict() for f in shown],
                "total": len(findings),
                "unsuppressed": len(unsuppressed),
                "counts": count_by_rule(findings),
            },
            indent=2,
        ))
    elif args.format == "sarif":
        # SARIF consumers (GitHub code scanning) resolve URIs against the
        # REPOSITORY root, not our scan root: a gate run as
        # `fdblint foundationdb_tpu --format=sarif` from the repo top
        # would otherwise emit 'server/proxy.py' and every annotation
        # fails to attach.  Rewrite each finding's path relative to the
        # CWD the gate runs from (the repo root in CI); a path that
        # escapes the CWD stays absolute rather than lying with '..'s.
        cwd = os.getcwd()
        for root, arg, fs in groups:
            for f in fs:
                ap = (
                    os.path.join(os.path.abspath(root), f.path)
                    if root is not None
                    else os.path.abspath(arg)
                )
                rel = os.path.relpath(ap, cwd).replace(os.sep, "/")
                f.path = rel if not rel.startswith("..") else ap.replace(os.sep, "/")
        print(json.dumps(to_sarif(shown), indent=2))
    else:
        for f in shown:
            tag = " (suppressed: %s)" % f.reason if f.suppressed else ""
            print(f.format() + tag)
        n_sup = len(findings) - len(unsuppressed)
        print(
            f"fdblint: {len(unsuppressed)} finding(s), {n_sup} suppressed; "
            + format_counts(findings),
            file=sys.stderr,
        )
    return 1 if unsuppressed else 0
