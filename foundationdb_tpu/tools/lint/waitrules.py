"""WAIT001/WAIT002: state held across ``await`` — the Python form of the
actor compiler's "state variable holding a reference across wait()"
rejection (flow/actorcompiler/ActorCompiler.cs).

While an actor is suspended at an ``await``, every other actor runs: a
local captured from ``self.*`` shared state before the suspension may be
stale (the attribute was reassigned) or silently mutating (the container
changed) when control returns, and a live iterator over shared state is
the exact analog of the invalidated-iterator class the reference rejects
at compile time.

WAIT001  a local bound from mutable shared state (``self.X`` attribute
         chain, ``self.X[k]`` element, or a live view/iterator
         ``self.X.items()`` / ``iter(self.X)`` / ``enumerate(self.X)``)
         before an ``await`` and DEREFERENCED after it without a re-read.
         Live views flag on ANY post-await use; plain captures flag only
         on deref uses (attribute/subscript/call/iteration/membership) —
         using a captured value as a value is a legitimate snapshot.
WAIT002  ``for ... in <shared state>`` whose loop body awaits: the
         container is reachable by every actor that runs during the
         suspension, so the iteration can skip/double entries or raise
         "changed size during iteration" only under the exact interleaving
         a seed may never hit.

Both rules fire only on attributes with MUTATION EVIDENCE: some method of
the class (outside ``__init__``) reassigns, deletes, subscript-assigns, or
calls a known mutator on the attribute.  Config-immutable attributes
(assigned only at construction) are snapshots by definition and never
flag.  Re-reading after the await (rebinding the local) kills the capture;
wrapping in ``list()``/``sorted()``/``.copy()`` is a deliberate snapshot
and never flags."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, SIMPLE_STMTS, attr_chain

VIEW_METHODS = {"items", "keys", "values"}
VIEW_FUNCS = {"iter", "enumerate", "reversed"}
SNAPSHOT_FUNCS = {"list", "tuple", "set", "dict", "sorted", "frozenset",
                  "sum", "len", "min", "max", "any", "all"}
MUTATOR_METHODS = {
    "append", "extend", "insert", "pop", "popitem", "remove", "discard",
    "add", "update", "clear", "setdefault",
}


def _pragma_span_end(s: ast.stmt) -> int:
    """End line of the statement's pragma-suppression scope: the full
    span for a simple statement (a pragma on any physical line of a
    multiline call covers it), but only the HEADER expression for a
    compound one — a pragma deep inside an if/while/for body must never
    suppress a finding on the header (base.SIMPLE_STMTS discipline)."""
    if isinstance(s, SIMPLE_STMTS):
        return getattr(s, "end_lineno", s.lineno) or s.lineno
    if isinstance(s, (ast.If, ast.While)):
        n: ast.AST = s.test
    elif isinstance(s, (ast.For, ast.AsyncFor)):
        n = s.iter
    elif isinstance(s, (ast.With, ast.AsyncWith)):
        n = s.items[-1].optional_vars or s.items[-1].context_expr
    elif isinstance(s, ast.Match):
        n = s.subject
    else:
        return s.lineno
    return getattr(n, "end_lineno", s.lineno) or s.lineno


def _self_attr(node: ast.AST) -> Optional[str]:
    """First attribute name of a pure self/cls-rooted chain, else None."""
    chain = attr_chain(node)
    if chain and len(chain) >= 2 and chain[0] in ("self", "cls"):
        return chain[1]
    return None


def mutable_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attrs with mutation evidence outside __init__."""
    out: Set[str] = set()
    for m in cls.body:
        if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if m.name == "__init__":
            continue
        for node in ast.walk(m):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    a = _self_attr(t)
                    if a is not None:
                        out.add(a)
                    elif isinstance(t, ast.Subscript):
                        a = _self_attr(t.value)
                        if a is not None:
                            out.add(a)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    a = _self_attr(t)
                    if a is not None:
                        out.add(a)
                    elif isinstance(t, ast.Subscript):
                        a = _self_attr(t.value)
                        if a is not None:
                            out.add(a)
            elif isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in MUTATOR_METHODS
                ):
                    a = _self_attr(node.func.value)
                    if a is not None:
                        out.add(a)
    return out


class _Capture:
    __slots__ = ("kind", "attr", "epoch", "line", "expr")

    def __init__(self, kind: str, attr: str, epoch: int, line: int, expr: str):
        self.kind = kind      # "view" | "attr"
        self.attr = attr      # the self.<attr> root
        self.epoch = epoch    # await count at binding
        self.line = line
        self.expr = expr      # source-ish description for the message


def _join_states(
    arms: List[Tuple[Dict[str, _Capture], int]],
) -> Tuple[Dict[str, _Capture], int]:
    """Pessimistic join of (env, epoch) control-flow states.  Staleness is
    the GAP epoch - capture.epoch, so a capture's gap must be judged
    against its OWN arm's epoch, never a sibling's: the joined epoch is
    the max over arms, and each surviving capture is rebased so it keeps
    exactly the widest gap it had in any arm that holds it."""
    epoch = max(e for _, e in arms)
    merged: Dict[str, _Capture] = {}
    for env, arm_epoch in arms:
        for name, cap in env.items():
            gap = arm_epoch - cap.epoch
            prev = merged.get(name)
            if prev is None or epoch - prev.epoch < gap:
                merged[name] = _Capture(
                    cap.kind, cap.attr, epoch - gap, cap.line, cap.expr
                )
    return merged, epoch


class _AsyncScope:
    """Walks one async function body in source order, tracking captures,
    await epochs, and flagging stale uses.  Nested function/lambda bodies
    are OPAQUE (a closure deliberately defers evaluation; flagging its
    uses would punish every callback), but nested async defs are analyzed
    as scopes of their own by the caller."""

    def __init__(self, relpath: str, cls_mutable: Set[str],
                 findings: List[Finding], func_name: str):
        self.relpath = relpath
        self.mutable = cls_mutable
        self.findings = findings
        self.func_name = func_name
        self.epoch = 0
        self.env: Dict[str, _Capture] = {}
        self.flagged: Set[Tuple[int, str]] = set()
        self.stmt_end = 0  # end line of current simple statement (pragma scope)

    # -- capture classification -------------------------------------------
    def _shared_chain_attr(self, node: ast.AST) -> Optional[str]:
        """self.X... chain (len>=2) whose X has mutation evidence."""
        a = _self_attr(node)
        if a is not None and a in self.mutable:
            return a
        return None

    def classify(self, value: ast.AST) -> Optional[Tuple[str, str, str]]:
        """(kind, attr, describe) when `value` captures shared state."""
        a = self._shared_chain_attr(value)
        if a is not None:
            return ("attr", a, f"self.{a}")
        if isinstance(value, ast.Subscript):
            a = self._shared_chain_attr(value.value)
            if a is not None:
                return ("attr", a, f"self.{a}[...]")
        if isinstance(value, ast.Call):
            f = value.func
            if isinstance(f, ast.Attribute) and f.attr in VIEW_METHODS:
                a = self._shared_chain_attr(f.value)
                if a is not None:
                    return ("view", a, f"self.{a}.{f.attr}()")
            if (
                isinstance(f, ast.Name)
                and f.id in VIEW_FUNCS
                and value.args
            ):
                inner = value.args[0]
                a = self._shared_chain_attr(inner)
                if a is None and isinstance(inner, ast.Call):
                    g = inner.func
                    if isinstance(g, ast.Attribute) and g.attr in VIEW_METHODS:
                        a = self._shared_chain_attr(g.value)
                if a is not None:
                    return ("view", a, f"{f.id}(self.{a}...)")
        if isinstance(value, ast.GeneratorExp):
            for gen in value.generators:
                a = self._shared_chain_attr(gen.iter)
                if a is not None:
                    return ("view", a, f"(... for ... in self.{a})")
        return None

    # -- flagging ----------------------------------------------------------
    def _flag(self, rule: str, node: ast.AST, msg: str):
        key = (node.lineno, msg)
        if key in self.flagged:
            return
        self.flagged.add(key)
        self.findings.append(Finding(
            rule, self.relpath, node.lineno, node.col_offset, msg,
            end_line=max(self.stmt_end, getattr(node, "end_lineno", 0) or 0),
        ))

    def _use(self, node: ast.Name, deref: bool):
        cap = self.env.get(node.id)
        if cap is None or self.epoch <= cap.epoch:
            return
        if cap.kind == "view" or deref:
            what = "live view" if cap.kind == "view" else "shared-state capture"
            self._flag(
                "WAIT001", node,
                f"'{node.id}' ({what} of {cap.expr}, bound at line "
                f"{cap.line}) used after an await without re-read — other "
                f"actors ran during the suspension (state-across-wait)",
            )

    # -- expression walk ---------------------------------------------------
    def expr(self, node: ast.AST, deref: bool = False):
        if node is None:
            return
        t = type(node)
        if t is ast.Name:
            if isinstance(node.ctx, ast.Load):
                self._use(node, deref)
            return
        if t is ast.Await:
            self.expr(node.value)
            self.epoch += 1
            return
        if t is ast.NamedExpr:
            # `(snap := self.d)` captures exactly like `snap = self.d`.
            self.expr(node.value)
            self._bind(node.target, node.value, node.lineno)
            return
        if t is ast.Attribute:
            self.expr(node.value, deref=isinstance(node.value, ast.Name))
            return
        if t is ast.Subscript:
            self.expr(node.value, deref=isinstance(node.value, ast.Name))
            self.expr(node.slice)
            return
        if t is ast.Call:
            self.expr(node.func, deref=isinstance(node.func, ast.Name))
            for a in node.args:
                self.expr(a, deref=isinstance(a, ast.Starred))
            for kw in node.keywords:
                self.expr(kw.value)
            return
        if t is ast.Compare:
            self.expr(node.left)
            for op, cmp in zip(node.ops, node.comparators):
                self.expr(cmp, deref=isinstance(op, (ast.In, ast.NotIn)))
            return
        if t in (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef):
            return  # opaque deferred scope
        if t in (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp):
            # Immediate iteration (genexps are captures, handled at
            # classification): the ITER expressions are deref uses.
            for gen in node.generators:
                self.expr(gen.iter, deref=isinstance(gen.iter, ast.Name))
                for cond in gen.ifs:
                    self.expr(cond)
            if t is ast.DictComp:
                self.expr(node.key)
                self.expr(node.value)
            elif t is not ast.GeneratorExp:
                self.expr(node.elt)
            return
        for child in ast.iter_child_nodes(node):
            self.expr(child)

    # -- binding/kill ------------------------------------------------------
    def _kill_target(self, t: ast.AST):
        if isinstance(t, ast.Name):
            self.env.pop(t.id, None)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._kill_target(e)
        elif isinstance(t, ast.Starred):
            self._kill_target(t.value)

    def _bind(self, target: ast.AST, value: ast.AST, line: int):
        if isinstance(target, (ast.Tuple, ast.List)):
            # `snap, other = self.d, 1` binds element-wise — each name
            # gets its own RHS, the same capture as the two-line
            # spelling.  Starred or length-mismatched unpacks fall back
            # to killing every target name.
            if (
                isinstance(value, (ast.Tuple, ast.List))
                and len(target.elts) == len(value.elts)
                and not any(isinstance(e, ast.Starred)
                            for e in list(target.elts) + list(value.elts))
            ):
                for te, ve in zip(target.elts, value.elts):
                    self._bind(te, ve, line)
                return
            self._kill_target(target)
            return
        if not isinstance(target, ast.Name):
            self._kill_target(target)
            return
        got = self.classify(value)
        if got is not None:
            kind, attr, desc = got
            self.env[target.id] = _Capture(kind, attr, self.epoch, line, desc)
        else:
            self.env.pop(target.id, None)

    # -- statement walk ----------------------------------------------------
    def stmts(self, body: List[ast.stmt]):
        for s in body:
            self.stmt(s)

    def stmt(self, s: ast.stmt):
        self.stmt_end = _pragma_span_end(s)
        t = type(s)
        if t is ast.Assign:
            self.expr(s.value)
            for target in s.targets:
                self._bind(target, s.value, s.lineno)
                # Deref via subscript/attribute STORE on a tracked name.
                if isinstance(target, (ast.Subscript, ast.Attribute)):
                    self.expr(target.value,
                              deref=isinstance(target.value, ast.Name))
        elif t is ast.AnnAssign:
            if s.value is not None:
                self.expr(s.value)
                self._bind(s.target, s.value, s.lineno)
        elif t is ast.AugAssign:
            self.expr(s.value)
            if isinstance(s.target, ast.Name):
                self._use(s.target, deref=False)
                self.env.pop(s.target.id, None)
            else:
                self.expr(s.target.value,
                          deref=isinstance(s.target.value, ast.Name))
        elif t in (ast.Expr, ast.Return):
            self.expr(s.value)
        elif t is ast.Delete:
            for target in s.targets:
                self._kill_target(target)
        elif t is ast.If:
            self.expr(s.test)
            saved = dict(self.env)
            epoch0 = self.epoch
            self.stmts(s.body)
            then_falls = _falls_through(s.body)
            after_then, epoch_then = self.env, self.epoch
            self.env = dict(saved)
            self.epoch = epoch0
            self.stmts(s.orelse)
            # Pessimistic join over the branches that can REACH the code
            # after the If: each branch walks with its own epoch (an
            # await-free path never inherits its sibling's suspension, and
            # a re-read inside the awaiting branch really clears it), and
            # a branch ending in return/raise/break/continue drops out of
            # the join entirely.
            else_falls = _falls_through(s.orelse)
            if then_falls and else_falls:
                self.env, self.epoch = _join_states(
                    [(after_then, epoch_then), (self.env, self.epoch)]
                )
            elif then_falls:
                self.env, self.epoch = after_then, epoch_then
            # else: only the else branch reaches past (or neither — then
            # the code after is unreachable and any state is fine).
        elif t in (ast.For, ast.AsyncFor):
            self.check_wait002(s)
            self.expr(s.iter, deref=isinstance(s.iter, ast.Name))
            if t is ast.AsyncFor:
                self.epoch += 1
            pre = (dict(self.env), self.epoch)  # zero-iteration path
            self._kill_target(s.target)
            # Two passes: the second sees captures made in iteration N used
            # in iteration N+1 after a loop-tail await (back-edge stale).
            for _ in range(2):
                self.stmts(s.body)
                self._kill_target(s.target)
            # The body may run ZERO times: a re-read inside it must not
            # clear a pre-loop capture on the loop-skipped path.
            self.env, self.epoch = _join_states([pre, (self.env, self.epoch)])
            self.stmts(s.orelse)
        elif t is ast.While:
            self.expr(s.test)
            infinite = isinstance(s.test, ast.Constant) and bool(s.test.value)
            pre = (dict(self.env), self.epoch)
            for _ in range(2):
                self.stmts(s.body)
                # The test re-evaluates after every iteration: a deref in
                # it sees any await the body just performed.  The body
                # walk moved stmt_end — restore the header's scope so the
                # finding's pragma span stays on the header.
                self.stmt_end = _pragma_span_end(s)
                self.expr(s.test)
            if not infinite:
                # Zero-iteration join, as for For; `while True:` always
                # enters, so only the body's exit state applies.
                self.env, self.epoch = _join_states(
                    [pre, (self.env, self.epoch)]
                )
            self.stmts(s.orelse)
        elif t is ast.Try:
            # Pessimistic handler entry: the body may raise at ANY of its
            # statement boundaries — in particular after an await but
            # before a later re-read — so each handler walks from the join
            # of every boundary state (a capture keeps the widest await
            # gap it had at any point the exception could have fired).
            states = [(dict(self.env), self.epoch)]
            for st in s.body:
                self.stmt(st)
                states.append((dict(self.env), self.epoch))
            after_env, after_epoch = self.env, self.epoch
            h_env, h_epoch = _join_states(states)
            exits: List[Tuple[Dict[str, _Capture], int]] = []
            for h in s.handlers:
                self.env = dict(h_env)
                self.epoch = h_epoch
                if h.name is not None:
                    # `except E as name:` rebinds name to the fresh
                    # exception — it is no longer the pre-await capture.
                    self.env.pop(h.name, None)
                self.stmts(h.body)
                if _falls_through(h.body):
                    exits.append((self.env, self.epoch))
            # orelse runs only when the body completed: walk it from the
            # body's end state.  Code AFTER the try is then reached from
            # that path (if it falls through) or any falling-through
            # handler — a handler that swallowed the raise-at-await
            # carries its possibly-stale captures past the try.
            self.env, self.epoch = after_env, after_epoch
            self.stmts(s.orelse)
            if _falls_through(s.body) and _falls_through(s.orelse):
                exits.append((self.env, self.epoch))
            if exits:
                self.env, self.epoch = _join_states(exits)
            self.stmts(s.finalbody)
        elif t in (ast.With, ast.AsyncWith):
            for item in s.items:
                self.expr(item.context_expr)
                if item.optional_vars is not None:
                    self._kill_target(item.optional_vars)
            if t is ast.AsyncWith:
                self.epoch += 1
            self.stmts(s.body)
        elif t is ast.Match:
            # N-way branch, same pessimistic join as If: each case walks
            # from the pre-match state, and the no-match fallthrough path
            # joins in unless some arm is irrefutable (a bare `case _:` /
            # capture-name case with no guard always matches).
            self.expr(s.subject, deref=isinstance(s.subject, ast.Name))
            saved = (dict(self.env), self.epoch)
            exits: List[Tuple[Dict[str, _Capture], int]] = []
            irrefutable = False
            for case in s.cases:
                self.env, self.epoch = dict(saved[0]), saved[1]
                for p in ast.walk(case.pattern):
                    if isinstance(p, ast.MatchValue):
                        self.expr(p.value)
                    nm = getattr(p, "name", None) or getattr(p, "rest", None)
                    if isinstance(nm, str):
                        self.env.pop(nm, None)  # pattern binds the name
                if case.guard is not None:
                    self.expr(case.guard)
                if (case.guard is None
                        and isinstance(case.pattern, ast.MatchAs)
                        and case.pattern.pattern is None):
                    irrefutable = True
                self.stmts(case.body)
                if _falls_through(case.body):
                    exits.append((self.env, self.epoch))
            if not irrefutable:
                exits.append(saved)
            if exits:
                self.env, self.epoch = _join_states(exits)
        elif t in (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef):
            return  # nested scopes analyzed separately / opaque
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.expr(child)
                elif isinstance(child, ast.stmt):
                    self.stmt(child)

    # -- WAIT002 -----------------------------------------------------------
    def _iter_is_shared(self, it: ast.AST) -> Optional[str]:
        a = self._shared_chain_attr(it)
        if a is not None:
            return f"self.{a}"
        if isinstance(it, ast.Call):
            f = it.func
            if isinstance(f, ast.Name):
                if f.id in SNAPSHOT_FUNCS:
                    return None  # deliberate snapshot
                if f.id in VIEW_FUNCS and it.args:
                    inner = self._iter_is_shared(it.args[0])
                    return inner
                return None
            if isinstance(f, ast.Attribute):
                if f.attr == "copy":
                    return None
                if f.attr in VIEW_METHODS:
                    a = self._shared_chain_attr(f.value)
                    if a is not None:
                        return f"self.{a}.{f.attr}()"
                return None
        if isinstance(it, ast.Name):
            # A local ALIAS of shared state is still the live container —
            # one rebinding must not hide the invalidated-iterator class
            # (plain captures and views alike; snapshots never enter env).
            cap = self.env.get(it.id)
            if cap is not None:
                return cap.expr
        return None

    def check_wait002(self, s):
        desc = self._iter_is_shared(s.iter)
        if desc is None:
            return
        if isinstance(s, ast.AsyncFor):
            pass  # the header itself suspends at every __anext__
        elif not _body_awaits(s.body):
            return
        self._flag(
            "WAIT002", s,
            f"iterating {desc} while the loop body awaits — the container "
            f"is reachable by other actors during the suspension "
            f"(reference-across-wait); snapshot with list(...) first",
        )


def _falls_through(body: List[ast.stmt]) -> bool:
    """Can control run past these statements?  A trailing
    return/raise/break/continue means no (nested all-paths-return shapes
    are treated as falling through — conservative merge, never a miss)."""
    return not body or not isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue)
    )


def _body_awaits(body: List[ast.stmt]) -> bool:
    """Await anywhere in these statements, excluding nested defs/lambdas."""
    stack: List[ast.AST] = list(body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


def run_wait_rules(relpath: str, tree: ast.Module) -> List[Finding]:
    findings: List[Finding] = []

    def own_async_defs(cls: ast.ClassDef):
        """Async defs belonging to THIS class (methods and closures nested
        inside them), stopping at nested ClassDef boundaries — a nested
        class is its own shared-state scope with its own mutation
        evidence, scanned by the outer walk."""
        stack: List[ast.AST] = list(cls.body)
        while stack:
            n = stack.pop()
            if isinstance(n, ast.ClassDef):
                continue
            if isinstance(n, ast.AsyncFunctionDef):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    def scan_class(cls: ast.ClassDef):
        mut = mutable_attrs(cls)
        for node in own_async_defs(cls):
            scope = _AsyncScope(relpath, mut, findings, node.name)
            scope.stmts(node.body)

    # EVERY class — module-level, factory-local, nested — is a scope.
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            scan_class(node)
    return findings
