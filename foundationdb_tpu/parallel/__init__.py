"""Multi-device scaling: the rebuild's answer to the reference's
multi-resolver key-range sharding (ref: keyResolvers KeyRangeMap,
MasterProxyServer.actor.cpp:185; ResolutionRequestBuilder :237).

Instead of N resolver processes coordinated over TCP, the key space is
sharded across a `jax.sharding.Mesh` axis: every device holds one shard of
the conflict-history step function and resolves the (replicated) batch
against its own key range; verdicts are combined with a `pmin` collective
over ICI — the device-mesh translation of the proxy's min() combine
(MasterProxyServer.actor.cpp:492-499).
"""

from .sharded_resolver import ShardedJaxConflictSet, uniform_int_split_keys

__all__ = ["ShardedJaxConflictSet", "uniform_int_split_keys"]
