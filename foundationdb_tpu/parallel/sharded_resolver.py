"""Range-sharded conflict resolution over a TPU device mesh.

The reference scales conflict resolution by partitioning the key space
across resolver *processes* (keyResolvers KeyRangeMap,
MasterProxyServer.actor.cpp:185), splitting each transaction's conflict
ranges per resolver (ResolutionRequestBuilder.addTransaction
MasterProxyServer.actor.cpp:280-303) and combining the per-resolver verdicts
with min() (:492-499).  TooOld is only reported by resolvers that actually
received read ranges for the transaction (addTransaction only forwards the
ranges that overlap the resolver's key space).

The TPU-native translation keeps the same *semantics* but replaces processes
and TCP with a device mesh and XLA:

  - one mesh axis ("resolvers"); device d owns key range [lo_d, hi_d)
  - the history step function lives sharded on its owner device
    (leading shard axis, NamedSharding over the mesh axis)
  - the packed batch is replicated; each device clips every range to its
    own bounds (the tensor form of ResolutionRequestBuilder's split)
  - per-device `conflict.engine_jax.detect_core` runs under shard_map
  - verdict min-combine is a cross-device reduction XLA lowers onto ICI

Semantics parity note: like the reference's multi-resolver mode, a
transaction judged conflicting in shard A still gets its writes (in shard B)
inserted into B's history if B judged it committed — each resolver's
ConflictBatch commits on its local view (Resolver.actor.cpp:140-153).  The
single-shard configuration is exactly `JaxConflictSet`.
"""

from __future__ import annotations

import math
from functools import partial
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
try:
    from jax import shard_map

    _SHARD_MAP_KW = {}
except ImportError:  # pre-0.5 releases export it under experimental only;
    # that signature needs check_rep=False (no replication rule for the
    # lax.while_loop fixpoint in detect_core) — the kwarg was renamed and
    # later removed in the public API, so only pass it here.
    from jax.experimental.shard_map import shard_map

    _SHARD_MAP_KW = {"check_rep": False}
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..conflict import keys as keylib
from ..conflict.engine_jax import (
    EP_KW1,
    EP_RR,
    EP_TXN,
    EP_WR,
    FLOOR_REL,
    REBASE_THRESHOLD,
    PackedBatch,
    _grow_step,
    _next_pow2,
    _rebase_step,
    detect_core,
    register_entry_point,
)
from ..conflict.types import TransactionConflictInfo
from ..ops.rangequery import lex_less

AXIS = "resolvers"


def _lex_max(a: jnp.ndarray, bound: jnp.ndarray) -> jnp.ndarray:
    """Column-wise max(a, bound); a [W, N] word-major, bound [W]."""
    b = jnp.broadcast_to(bound[:, None], a.shape)
    return jnp.where(lex_less(a, b)[None, :], b, a)


def _lex_min(a: jnp.ndarray, bound: jnp.ndarray) -> jnp.ndarray:
    b = jnp.broadcast_to(bound[:, None], a.shape)
    return jnp.where(lex_less(b, a)[None, :], b, a)


def _shard_body(
    lo,
    hi,
    hkeys,
    hvers,
    hcount,
    oldest,
    r_begin,
    r_end,
    r_txn,
    r_snap,
    w_begin,
    w_end,
    w_txn,
    t_snap,
    t_valid,
    now_rel,
    new_oldest_rel,
    *,
    txn_cap: int,
    rr_cap: int,
    wr_cap: int,
    h_cap: int,
    kernels: bool = False,
    kernel_interpret: bool = False,
):
    """Per-device block: clip the replicated batch to this shard's bounds and
    run the single-device engine on the local history slice.

    State blocks carry a leading shard axis of length 1 (shard_map slices).
    """
    lo0, hi0 = lo[0], hi[0]
    TXN = txn_cap
    rb = _lex_max(r_begin, lo0)
    re_ = _lex_min(r_end, hi0)
    wb = _lex_max(w_begin, lo0)
    we = _lex_min(w_end, hi0)
    # TooOld applies only where this shard actually sees read ranges (ref:
    # ResolutionRequestBuilder forwards only overlapping ranges, so a
    # resolver with none never reports TooOld for that txn).
    r_ne = lex_less(rb, re_) & (r_txn < TXN)
    t_has_reads = (
        jnp.zeros((TXN + 1,), bool)
        .at[jnp.where(r_ne, r_txn, TXN)]
        .max(r_ne)[:TXN]
    )
    out = detect_core(
        hkeys[0],
        hvers[0],
        hcount[0],
        oldest[0],
        rb,
        re_,
        r_txn,
        r_snap,
        wb,
        we,
        w_txn,
        t_snap,
        t_has_reads,
        t_valid,
        now_rel,
        new_oldest_rel,
        txn_cap=txn_cap,
        rr_cap=rr_cap,
        wr_cap=wr_cap,
        h_cap=h_cap,
        kernels=kernels,
        kernel_interpret=kernel_interpret,
    )
    (out_keys, out_vers, out_count, new_oldest, status, undecided, iters) = out
    # Convergence is all-or-nothing across the mesh: if ANY shard's fixpoint
    # diverged, every shard keeps its pristine state (detect_core already
    # reverts the local shard; this psum extends the revert globally) so the
    # host can re-run the whole batch on the CPU engine consistently.
    total_undec = jax.lax.psum(undecided, AXIS)
    ok = total_undec == 0
    out_keys = jnp.where(ok, out_keys, hkeys[0])
    out_vers = jnp.where(ok, out_vers, hvers[0])
    out_count = jnp.where(ok, out_count, hcount[0])
    new_oldest = jnp.where(ok, new_oldest, oldest[0])
    return (
        out_keys[None],
        out_vers[None],
        out_count[None],
        new_oldest[None],
        status[None],
        undecided[None],
        iters[None],
    )


def _make_sharded_step(mesh: Mesh, txn_cap, rr_cap, wr_cap, h_cap,
                       kernels: bool = False,
                       kernel_interpret: bool = False):
    body = partial(
        _shard_body, txn_cap=txn_cap, rr_cap=rr_cap, wr_cap=wr_cap,
        h_cap=h_cap, kernels=kernels, kernel_interpret=kernel_interpret,
    )
    shard = P(AXIS)
    repl = P()
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            shard,  # lo
            shard,  # hi
            shard,  # hkeys
            shard,  # hvers
            shard,  # hcount
            shard,  # oldest
            repl,  # r_begin
            repl,  # r_end
            repl,  # r_txn
            repl,  # r_snap
            repl,  # w_begin
            repl,  # w_end
            repl,  # w_txn
            repl,  # t_snap
            repl,  # t_valid
            repl,  # now_rel
            repl,  # new_oldest_rel
        ),
        out_specs=(shard, shard, shard, shard, shard, shard, shard),
        **_SHARD_MAP_KW,
    )

    def step(*args):
        (hkeys, hvers, hcount, oldest, status_s, undec_s, iters_s) = mapped(*args)
        # Proxy-side verdict combine (ref MasterProxyServer.actor.cpp:492-499:
        # min over resolvers — Conflict(0) < TooOld(1) < Committed(2)).
        status = jnp.min(status_s, axis=0)
        undecided = jnp.sum(undec_s)
        iters = jnp.max(iters_s)
        return hkeys, hvers, hcount, oldest, status, undecided, iters

    return jax.jit(step, donate_argnums=(2, 3, 4, 5))


def uniform_int_split_keys(
    n_shards: int, max_key: int, byte_len: int = 8
) -> List[bytes]:
    """n_shards-1 split points dividing big-endian byte_len-int keys evenly."""
    return [
        (max_key * s // n_shards).to_bytes(byte_len, "big")
        for s in range(1, n_shards)
    ]


class ShardedJaxConflictSet:
    """Conflict set whose history is range-sharded across a device mesh.

    Drop-in for `JaxConflictSet` (same detect()/detect_packed()/clear() ABI),
    so the resolver role can swap it in when a mesh is available.
    """

    # Pin-release hysteresis (the hybrid's discipline, api.py): after a
    # long-key pin, this many consecutive short batches must pass before
    # the device reloads — alternating workloads must not pay a full
    # history transfer per flip.
    AUTHORITY_HYSTERESIS = 8

    def __init__(
        self,
        split_keys: Sequence[bytes],
        key_words: int = 4,
        h_cap: int = 1 << 16,
        oldest_version: int = 0,
        mesh: Optional[Mesh] = None,
        devices: Optional[Sequence] = None,
        bucket_mins: tuple = (8, 8, 8),
    ):
        self.n_shards = len(split_keys) + 1
        if mesh is None:
            devs = list(devices) if devices is not None else jax.devices()
            assert len(devs) >= self.n_shards, (
                f"{self.n_shards} shards need >= that many devices, "
                f"got {len(devs)}"
            )
            mesh = Mesh(np.array(devs[: self.n_shards]), (AXIS,))
        assert mesh.devices.size == self.n_shards, (
            f"mesh has {mesh.devices.size} devices but split_keys implies "
            f"{self.n_shards} shards"
        )
        self.mesh = mesh
        self.key_words = key_words
        self.h_cap = h_cap
        self._base = oldest_version
        kw1 = key_words + 1
        lo = np.zeros((self.n_shards, kw1), np.uint32)
        hi = np.full((self.n_shards, kw1), keylib.INF_WORD, np.uint32)
        if split_keys:
            enc = keylib.encode_keys(list(split_keys), key_words)
            lo[1:] = enc
            hi[:-1] = enc
        self.bucket_mins = bucket_mins
        # Decoded shard bounds, for host-side state exchange (CPU fallback,
        # resharding): split_keys[s-1] is shard s's inclusive lower bound.
        self.split_keys = [bytes(k) for k in split_keys]
        self._shardspec = NamedSharding(mesh, P(AXIS))
        self._lo = jax.device_put(jnp.asarray(lo), self._shardspec)
        self._hi = jax.device_put(jnp.asarray(hi), self._shardspec)
        self._steps: dict = {}
        # Pallas kernel routing inside the shard_map body (ISSUE 14),
        # resolved once per set exactly like JaxConflictSet (invalid
        # flag values raise): per-shard detect_core runs its fused
        # merge/search kernels on each device's history slice; the
        # differential gate covers the sharded mode on CPU interpret
        # (tests/test_kernels.py).
        from ..conflict.kernels import resolve_kernel_flag

        self._use_kernels, self._kernel_interpret = resolve_kernel_flag(
            jax.default_backend()
        )
        self._init_state(oldest_rel=0)
        self.last_iters = 0
        self._cpu_engines = None
        self._short_streak = 0

    # -- state management (mirrors JaxConflictSet, with a leading shard axis) --
    def _init_state(self, oldest_rel: int):
        S, kw1 = self.n_shards, self.key_words + 1
        # Word-major per shard: (S, kw1, H) — see ops/rangequery.py on TPU
        # minor-dim tiling.
        hkeys = np.full((S, kw1, self.h_cap), keylib.INF_WORD, np.uint32)
        hkeys[:, :, 0] = 0  # b"" floor boundary per shard
        hvers = np.full((S, self.h_cap), FLOOR_REL, np.int32)
        put = partial(jax.device_put, device=self._shardspec)
        self._hkeys = put(jnp.asarray(hkeys))
        self._hvers = put(jnp.asarray(hvers))
        self._hcount = put(jnp.ones((S,), jnp.int32))
        self._oldest = put(jnp.full((S,), oldest_rel, jnp.int32))

    @property
    def oldest_version(self) -> int:
        if self._cpu_engines is not None:
            # The pinned engines advance their windows per batch; the
            # device arrays are stale for the pin's duration.
            return max(e.oldest_version for e in self._cpu_engines)
        return int(np.max(np.asarray(self._oldest))) + self._base

    @property
    def boundary_count(self) -> int:
        if self._cpu_engines is not None:
            return sum(len(e.keys) for e in self._cpu_engines)
        return int(np.sum(np.asarray(self._hcount)))

    def clear(self, version: int):
        self._base = version
        self._cpu_engines = None
        self._short_streak = 0
        self._init_state(oldest_rel=0)

    def _maybe_grow_or_rebase(self, now: int, wr_cap: int):
        if now - self._base > REBASE_THRESHOLD:
            d = int(np.min(np.asarray(self._oldest)))
            if d > 0:
                # Donating rebase body shared with the single-device
                # engine (jaxcheck-registered: rebase_body).
                self._hvers = _rebase_step(self._hvers, d)
                self._oldest = self._oldest - d
                self._base += d
        if int(np.max(np.asarray(self._hcount))) + 2 * wr_cap + 2 > self.h_cap:
            self._grow(max(self.h_cap * 2, self.h_cap + 4 * wr_cap))

    def _grow(self, new_cap: int):
        pad = new_cap - self.h_cap
        put = partial(jax.device_put, device=self._shardspec)
        # Shared grow body (jaxcheck-registered: grow_body); the minor
        # axis is the per-shard history for both state blocks.
        self._hkeys = put(
            _grow_step(self._hkeys, pad=pad, fill=int(keylib.INF_WORD))
        )
        self._hvers = put(_grow_step(self._hvers, pad=pad, fill=FLOOR_REL))
        self.h_cap = new_cap
        self._steps.clear()

    def _step_for(self, pb: PackedBatch):
        key = (pb.txn_cap, pb.rr_cap, pb.wr_cap, self.h_cap)
        step = self._steps.get(key)
        if step is None:
            step = _make_sharded_step(
                self.mesh, *key, kernels=self._use_kernels,
                kernel_interpret=self._kernel_interpret,
            )
            self._steps[key] = step
        return step

    # -- ConflictSet ABI --
    def new_batch(self):
        """Drop-in for the Resolver's ConflictSet surface (api.py): the
        mesh-sharded set plugs into a live cluster's resolver via
        `Resolver(conflict_set=...)` (ref: the ConflictSet swap point,
        Resolver.actor.cpp:140-153)."""
        from ..conflict.api import ConflictBatch

        return ConflictBatch(self)

    def _detect(self, txns, now, new_oldest_version) -> List[int]:
        return self.detect(txns, now, new_oldest_version)

    def detect(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> List[int]:
        # Long-key discipline (the hybrid single-chip set's, sharded):
        # keys beyond the device key width (min of the digitization width
        # and the conflict_max_device_key_bytes knob, like api.py's
        # hybrid) cannot ride the device — such batches run on per-shard
        # CPU engines with the exact multi-resolver semantics against the
        # SAME logical state, so cluster use with arbitrary byte keys
        # (system keyspace, markers) is safe.  A long-key WRITE enters
        # shard HISTORY, which the device arrays cannot represent:
        # authority pins to the CPU engines until every shard's history
        # fits again (window eviction ages the long keys out) AND a
        # hysteresis streak of short batches passes (the hybrid's
        # AUTHORITY_HYSTERESIS: alternating workloads must not pay a full
        # history transfer per flip), then the device reloads.
        from ..flow.knobs import g_knobs

        width = min(
            g_knobs.server.conflict_max_device_key_bytes,
            self.key_words * 4,
        )
        batch_long = any(
            len(b) > width
            for t in transactions
            for rng in (t.read_ranges, t.write_ranges)
            for pair in rng
            for b in pair
        )
        if batch_long or self._cpu_engines is not None:
            if batch_long:
                from ..flow.testprobe import test_probe

                test_probe("sharded_long_key_fallback")
                self._short_streak = 0
            else:
                self._short_streak += 1
            return self._fallback_txns(
                transactions, now, new_oldest_version
            )
        mt, mr, mw = self.bucket_mins
        pb = PackedBatch.from_transactions(
            transactions, self.key_words, min_txn=mt, min_rr=mr, min_wr=mw
        )
        statuses = self.detect_packed(pb, now, new_oldest_version)
        return [int(s) for s in statuses[: len(transactions)]]

    def detect_packed(self, pb: PackedBatch, now: int, new_oldest_version: int):
        if self._cpu_engines is not None:
            # CPU engines hold the authoritative history (long-key pin):
            # resolving on the stale device arrays would miss every write
            # committed since the pin.
            self._short_streak += 1
            return self._fallback_packed(pb, now, new_oldest_version)
        self._maybe_grow_or_rebase(now, pb.wr_cap)
        clip = lambda v: np.clip(v - self._base, FLOOR_REL + 1, 2**31 - 2)
        step = self._step_for(pb)
        (
            self._hkeys,
            self._hvers,
            self._hcount,
            self._oldest,
            statuses,
            undecided,
            iters,
        ) = step(
            self._lo,
            self._hi,
            self._hkeys,
            self._hvers,
            self._hcount,
            self._oldest,
            jnp.asarray(np.ascontiguousarray(pb.r_begin.T)),
            jnp.asarray(np.ascontiguousarray(pb.r_end.T)),
            jnp.asarray(pb.r_txn),
            jnp.asarray(clip(pb.r_snap).astype(np.int32)),
            jnp.asarray(np.ascontiguousarray(pb.w_begin.T)),
            jnp.asarray(np.ascontiguousarray(pb.w_end.T)),
            jnp.asarray(pb.w_txn),
            jnp.asarray(clip(pb.t_snap).astype(np.int32)),
            jnp.asarray(pb.t_valid),
            jnp.asarray(clip(now), dtype=jnp.int32),
            jnp.asarray(clip(new_oldest_version), dtype=jnp.int32),
        )
        self.last_iters = int(iters)
        if int(undecided) != 0:
            # All shards kept pristine state (the psum gate in _shard_body);
            # re-run the batch on the CPU engine and push the result back.
            return self._fallback_cpu(pb, now, new_oldest_version)
        return np.asarray(statuses)

    def _fallback_cpu(self, pb: PackedBatch, now: int, new_oldest_version: int):
        """Diverged-batch path: unpack and re-run on the shard engines.
        A divergence with NO pin active is a one-off — the device must
        reload immediately after (no hysteresis hold): the streak is
        primed so a fitting history unpins at once."""
        from ..flow.trace import TraceEvent

        TraceEvent("ConflictFixpointDiverged", severity=30).detail(
            "n_txn", pb.n_txn
        ).detail("sharded", True).log()
        if self._cpu_engines is None:
            self._short_streak = self.AUTHORITY_HYSTERESIS
        return self._fallback_packed(pb, now, new_oldest_version)

    def _fallback_packed(self, pb: PackedBatch, now: int, new_oldest_version: int):
        """PackedBatch adapter over _fallback_txns (shared by the pin and
        divergence paths)."""
        from ..conflict.engine_jax import _unpack_transactions
        from ..conflict.types import COMMITTED

        statuses = self._fallback_txns(
            _unpack_transactions(pb), now, new_oldest_version
        )
        out = np.full((pb.txn_cap,), COMMITTED, np.int32)
        out[: pb.n_txn] = statuses
        return out

    def _fallback_txns(self, txns, now: int, new_oldest_version: int):
        """Run a batch on per-shard CPU engines with the exact
        multi-resolver semantics of the device path: ranges clipped per
        shard, each shard commits writes on its LOCAL verdict, verdicts
        min-combined (ref Resolver.actor.cpp:140-153, proxy :492-499).
        The device state is flattened in and reloaded out, so device and
        CPU batches interleave against ONE logical history.  While any
        shard's history holds a long key the engines persist host-side
        (CPU authority) — the device reloads once everything fits."""
        engines = self._cpu_engines or self._store_shard_engines()
        bounds = self._shard_bounds()
        verdicts = []
        for (lo, hi), eng in zip(bounds, engines):
            local = []
            for tr in txns:
                rr, wr = [], []
                for (b, e) in tr.read_ranges:
                    cb = max(b, lo)
                    ce = e if hi is None else min(e, hi)
                    if cb < ce:
                        rr.append((cb, ce))
                for (b, e) in tr.write_ranges:
                    cb = max(b, lo)
                    ce = e if hi is None else min(e, hi)
                    if cb < ce:
                        wr.append((cb, ce))
                local.append(
                    TransactionConflictInfo(
                        read_snapshot=tr.read_snapshot,
                        read_ranges=rr,
                        write_ranges=wr,
                    )
                )
            verdicts.append(eng.detect(local, now, new_oldest_version))
        statuses = [min(v) for v in zip(*verdicts)] if txns else []
        if self._short_streak >= self.AUTHORITY_HYSTERESIS and all(
            keylib.fits(eng.keys, self.key_words) for eng in engines
        ):
            self._load_shard_engines(engines)
            self._cpu_engines = None
        else:
            self._cpu_engines = engines  # CPU stays authoritative
        return statuses

    def _shard_bounds(self):
        """[(lo, hi_or_None)] per shard — the one definition."""
        return list(zip([b""] + self.split_keys, self.split_keys + [None]))

    def _flatten_engines_to(self, engines: list, cpu) -> None:
        """Per-shard CPU engines -> one global step function (the
        engines-sourced twin of store_to's device flatten): shard 0
        contributes its full boundary list below hi_0; each later shard
        re-anchors at lo_s with its value there, then its boundaries
        strictly inside (lo_s, hi_s)."""
        bounds = self._shard_bounds()
        keys: list = []
        vers: list = []
        for (lo, hi), eng in zip(bounds, engines):
            from bisect import bisect_left, bisect_right

            if lo == b"":
                i0 = 0
            else:
                keys.append(lo)
                vers.append(eng._value_at(lo))
                i0 = bisect_right(eng.keys, lo)
            i1 = len(eng.keys) if hi is None else bisect_left(eng.keys, hi)
            keys.extend(eng.keys[i0:i1])
            vers.extend(eng.vers[i0:i1])
        cpu.keys = keys
        cpu.vers = vers
        cpu.oldest_version = min(e.oldest_version for e in engines)

    def _split_flat_to_engines(self, cpu) -> list:
        """One global step function -> per-shard CPU engines (the inverse
        of _flatten_engines_to; the long-key load_from path)."""
        from bisect import bisect_left, bisect_right

        from ..conflict.engine_cpu import CpuConflictSet

        bounds = self._shard_bounds()
        engines = []
        for lo, hi in bounds:
            eng = CpuConflictSet(cpu.oldest_version)
            i0 = bisect_right(cpu.keys, lo)
            i1 = len(cpu.keys) if hi is None else bisect_left(cpu.keys, hi)
            eng.keys = [b""] + cpu.keys[i0:i1]
            eng.vers = [cpu._value_at(lo)] + cpu.vers[i0:i1]
            engines.append(eng)
        return engines

    def _store_shard_engines(self) -> list:
        """Per-shard CpuConflictSet mirrors of the device state."""
        from ..conflict.engine_cpu import CpuConflictSet, FLOOR_VERSION

        hkeys = np.asarray(self._hkeys)
        hvers = np.asarray(self._hvers)
        counts = np.asarray(self._hcount)
        oldest = np.asarray(self._oldest)
        engines = []
        for s in range(self.n_shards):
            eng = CpuConflictSet(int(oldest[s]) + self._base)
            n = int(counts[s])
            rows = hkeys[s, :, :n].T
            eng.keys = [
                keylib.decode_key(rows[i], self.key_words) for i in range(n)
            ]
            eng.vers = [
                FLOOR_VERSION if int(v) == FLOOR_REL else int(v) + self._base
                for v in hvers[s, :n]
            ]
            engines.append(eng)
        return engines

    def _load_shard_engines(self, engines: list) -> None:
        from ..conflict.engine_cpu import FLOOR_VERSION

        S, kw1 = self.n_shards, self.key_words + 1
        need = max(len(e.keys) for e in engines) + 2
        if need + 8 > self.h_cap:
            self._grow(_next_pow2(need + 8, self.h_cap * 2))
        hkeys = np.full((S, kw1, self.h_cap), keylib.INF_WORD, np.uint32)
        hvers = np.full((S, self.h_cap), FLOOR_REL, np.int32)
        counts = np.zeros((S,), np.int32)
        oldest = np.zeros((S,), np.int32)
        for s, eng in enumerate(engines):
            n = len(eng.keys)
            hkeys[s, :, :n] = keylib.encode_keys(eng.keys, self.key_words).T
            hvers[s, :n] = [
                FLOOR_REL
                if v == FLOOR_VERSION
                else int(np.clip(v - self._base, FLOOR_REL + 1, 2**31 - 2))
                for v in eng.vers
            ]
            counts[s] = n
            oldest[s] = int(
                np.clip(eng.oldest_version - self._base, 0, 2**31 - 2)
            )
        put = partial(jax.device_put, device=self._shardspec)
        self._hkeys = put(jnp.asarray(hkeys))
        self._hvers = put(jnp.asarray(hvers))
        self._hcount = put(jnp.asarray(counts))
        self._oldest = put(jnp.asarray(oldest, dtype=jnp.int32))

    # -- host state exchange (CPU fallback + resharding) --
    def store_to(self, cpu) -> None:
        """Flatten the per-shard step functions into the CPU engine's global
        one.  Shard s owns [lo_s, hi_s); its boundary list is already sorted,
        so concatenating shards in order — re-anchoring each shard's value at
        lo_s and dropping boundaries outside its ownership — yields the
        global sorted boundary array."""
        if self._cpu_engines is not None:
            # The pinned CPU engines ARE the authoritative per-shard
            # state; exporting the stale device arrays would drop every
            # write since the pin.
            self._flatten_engines_to(self._cpu_engines, cpu)
            return
        from bisect import bisect_right

        from ..conflict.engine_cpu import FLOOR_VERSION

        hkeys = np.asarray(self._hkeys)
        hvers = np.asarray(self._hvers)
        counts = np.asarray(self._hcount)

        def absv(rel: int) -> int:
            return FLOOR_VERSION if rel == FLOOR_REL else int(rel) + self._base

        keys: list = []
        vers: list = []
        for s in range(self.n_shards):
            n = int(counts[s])
            rows = hkeys[s, :, :n].T
            sk = [keylib.decode_key(rows[i], self.key_words) for i in range(n)]
            sv = hvers[s, :n]
            lo_key = b"" if s == 0 else self.split_keys[s - 1]
            hi_key = None if s == self.n_shards - 1 else self.split_keys[s]
            at_lo = bisect_right(sk, lo_key) - 1
            keys.append(lo_key)
            vers.append(absv(sv[at_lo]))
            for i in range(at_lo + 1, n):
                if hi_key is not None and sk[i] >= hi_key:
                    break
                keys.append(sk[i])
                vers.append(absv(sv[i]))
        cpu.keys = keys
        cpu.vers = vers
        cpu.oldest_version = self.oldest_version

    def load_from(self, cpu) -> None:
        """Scatter the CPU engine's global step function back into per-shard
        slices (inverse of store_to)."""
        # The loaded state supersedes any long-key pin; if it itself
        # contains long keys the device cannot hold it — install it as
        # pinned per-shard engines instead of raising at encode.
        self._cpu_engines = None
        self._short_streak = 0
        if not keylib.fits(cpu.keys, self.key_words):
            self._cpu_engines = self._split_flat_to_engines(cpu)
            self._base = cpu.oldest_version
            return
        from bisect import bisect_left, bisect_right

        from ..conflict.engine_cpu import FLOOR_VERSION

        self._base = cpu.oldest_version
        S, kw1 = self.n_shards, self.key_words + 1
        need = 2
        bounds = [b""] + self.split_keys + [None]
        per_shard: list = []
        for s in range(S):
            lo_key, hi_key = bounds[s], bounds[s + 1]
            i0 = bisect_right(cpu.keys, lo_key)  # strictly-after lo
            i1 = len(cpu.keys) if hi_key is None else bisect_left(cpu.keys, hi_key)
            v_at_lo = cpu._value_at(lo_key)
            sk = [b""] + cpu.keys[i0:i1]
            sv = [v_at_lo] + cpu.vers[i0:i1]
            per_shard.append((sk, sv))
            need = max(need, len(sk) + 2)
        if need + 8 > self.h_cap:
            self._grow(_next_pow2(need + 8, self.h_cap * 2))
        hkeys = np.full((S, kw1, self.h_cap), keylib.INF_WORD, np.uint32)
        hvers = np.full((S, self.h_cap), FLOOR_REL, np.int32)
        counts = np.zeros((S,), np.int32)
        for s, (sk, sv) in enumerate(per_shard):
            n = len(sk)
            hkeys[s, :, :n] = keylib.encode_keys(sk, self.key_words).T
            rel = np.array(
                [
                    FLOOR_REL
                    if v == FLOOR_VERSION
                    else int(np.clip(v - self._base, FLOOR_REL + 1, 2**31 - 2))
                    for v in sv
                ],
                np.int32,
            )
            hvers[s, :n] = rel
            counts[s] = n
        put = partial(jax.device_put, device=self._shardspec)
        self._hkeys = put(jnp.asarray(hkeys))
        self._hvers = put(jnp.asarray(hvers))
        self._hcount = put(jnp.asarray(counts))
        self._oldest = put(jnp.zeros((S,), jnp.int32))


# ---------------------------------------------------------------------------
# jaxcheck entry-point registration (tools/lint/jaxir.py): the shard_map
# step is traced at a canonical 2-shard mesh on virtual CPU devices, so the
# per-shard structural invariants — no work primitive wider than ONE
# shard's history slice (a global-width op inside shard_map would show up
# as S*h_cap-sized), carried state donated, pinned shard bounds NOT
# donated — hold statically before any multi-chip run (ROADMAP item 2's
# static down-payment).
# ---------------------------------------------------------------------------

EP_SHARDS, EP_SHARD_H = 2, 2048


def _ep_sharded_step():
    devs = jax.devices()
    if len(devs) < EP_SHARDS:
        raise RuntimeError(
            f"sharded_step entry needs >= {EP_SHARDS} devices to trace; "
            "set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "(tests/conftest.py and the jaxir CLI both do)"
        )
    mesh = Mesh(np.array(devs[:EP_SHARDS]), (AXIS,))
    jitted = _make_sharded_step(mesh, EP_TXN, EP_RR, EP_WR, EP_SHARD_H)
    sds = jax.ShapeDtypeStruct
    S, kw1 = EP_SHARDS, EP_KW1
    u32, i32 = jnp.uint32, jnp.int32
    args = (
        sds((S, kw1), u32),                 # lo
        sds((S, kw1), u32),                 # hi
        sds((S, kw1, EP_SHARD_H), u32),     # hkeys
        sds((S, EP_SHARD_H), i32),          # hvers
        sds((S,), i32),                     # hcount
        sds((S,), i32),                     # oldest
        sds((kw1, EP_RR), u32),             # r_begin
        sds((kw1, EP_RR), u32),             # r_end
        sds((EP_RR,), i32),                 # r_txn
        sds((EP_RR,), i32),                 # r_snap
        sds((kw1, EP_WR), u32),             # w_begin
        sds((kw1, EP_WR), u32),             # w_end
        sds((EP_WR,), i32),                 # w_txn
        sds((EP_TXN,), i32),                # t_snap
        sds((EP_TXN,), jnp.bool_),          # t_valid
        sds((), i32),                       # now_rel
        sds((), i32),                       # new_oldest_rel
    )
    return jitted.__wrapped__, jitted, args, {}


register_entry_point(
    "sharded_step", _ep_sharded_step,
    arg_names=("lo", "hi", "hkeys", "hvers", "hcount", "oldest",
               "r_begin", "r_end", "r_txn", "r_snap",
               "w_begin", "w_end", "w_txn",
               "t_snap", "t_valid", "now_rel", "new_oldest_rel"),
    carried=("hkeys", "hvers", "hcount", "oldest"),
    pinned=("lo", "hi"),
    size_classes=(("H", EP_SHARD_H), ("P", 2 * (EP_RR + EP_WR)),
                  ("batch", EP_TXN)),
    h_threshold=EP_SHARD_H,
    # Per-shard width bound: the flat engine's legitimate full-width merge
    # at ONE shard's h_cap.  Anything wider means a primitive is touching
    # globally-sized (S*h_cap) data inside the shard_map body.
    work_bound=EP_SHARD_H + 4 * EP_WR,
    bucket_dims={
        "txn_cap": (EP_TXN, 8), "rr_cap": (EP_RR, 8), "wr_cap": (EP_WR, 8),
        "h_cap": (EP_SHARD_H, 64),
    },
)
