"""Range-sharded conflict resolution over a TPU device mesh, with
SHARD-GRANULAR fault domains (ISSUE 15).

The reference scales conflict resolution by partitioning the key space
across resolver *processes* (keyResolvers KeyRangeMap,
MasterProxyServer.actor.cpp:185), splitting each transaction's conflict
ranges per resolver (ResolutionRequestBuilder.addTransaction
MasterProxyServer.actor.cpp:280-303) and combining the per-resolver verdicts
with min() (:492-499).  TooOld is only reported by resolvers that actually
received read ranges for the transaction (addTransaction only forwards the
ranges that overlap the resolver's key space).  Crucially, that process
split is also the reference's FAULT boundary: one sick resolver degrades
one key range, not the commit pipeline.

The TPU-native translation keeps the same *semantics* but replaces processes
and TCP with a device mesh and XLA:

  - one mesh axis ("resolvers"); device d owns key range [lo_d, hi_d)
  - the history step function lives sharded on its owner device
    (leading shard axis, NamedSharding over the mesh axis)
  - the packed batch is replicated; each device clips every range to its
    own bounds (the tensor form of ResolutionRequestBuilder's split)
  - per-device `conflict.engine_jax.detect_core` (or, under
    FDB_TPU_HISTORY=tiered, `detect_core_tiered` with per-shard delta
    tiers and a shared compaction cadence) runs under shard_map
  - each shard returns its LOCAL verdicts; the proxy-side min-combine
    runs host-side so a degraded shard's row can be substituted exactly

and makes the unit of failure ONE shard:

  - every shard has its own always-authoritative chunked CpuConflictSet
    MIRROR, key-range-partitioned along the same split points the
    resolver-balancer uses (`split_keys`), updated per batch with that
    shard's LOCAL verdicts (ref: each resolver's ConflictBatch commits on
    its local view, Resolver.actor.cpp:140-153);
  - every shard has its own DeviceCircuitBreaker (counters namespaced
    `shard<k>_*` in one registry, all pre-created so snapshots are
    byte-stable regardless of which shards fault);
  - a fault on chip k (DeviceFaultInjector checks each choke point —
    dispatch/compile/grow/rebase — per shard, BEFORE any state mutation)
    re-runs only shard k's slice of the batch on shard k's mirror with
    bit-identical verdicts, opens only shard k's breaker, and the other
    shards keep serving on device (their slices ride the same shard_map
    program; the sick shard's slice is masked inactive and its state
    reverts to pristine in-core);
  - shard k's half-open probe rehydrates only shard k, from an immutable
    MirrorSnapshot with per-chunk encode caches — host work proportional
    to chunks changed since shard k's last device sync (the ISSUE-9
    handoff, shard-granular).

Semantics parity note: like the reference's multi-resolver mode, a
transaction judged conflicting in shard A still gets its writes (in shard B)
inserted into B's history if B judged it committed.  The single-shard
configuration is exactly `JaxConflictSet` semantics.
"""

from __future__ import annotations

from collections import deque
from functools import partial
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
try:
    from jax import shard_map

    _SHARD_MAP_KW = {}
except ImportError:  # pre-0.5 releases export it under experimental only;
    # that signature needs check_rep=False (no replication rule for the
    # lax.while_loop fixpoint in detect_core) — the kwarg was renamed and
    # later removed in the public API, so only pass it here.
    from jax.experimental.shard_map import shard_map

    _SHARD_MAP_KW = {"check_rep": False}
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..conflict import keys as keylib
from ..flow.hotpath import hot_path
from ..conflict.device_faults import DeviceCircuitBreaker, DeviceFault
from ..conflict.engine_cpu import (
    CpuConflictSet,
    FLOOR_VERSION,
    engine_from_handoff,
)
from ..conflict.engine_jax import (
    EP_KW1,
    EP_RR,
    EP_TXN,
    EP_WR,
    FLOOR_REL,
    REBASE_THRESHOLD,
    WITNESS_NONE_RANGE,
    PackedBatch,
    _build_max_table_np,
    _grow_step,
    _next_pow2,
    _rebase_step,
    _unpack_transactions,
    chunk_encoding,
    decode_witness,
    detect_core,
    detect_core_tiered,
    fold_delta_over_base,
    register_entry_point,
)
from ..conflict.types import COMMITTED, CONFLICT, TransactionConflictInfo
from ..ops.rangequery import lex_less

AXIS = "resolvers"


def _lex_max(a: jnp.ndarray, bound: jnp.ndarray) -> jnp.ndarray:
    """Column-wise max(a, bound); a [W, N] word-major, bound [W]."""
    b = jnp.broadcast_to(bound[:, None], a.shape)
    return jnp.where(lex_less(a, b)[None, :], b, a)


def _lex_min(a: jnp.ndarray, bound: jnp.ndarray) -> jnp.ndarray:
    b = jnp.broadcast_to(bound[:, None], a.shape)
    return jnp.where(lex_less(b, a)[None, :], b, a)


def _clip_batch(lo0, hi0, r_begin, r_end, r_txn, w_begin, w_end, txn_cap):
    """Per-device range clip + the TooOld read-presence mask (ref:
    ResolutionRequestBuilder forwards only overlapping ranges, so a
    resolver with none never reports TooOld for that txn)."""
    rb = _lex_max(r_begin, lo0)
    re_ = _lex_min(r_end, hi0)
    wb = _lex_max(w_begin, lo0)
    we = _lex_min(w_end, hi0)
    r_ne = lex_less(rb, re_) & (r_txn < txn_cap)
    t_has_reads = (
        jnp.zeros((txn_cap + 1,), bool)
        .at[jnp.where(r_ne, r_txn, txn_cap)]
        .max(r_ne)[:txn_cap]
    )
    return rb, re_, wb, we, t_has_reads


def _active_combine(act):
    """Cross-shard convergence combiner: total undecided over ACTIVE
    shards only — a masked (degraded) shard's slice is stale garbage and
    must neither trigger nor veto the global divergence revert."""
    return lambda u: jax.lax.psum(
        jnp.where(act, u, jnp.zeros_like(u)), AXIS
    )


def _witness_combine(act):
    """Cross-shard witness combiner (ISSUE 17), the in-core twin of the
    proxy's multi-resolver rule: losing range = MIN packed read index
    over conflicting ACTIVE shards (packed indices are global, so min in
    packed space == min in per-txn-ordinal space), version = MAX over
    the shards reporting that minimal range (a range spanning shards may
    carry a different local range-max on each).  A masked shard's vector
    is stale garbage and contributes nothing."""
    BIG = jnp.int32(WITNESS_NONE_RANGE)

    def comb(w_ver, w_rng):
        rng = jnp.where(act, w_rng, BIG)
        rng_g = jax.lax.pmin(rng, AXIS)
        ver = jnp.where(
            act & (w_rng == rng_g), w_ver, jnp.int32(FLOOR_REL)
        )
        return jax.lax.pmax(ver, AXIS), rng_g

    return comb


def _translate_witness(wit, rmap):
    """Per-shard mirror witness ordinals (indices into the CLIPPED read
    list — _clip_txns_for drops empty clips) back to ordinals into the
    transaction's original read_ranges."""
    return [
        None if w is None else (w[0], rmap[t][w[1]])
        for t, w in enumerate(wit)
    ]


def _combine_witness(parts, statuses):
    """The witness combine rule, host-side (mirror-served and mixed
    device/mirror batches): min losing ordinal across conflicting
    shards' contributions, version = max among the holders of that
    ordinal — bit-identical to _witness_combine's in-core pmin/pmax."""
    out: list = []
    for t, st in enumerate(statuses):
        cands = [p[t] for p in parts if p[t] is not None]
        if int(st) != CONFLICT or not cands:
            out.append(None)
            continue
        rng = min(c[1] for c in cands)
        out.append((max(c[0] for c in cands if c[1] == rng), rng))
    return out


def _shard_body(
    lo,
    hi,
    active,
    hkeys,
    hvers,
    hcount,
    oldest,
    r_begin,
    r_end,
    r_txn,
    r_snap,
    w_begin,
    w_end,
    w_txn,
    t_snap,
    t_valid,
    now_rel,
    new_oldest_rel,
    *,
    txn_cap: int,
    rr_cap: int,
    wr_cap: int,
    h_cap: int,
    kernels: bool = False,
    kernel_interpret: bool = False,
    witness: bool = False,
):
    """Per-device block (flat history): clip the replicated batch to this
    shard's bounds and run the single-device engine on the local history
    slice.  State blocks carry a leading shard axis of length 1
    (shard_map slices).  `active` masks a degraded shard: its slice
    reverts to pristine (the mirror serves its key range host-side) and
    its fixpoint result is excluded from the global convergence psum."""
    lo0, hi0, act = lo[0], hi[0], active[0]
    rb, re_, wb, we, t_has_reads = _clip_batch(
        lo0, hi0, r_begin, r_end, r_txn, w_begin, w_end, txn_cap
    )
    out = detect_core(
        hkeys[0],
        hvers[0],
        hcount[0],
        oldest[0],
        rb,
        re_,
        r_txn,
        r_snap,
        wb,
        we,
        w_txn,
        t_snap,
        t_has_reads,
        t_valid,
        now_rel,
        new_oldest_rel,
        txn_cap=txn_cap,
        rr_cap=rr_cap,
        wr_cap=wr_cap,
        h_cap=h_cap,
        kernels=kernels,
        kernel_interpret=kernel_interpret,
        undecided_combine=_active_combine(act),
        witness=witness,
        witness_combine=_witness_combine(act) if witness else None,
    )
    (out_keys, out_vers, out_count, new_oldest, status, undecided, iters) = out[:7]
    keep = lambda new, old: jnp.where(act, new, old)
    res = (
        keep(out_keys, hkeys[0])[None],
        keep(out_vers, hvers[0])[None],
        keep(out_count, hcount[0])[None],
        keep(new_oldest, oldest[0])[None],
        status[None],
        undecided[None],
        iters[None],
    )
    if witness:
        # Already cross-shard combined in-core: every shard's row is the
        # same replicated (version, range) vector.
        res += (out[7][None], out[8][None])
    return res


def _shard_body_tiered(
    lo,
    hi,
    active,
    hkeys,
    hvers,
    hcount,
    maxtab,
    dkeys,
    dvers,
    dcount,
    oldest,
    r_begin,
    r_end,
    r_txn,
    r_snap,
    w_begin,
    w_end,
    w_txn,
    t_snap,
    t_valid,
    now_rel,
    new_oldest_rel,
    do_major,
    *,
    txn_cap: int,
    rr_cap: int,
    wr_cap: int,
    h_cap: int,
    d_cap: int,
    kernels: bool = False,
    kernel_interpret: bool = False,
    witness: bool = False,
):
    """Tiered twin of _shard_body (ROADMAP item 3's mesh-sharded tiered
    history): every shard carries its own frozen base + max-table + delta
    tier; `do_major` is the HOST's shared compaction cadence (replicated
    scalar — all active shards compact on the same batch, so the host's
    deterministic delta bounds stay true for every shard)."""
    lo0, hi0, act = lo[0], hi[0], active[0]
    rb, re_, wb, we, t_has_reads = _clip_batch(
        lo0, hi0, r_begin, r_end, r_txn, w_begin, w_end, txn_cap
    )
    out = detect_core_tiered(
        hkeys[0],
        hvers[0],
        hcount[0],
        maxtab[0],
        dkeys[0],
        dvers[0],
        dcount[0],
        oldest[0],
        rb,
        re_,
        r_txn,
        r_snap,
        wb,
        we,
        w_txn,
        t_snap,
        t_has_reads,
        t_valid,
        now_rel,
        new_oldest_rel,
        do_major,
        txn_cap=txn_cap,
        rr_cap=rr_cap,
        wr_cap=wr_cap,
        h_cap=h_cap,
        d_cap=d_cap,
        kernels=kernels,
        kernel_interpret=kernel_interpret,
        undecided_combine=_active_combine(act),
        witness=witness,
        witness_combine=_witness_combine(act) if witness else None,
    )
    (ohk, ohv, ohc, omt, odk, odv, odc, new_oldest, status, undec, iters) = out[:11]
    keep = lambda new, old: jnp.where(act, new, old)
    res = (
        keep(ohk, hkeys[0])[None],
        keep(ohv, hvers[0])[None],
        keep(ohc, hcount[0])[None],
        keep(omt, maxtab[0])[None],
        keep(odk, dkeys[0])[None],
        keep(odv, dvers[0])[None],
        keep(odc, dcount[0])[None],
        keep(new_oldest, oldest[0])[None],
        status[None],
        undec[None],
        iters[None],
    )
    if witness:
        res += (out[11][None], out[12][None])
    return res


def _make_sharded_step(mesh: Mesh, txn_cap, rr_cap, wr_cap, h_cap,
                       tiered: bool = False, d_cap: int = 0,
                       kernels: bool = False,
                       kernel_interpret: bool = False,
                       witness: bool = False):
    """One jitted shard_map step.  Outputs are PER-SHARD (statuses
    included): the host substitutes a degraded shard's verdict row from
    its mirror and min-combines (ref MasterProxyServer.actor.cpp:492-499
    — Conflict(0) < TooOld(1) < Committed(2)).  With `witness` the step
    appends the cross-shard-combined (version, range) witness vectors
    (replicated rows; the donation indices are untouched)."""
    shard = P(AXIS)
    repl = P()
    batch_specs = (repl,) * 11
    wit_extra = (shard,) * 2 if witness else ()
    if tiered:
        body = partial(
            _shard_body_tiered, txn_cap=txn_cap, rr_cap=rr_cap,
            wr_cap=wr_cap, h_cap=h_cap, d_cap=d_cap, kernels=kernels,
            kernel_interpret=kernel_interpret, witness=witness,
        )
        in_specs = (shard, shard, shard) + (shard,) * 8 + batch_specs + (repl,)
        out_specs = (shard,) * 11 + wit_extra
        donate = tuple(range(3, 11))
    else:
        body = partial(
            _shard_body, txn_cap=txn_cap, rr_cap=rr_cap, wr_cap=wr_cap,
            h_cap=h_cap, kernels=kernels, kernel_interpret=kernel_interpret,
            witness=witness,
        )
        in_specs = (shard, shard, shard) + (shard,) * 4 + batch_specs
        out_specs = (shard,) * 7 + wit_extra
        donate = (3, 4, 5, 6)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **_SHARD_MAP_KW,
    )
    return jax.jit(mapped, donate_argnums=donate)


def uniform_int_split_keys(
    n_shards: int, max_key: int, byte_len: int = 8
) -> List[bytes]:
    """n_shards-1 split points dividing big-endian byte_len-int keys evenly."""
    return [
        (max_key * s // n_shards).to_bytes(byte_len, "big")
        for s in range(1, n_shards)
    ]


# Per-shard breaker instruments, ALL pre-created at construction (the
# PR-4 flat-snapshot discipline, ISSUE 15 satellite): which shards fault
# during a run must never change the snapshot's key set — and none of
# these exist at all on the single-device engines, so flat snapshots are
# untouched when sharding is off.
_BREAKER_COUNTERS = (
    "device_faults", "faults_dispatch", "faults_compile", "faults_grow",
    "faults_rebase", "faults_mirror", "faults_reshard", "breaker_opens",
    "breaker_probes", "breaker_closes", "degraded_batches", "rehydrates",
)


class ShardedJaxConflictSet:
    """Conflict set whose history is range-sharded across a device mesh,
    served as a first-class production path: per-shard breakers, per-shard
    always-authoritative mirrors, per-shard degraded serving and probe
    rehydration (ISSUE 15).

    Drop-in for `JaxConflictSet` (same detect()/detect_packed()/clear()
    ABI plus the ConflictSet-style robustness surface: backend_signal,
    device_metrics, mirror_check, consume_degraded,
    install_fault_injector), so the resolver role can swap it in when a
    mesh is available.
    """

    # Pin-release hysteresis (the hybrid's discipline, api.py): after a
    # long-key pin, this many consecutive short batches must pass before
    # the device reloads — alternating workloads must not pay a full
    # history transfer per flip.
    AUTHORITY_HYSTERESIS = 8

    def __init__(
        self,
        split_keys: Sequence[bytes],
        key_words: int = 4,
        h_cap: int = 1 << 16,
        oldest_version: int = 0,
        mesh: Optional[Mesh] = None,
        devices: Optional[Sequence] = None,
        bucket_mins: tuple = (8, 8, 8),
        fault_injector=None,
        max_shards: Optional[int] = None,
    ):
        self.n_shards = len(split_keys) + 1
        if mesh is None:
            devs = list(devices) if devices is not None else jax.devices()
            assert len(devs) >= self.n_shards, (
                f"{self.n_shards} shards need >= that many devices, "
                f"got {len(devs)}"
            )
            mesh = Mesh(np.array(devs[: self.n_shards]), (AXIS,))
        else:
            devs = list(mesh.devices.flat)
        assert mesh.devices.size == self.n_shards, (
            f"mesh has {mesh.devices.size} devices but split_keys implies "
            f"{self.n_shards} shards"
        )
        self.mesh = mesh
        # Elastic resharding (ISSUE 18): reshard()/the split balancer may
        # later scale the shard count up to `max_shards` (bounded by the
        # devices handed in).  Every per-shard instrument is pre-created
        # up to that bound, so a mid-run scale-up never mints new metric
        # names (the PR-4 flat-snapshot discipline, extended to scaling).
        self._devices = devs
        self.max_shards = min(
            len(devs), max(self.n_shards, int(max_shards or self.n_shards))
        )
        self.key_words = key_words
        self.h_cap = h_cap
        self._base = oldest_version
        lo, hi = self._partition_arrays(list(split_keys))
        self.bucket_mins = bucket_mins
        # Decoded shard bounds, for host-side state exchange (mirrors,
        # resharding): split_keys[s-1] is shard s's inclusive lower bound.
        # These ARE the resolver-balancer's split points — the mirror
        # partition and the device partition can never drift.
        self.split_keys = [bytes(k) for k in split_keys]
        self._shardspec = NamedSharding(mesh, P(AXIS))
        self._lo = jax.device_put(jnp.asarray(lo), self._shardspec)
        self._hi = jax.device_put(jnp.asarray(hi), self._shardspec)
        self._steps: dict = {}
        # Engine-variant flags, resolved once per set exactly like
        # JaxConflictSet (invalid values raise): Pallas kernel routing
        # inside the shard_map body (ISSUE 14) and the two-tier history
        # (ISSUE 4, now mesh-sharded: per-shard delta tiers, one shared
        # compaction cadence).
        from ..conflict.kernels import resolve_kernel_flag
        from ..flow.knobs import g_env

        self._use_kernels, self._kernel_interpret = resolve_kernel_flag(
            jax.default_backend()
        )
        self.tiered = g_env.get("FDB_TPU_HISTORY") == "tiered"
        # Abort-witness emission (ISSUE 17), resolved once like the other
        # engine-variant flags; JaxConflictSet's exact semantics.
        self._witness = g_env.get("FDB_TPU_WITNESS") not in ("", "0")
        # Per-txn (absolute version, read-range ordinal) pairs — or None —
        # for the most recent decided batch; [] when witness is off.
        self.last_witness: list = []
        self._last_witness_dev = ()
        self.evict_every = max(1, g_env.get_int("FDB_TPU_EVICT_EVERY"))
        self.compact_every = 0
        self.d_cap = 0
        if self.tiered:
            self.compact_every = (
                self.evict_every if self.evict_every > 1 else 0
            )
            dc_env = g_env.get_int("FDB_TPU_DELTA_CAP")
            self.d_cap = max(64, dc_env if dc_env > 0 else self.h_cap // 8)
        self._batches_since_major = 0
        # Telemetry registry (ISSUE 15): one registry, global counters
        # plus per-shard breaker instruments — every name pre-created so
        # same-seed snapshots are byte-identical regardless of which
        # shards fault (and the single-device engines' snapshots carry
        # none of this, the flat-snapshot discipline).
        from ..flow.metrics import MetricsRegistry

        self.metrics = MetricsRegistry("ShardedConflict")
        for _c in ("batches", "transactions", "device_batches", "retraces",
                   "grows", "rebases", "cpu_fallbacks", "cpu_fallback_txns",
                   "degraded_shard_serves", "long_key_pins",
                   "rehydrate_keys_total", "rehydrate_keys_encoded",
                   "mirror_sync_keys_encoded", "mirror_checks",
                   "mirror_divergence", "mirror_mismatch_keys",
                   "reshards", "reshard_moved_shards", "reshard_deferred",
                   "reshard_degraded"):
            self.metrics.counter(_c)
        if self.tiered:
            self.metrics.counter("major_compactions")
        # Per-shard fault domain state: breaker + authoritative mirror +
        # device-slice staleness + mirror-sync stamp.  Instruments (and
        # breakers — construction-order ids) cover max_shards so a later
        # scale-up finds its fault domain already wired.
        self._breakers: List[DeviceCircuitBreaker] = []
        for s in range(self.max_shards):
            prefix = f"shard{s}_"
            for name in _BREAKER_COUNTERS:
                self.metrics.counter(prefix + name)
            self._breakers.append(
                DeviceCircuitBreaker(
                    metrics=self.metrics,
                    label=f"shard{s}",
                    counter_prefix=prefix,
                )
            )
        self._mirrors = [
            CpuConflictSet(oldest_version, key_words=self.key_words)
            for _ in range(self.n_shards)
        ]
        self._stale = [False] * self.n_shards
        self._synced_stamp: list = [m.stamp for m in self._mirrors]
        # Long-key authority pin: the device cannot represent a long-key
        # boundary, so ALL serving moves to the mirrors until the window
        # flushes it and a hysteresis streak of short batches passes.
        self._pinned = False
        self._short_streak = 0
        self._degraded_last = False
        self._cpu_fallback_txns = 0
        self._cpu_fallback_recent = deque(maxlen=32)  # (txns, wall_seconds)
        self._last_mirror_check: Optional[dict] = None
        self.fault_injector = fault_injector
        # Replayable split-point move log (ISSUE 18): every reshard —
        # committed, deferred, or degraded — appends one entry; same seed
        # => json dump byte-identical.
        self.move_log: list = []
        self._init_state(oldest_rel=0)
        self.last_iters = 0

    def _partition_arrays(self, split_keys: list):
        """Encoded per-shard [lo, hi) bound arrays for a split-key list —
        shared by construction and reshard() (the one definition of the
        device-side partition)."""
        kw1 = self.key_words + 1
        S = len(split_keys) + 1
        lo = np.zeros((S, kw1), np.uint32)
        hi = np.full((S, kw1), keylib.INF_WORD, np.uint32)
        if split_keys:
            enc = keylib.encode_keys(list(split_keys), self.key_words)
            lo[1:] = enc
            hi[:-1] = enc
        return lo, hi

    # -- compat: the long-key pin's legacy surface (tests/old callers) --
    @property
    def _cpu_engines(self):
        """Pre-ISSUE-15 shape: the per-shard CPU engines while pinned,
        else None.  The mirrors now ALWAYS exist; the pin only moves
        authority wholesale."""
        return self._mirrors if self._pinned else None

    # -- state management (mirrors JaxConflictSet, with a leading shard axis) --
    def _init_state(self, oldest_rel: int):
        S, kw1 = self.n_shards, self.key_words + 1
        # Word-major per shard: (S, kw1, H) — see ops/rangequery.py on TPU
        # minor-dim tiling.
        hkeys = np.full((S, kw1, self.h_cap), keylib.INF_WORD, np.uint32)
        hkeys[:, :, 0] = 0  # b"" floor boundary per shard
        hvers = np.full((S, self.h_cap), FLOOR_REL, np.int32)
        put = partial(jax.device_put, device=self._shardspec)
        self._hkeys = put(jnp.asarray(hkeys))
        self._hvers = put(jnp.asarray(hvers))
        self._hcount = put(jnp.ones((S,), jnp.int32))
        self._oldest = put(jnp.full((S,), oldest_rel, jnp.int32))
        if self.tiered:
            table = _build_max_table_np(hvers[0])
            self._maxtab = put(
                jnp.asarray(np.broadcast_to(table, (S,) + table.shape).copy())
            )
            dkeys = np.full((S, kw1, self.d_cap), keylib.INF_WORD, np.uint32)
            dkeys[:, :, 0] = 0
            self._dkeys = put(jnp.asarray(dkeys))
            self._dvers = put(
                jnp.asarray(np.full((S, self.d_cap), FLOOR_REL, np.int32))
            )
            self._dcount = put(jnp.ones((S,), jnp.int32))
        self._batches_since_major = 0

    @property
    def oldest_version(self) -> int:
        # The mirrors are always authoritative (stale device slices lag).
        return max(m.oldest_version for m in self._mirrors)

    @property
    def boundary_count(self) -> int:
        return sum(m.boundary_count for m in self._mirrors)

    def clear(self, version: int):
        self._base = version
        self._pinned = False
        self._short_streak = 0
        self._mirrors = [
            CpuConflictSet(version, key_words=self.key_words)
            for _ in range(self.n_shards)
        ]
        self._init_state(oldest_rel=0)
        # Cleared device state == cleared mirrors, so no rehydration is
        # owed.  Breaker state is NOT reset — clearing data says nothing
        # about device health.
        self._stale = [False] * self.n_shards
        self._synced_stamp = [m.stamp for m in self._mirrors]

    # -- fault plumbing ---------------------------------------------------
    def install_fault_injector(self, injector) -> None:
        """Attach a DeviceFaultInjector (chaos workloads / soak shard
        kills); its per-shard plans target this set's choke points."""
        self.fault_injector = injector

    def consume_degraded(self) -> bool:
        """True iff the most recent batch had at least one shard served by
        its mirror because of a fault or an open shard breaker; reading
        resets the flag."""
        was, self._degraded_last = self._degraded_last, False
        return was

    def _check_fault(self, site: str, shard: int) -> None:
        if self.fault_injector is not None:
            self.fault_injector.check(site, shard=shard)

    def _shard_fault(self, s: int, fault: DeviceFault) -> None:
        """Fault attributed to shard s: only ITS breaker records it and
        only ITS device slice goes stale — the other shards' serve path
        is untouched (the fault-domain contract)."""
        self._breakers[s].on_failure(fault)
        self._stale[s] = True

    def _check_sites(self, site: str, allowed: list) -> list:
        out = list(allowed)
        for s in range(self.n_shards):
            if not out[s]:
                continue
            try:
                self._check_fault(site, s)
            except DeviceFault as e:
                self._shard_fault(s, e)
                out[s] = False
        return out

    # -- maintenance (rebase / growth), per-shard choke-pointed -----------
    def _maybe_grow_or_rebase(self, now: int, wr_cap: int, allowed: list):
        if now - self._base > REBASE_THRESHOLD:
            d = int(np.min(np.asarray(self._oldest)))
            if d > 0:
                allowed = self._check_sites("rebase", allowed)
                if any(allowed):
                    self.metrics.counter("rebases").add()
                    # Donating rebase body shared with the single-device
                    # engine (jaxcheck-registered: rebase_body).  A stale
                    # shard's slice shifts mechanically too — its logical
                    # state lives in its mirror (absolute versions), so
                    # rehydration is unaffected.
                    self._hvers = _rebase_step(self._hvers, d)
                    if self.tiered:
                        self._dvers = _rebase_step(self._dvers, d)
                        self._maxtab = _rebase_step(self._maxtab, d)
                    self._oldest = self._oldest - d
                    self._base += d
        if self.tiered or not any(allowed):
            return allowed
        need = int(np.max(np.asarray(self._hcount))) + 2 * wr_cap + 2
        if need > self.h_cap:
            allowed = self._check_sites("grow", allowed)
            if any(allowed):
                self._grow(max(self.h_cap * 2, self.h_cap + 4 * wr_cap))
        return allowed

    def _plan_tiered_batch(self, wr_cap: int, allowed: list):
        """Host-side compaction/growth plan for one tiered batch (the
        single-device engine's _plan_tiered_batch, with true counts maxed
        across shards — each shard receives at most the whole batch's
        writes, so one shared plan bounds every shard).  Returns
        (do_major, allowed)."""
        add = 2 * wr_cap
        if 2 * add + 8 > self.d_cap:
            allowed = self._check_sites("grow", allowed)
            if not any(allowed):
                return 0, allowed
            self._grow_delta(_next_pow2(2 * add + 8, self.d_cap * 2))
        dmax = int(np.max(np.asarray(self._dcount)))
        if dmax + add + 2 > self.d_cap:
            allowed = self._check_sites("grow", allowed)
            if not any(allowed):
                return 0, allowed
            self._grow_delta(_next_pow2(dmax + add + 2, self.d_cap * 2))
        do_major = 0
        if self.compact_every and (
            self._batches_since_major + 1 >= self.compact_every
        ):
            do_major = 1
        # Fill trigger: compact NOW if the batch AFTER this one might not
        # fit (so the merge never truncates on any shard).
        if dmax + 2 * add + 2 > self.d_cap:
            do_major = 1
        if do_major:
            hmax = int(np.max(np.asarray(self._hcount)))
            need = hmax + dmax + add + 2
            if need > self.h_cap:
                allowed = self._check_sites("grow", allowed)
                if not any(allowed):
                    return 0, allowed
                self._grow(
                    max(self.h_cap * 2, _next_pow2(need, self.h_cap))
                )
        return do_major, allowed

    def _grow(self, new_cap: int):
        self.metrics.counter("grows").add()
        pad = new_cap - self.h_cap
        put = partial(jax.device_put, device=self._shardspec)
        # Shared grow body (jaxcheck-registered: grow_body); the minor
        # axis is the per-shard history for both state blocks.
        self._hkeys = put(
            _grow_step(self._hkeys, pad=pad, fill=int(keylib.INF_WORD))
        )
        self._hvers = put(_grow_step(self._hvers, pad=pad, fill=FLOOR_REL))
        self.h_cap = new_cap
        if self.tiered:
            # The carried table's level count is a function of h_cap:
            # rebuild per shard from the (grown) base versions.
            hv = np.asarray(self._hvers)
            self._maxtab = put(jnp.asarray(np.stack(
                [_build_max_table_np(hv[s]) for s in range(self.n_shards)]
            )))
        self._steps.clear()

    def _grow_delta(self, new_cap: int):
        self.metrics.counter("grows").add()
        pad = new_cap - self.d_cap
        put = partial(jax.device_put, device=self._shardspec)
        self._dkeys = put(
            _grow_step(self._dkeys, pad=pad, fill=int(keylib.INF_WORD))
        )
        self._dvers = put(_grow_step(self._dvers, pad=pad, fill=FLOOR_REL))
        self.d_cap = new_cap
        self._steps.clear()

    def _step_key(self, pb: PackedBatch):
        """The compiled-program cache key — ONE definition, shared by
        _step_for and _serve's compile-choke-point check (a dimension
        added to one but not the other would silently skip or spuriously
        fire the per-shard compile fault site)."""
        return (pb.txn_cap, pb.rr_cap, pb.wr_cap, self.h_cap,
                self.d_cap if self.tiered else 0)

    def _step_for(self, pb: PackedBatch):
        key = self._step_key(pb)
        step = self._steps.get(key)
        if step is None:
            self.metrics.counter("retraces").add()
            step = _make_sharded_step(
                self.mesh, pb.txn_cap, pb.rr_cap, pb.wr_cap, self.h_cap,
                tiered=self.tiered, d_cap=self.d_cap,
                kernels=self._use_kernels,
                kernel_interpret=self._kernel_interpret,
                witness=self._witness,
            )
            self._steps[key] = step
        return step

    # -- per-shard mirror plumbing ----------------------------------------
    def _shard_bounds(self):
        """[(lo, hi_or_None)] per shard — the one definition."""
        return list(zip([b""] + self.split_keys, self.split_keys + [None]))

    @hot_path(bound="batch")
    def _clip_txns_for(self, txns, s: int, with_read_map: bool = False):
        """This shard's view of the batch: every range clipped to
        [lo_s, hi_s), empty clips dropped (the host twin of the device
        body's _clip_batch — TooOld then only applies where reads
        survive, exactly like the device's t_has_reads mask).  With
        `with_read_map`, also returns per txn the ORIGINAL read-range
        ordinal of each surviving clipped range, so a shard mirror's
        witness (indexed into the clipped list) translates back."""
        lo, hi = self._shard_bounds()[s]
        out = []
        rmap: list = []
        for tr in txns:
            rr, wr = [], []
            rmap_t: list = []
            for i, (b, e) in enumerate(tr.read_ranges):
                cb = b if b >= lo else lo
                ce = e if hi is None or e <= hi else hi
                if cb < ce:
                    rr.append((cb, ce))
                    rmap_t.append(i)
            for (b, e) in tr.write_ranges:
                cb = b if b >= lo else lo
                ce = e if hi is None or e <= hi else hi
                if cb < ce:
                    wr.append((cb, ce))
            rmap.append(rmap_t)
            out.append(
                TransactionConflictInfo(
                    read_snapshot=tr.read_snapshot,
                    read_ranges=rr,
                    write_ranges=wr,
                )
            )
        if with_read_map:
            return out, rmap
        return out

    @hot_path(bound="batch")
    def _committed_writes_per_shard(self, txns, rows, shards):
        """Per-shard clipped COMMITTED write ranges, judged by each
        shard's LOCAL verdict row (ref: each resolver commits on its
        local view).  Ranges are assigned by bisect span over the split
        points — O(ranges x spanned shards), not O(ranges x S) — so the
        healthy path's mirror maintenance stays cheap at production
        batch sizes."""
        from bisect import bisect_left, bisect_right

        split = self.split_keys
        last = self.n_shards - 1
        bounds = self._shard_bounds()
        per = {s: [] for s in shards}
        for i, tr in enumerate(txns):
            for (b, e) in tr.write_ranges:
                if b >= e:
                    continue
                s0 = bisect_right(split, b)
                s1 = bisect_left(split, e)
                for s in range(s0, min(s1, last) + 1):  # perfcheck: ignore[HOT004]: iterates spanned SHARDS (bounded by the mesh, not rows); each reads one verdict scalar
                    lst = per.get(s)
                    if lst is None or int(rows[s][i]) != COMMITTED:
                        continue
                    lo, hi = bounds[s]
                    cb = b if b >= lo else lo
                    ce = e if hi is None or e <= hi else hi
                    if cb < ce:
                        lst.append((cb, ce))
        return per

    def _apply_shard_writes(self, s, ranges, now, new_oldest_version):
        """Adopt a device-decided batch into shard s's mirror: merge the
        shard's committed write union and advance its window exactly as
        its detect() would have (one chunk sweep)."""
        txn = (
            [TransactionConflictInfo(read_snapshot=0, write_ranges=ranges)]
            if ranges
            else []
        )
        self._mirrors[s].apply_batch(
            txn, [COMMITTED] if ranges else [], now, new_oldest_version
        )

    @hot_path(bound="chunks")
    def _note_synced_shard(self, s: int) -> None:
        """Record that shard s's device slice now equals its mirror,
        pre-encoding chunks created this batch (the mirror's
        take_fresh_chunks hint) so a LATER probe's rehydration pays only
        for chunks created after the fault — O(changed chunks) PER SHARD
        (ISSUE 15 satellite; the ISSUE-9 sync discipline)."""
        mir = self._mirrors[s]
        fresh, complete = mir.take_fresh_chunks()
        if mir.stamp == self._synced_stamp[s]:
            return
        candidates = fresh if complete else mir.snapshot().chunks
        encoded = 0
        for ch in candidates:
            cache = ch.enc
            if cache is None or self.key_words not in cache:
                try:
                    _ent, k = chunk_encoding(ch, self.key_words)
                except ValueError:
                    continue  # dead long-key chunk from the hint
                encoded += k
        if encoded:
            self.metrics.counter("mirror_sync_keys_encoded").add(encoded)
        self._synced_stamp[s] = mir.stamp

    def _replace_slice(self, arr, s: int, new_np):
        """Replace ONE shard's slice of a mesh-sharded carried array,
        reusing every other shard's device buffer by reference (only the
        rebuilt slice transfers — per-shard rehydration must not pay
        O(S x H))."""
        new_dev = jnp.asarray(new_np)[None]
        if self.n_shards == 1:
            return jax.device_put(new_dev, self._shardspec)
        devs = list(self.mesh.devices.flat)
        shards = sorted(
            arr.addressable_shards, key=lambda sh: sh.index[0].start or 0
        )
        bufs = [sh.data for sh in shards]
        bufs[s] = jax.device_put(new_dev, devs[s])
        return jax.make_array_from_single_device_arrays(
            arr.shape, self._shardspec, bufs
        )

    def _rehydrate_shard(self, s: int) -> None:
        """Rebuild shard s's device slice from its mirror SNAPSHOT — the
        per-shard half-open probe's recovery path.  The snapshot is
        immutable (a fault mid-probe can neither observe nor corrupt a
        half-mutated mirror) and the per-chunk encode caches make the
        host work proportional to chunks changed since shard s's last
        device sync (rehydrate_keys_encoded vs rehydrate_keys_total is
        the asserted evidence).  Raises DeviceFault (site grow: the
        reallocation choke point) BEFORE any state mutates."""
        from ..flow.spans import begin_span

        self._check_fault("grow", s)
        m = self.metrics
        mir = self._mirrors[s]
        with begin_span("rehydrate", attrs={"shard": s}):
            snap = mir.snapshot()
            n = snap.boundary_count
            if n + 8 > self.h_cap:
                self._grow(_next_pow2(n + 8, self.h_cap * 2))
            ents = []
            encoded = 0
            for ch in snap.chunks:
                ent, k = chunk_encoding(ch, self.key_words)
                ents.append(ent)
                encoded += k
            m.counter("rehydrate_keys_total").add(n)
            m.counter("rehydrate_keys_encoded").add(encoded)
            kw1 = self.key_words + 1
            hk = np.full((kw1, self.h_cap), keylib.INF_WORD, np.uint32)
            hv = np.full((self.h_cap,), FLOOR_REL, np.int32)
            keys_enc = np.concatenate([e[0] for e in ents], axis=0)
            vers_abs = np.concatenate([e[1] for e in ents])
            hk[:, :n] = keys_enc.T
            rel = np.clip(vers_abs - self._base, FLOOR_REL, 2**31 - 2)
            rel[vers_abs == FLOOR_VERSION] = FLOOR_REL
            hv[:n] = rel.astype(np.int32)
            oldest_rel = int(
                np.clip(snap.oldest_version - self._base, 0, 2**31 - 2)
            )
            self._write_shard_slice(s, hk, hv, n, oldest_rel)
        self._breakers[s].note_rehydrate()
        self._stale[s] = False
        self._synced_stamp[s] = snap.stamp
        mir.take_fresh_chunks()  # everything just encoded: backlog moot

    def _write_shard_slice(self, s, hk, hv, count, oldest_rel):
        put = partial(jax.device_put, device=self._shardspec)
        self._hkeys = self._replace_slice(self._hkeys, s, hk)
        self._hvers = self._replace_slice(self._hvers, s, hv)
        counts = np.asarray(self._hcount).copy()
        counts[s] = count
        olds = np.asarray(self._oldest).copy()
        olds[s] = oldest_rel
        self._hcount = put(jnp.asarray(counts.astype(np.int32)))
        self._oldest = put(jnp.asarray(olds.astype(np.int32)))
        if self.tiered:
            # Rehydration resets the shard's tier split: the adopted
            # state becomes its frozen base, its delta restarts empty.
            self._maxtab = self._replace_slice(
                self._maxtab, s, _build_max_table_np(hv)
            )
            kw1 = self.key_words + 1
            dk = np.full((kw1, self.d_cap), keylib.INF_WORD, np.uint32)
            dk[:, 0] = 0
            dv = np.full((self.d_cap,), FLOOR_REL, np.int32)
            self._dkeys = self._replace_slice(self._dkeys, s, dk)
            self._dvers = self._replace_slice(self._dvers, s, dv)
            dc = np.asarray(self._dcount).copy()
            dc[s] = 1
            self._dcount = put(jnp.asarray(dc.astype(np.int32)))

    # -- ConflictSet ABI --
    def new_batch(self):
        """Drop-in for the Resolver's ConflictSet surface (api.py): the
        mesh-sharded set plugs into a live cluster's resolver via
        `Resolver(conflict_set=...)` (ref: the ConflictSet swap point,
        Resolver.actor.cpp:140-153)."""
        from ..conflict.api import ConflictBatch

        return ConflictBatch(self)

    def _detect(self, txns, now, new_oldest_version) -> List[int]:
        return self.detect(txns, now, new_oldest_version)

    def detect(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> List[int]:
        # Long-key discipline (the hybrid single-chip set's, sharded):
        # keys beyond the device key width cannot ride the device — such
        # batches run on the per-shard MIRRORS with the exact
        # multi-resolver semantics against the SAME logical state, so
        # cluster use with arbitrary byte keys (system keyspace, markers)
        # is safe.  A long-key WRITE enters shard history, which the
        # device arrays cannot represent: authority pins to the mirrors
        # until every shard's history fits again AND a hysteresis streak
        # of short batches passes, then each shard's device slice
        # rehydrates from its mirror snapshot.
        from ..flow.knobs import g_knobs

        width = min(
            g_knobs.server.conflict_max_device_key_bytes,
            self.key_words * 4,
        )
        batch_long = any(
            len(b) > width
            for t in transactions
            for rng in (t.read_ranges, t.write_ranges)
            for pair in rng
            for b in pair
        )
        if batch_long or self._pinned:
            if batch_long:
                from ..flow.testprobe import test_probe

                test_probe("sharded_long_key_fallback")
                if not self._pinned:
                    self.metrics.counter("long_key_pins").add()
                self._pinned = True
                self._short_streak = 0
            else:
                self._short_streak += 1
            return self._serve_pinned(
                transactions, now, new_oldest_version
            )
        mt, mr, mw = self.bucket_mins
        pb = PackedBatch.from_transactions(
            transactions, self.key_words, min_txn=mt, min_rr=mr, min_wr=mw
        )
        # Through the instance's detect_packed (the bench/dispatch ABI and
        # the observable device entry — tests wrap it to count dispatches).
        # Short-key batches pack/unpack losslessly, so the mirrors see the
        # exact ranges.
        statuses = self.detect_packed(pb, now, new_oldest_version)
        return [int(s) for s in statuses[: len(transactions)]]

    def detect_packed(self, pb: PackedBatch, now: int, new_oldest_version: int):
        txns = _unpack_transactions(pb)
        if self._pinned:
            # Mirrors hold the authoritative history (long-key pin):
            # resolving on the stale device arrays would miss every write
            # committed since the pin.
            self._short_streak += 1
            out = np.full((pb.txn_cap,), COMMITTED, np.int32)
            res = self._serve_pinned(txns, now, new_oldest_version)
            out[: len(res)] = res
            return out
        return self._serve(txns, pb, now, new_oldest_version)

    def _serve_pinned(self, txns, now: int, new_oldest_version: int):
        """All-mirror serve during the long-key pin (by-design routing,
        never a degraded serve), plus the unpin check."""
        statuses = self._mirror_detect_all(txns, now, new_oldest_version)
        if self._short_streak >= self.AUTHORITY_HYSTERESIS and all(
            keylib.fits(m.keys, self.key_words) for m in self._mirrors
        ):
            self._pinned = False
            self._short_streak = 0
            # Each shard's device slice rehydrates lazily from its mirror
            # snapshot on the next device batch (per-chunk encode caches
            # make that O(changed chunks) per shard).
            self._stale = [True] * self.n_shards
        return statuses

    def _mirror_detect_all(self, txns, now: int, new_oldest_version: int):
        """Run a whole batch on the per-shard mirrors with the exact
        multi-resolver semantics: ranges clipped per shard, each shard
        commits writes on its LOCAL verdict, verdicts min-combined (ref
        Resolver.actor.cpp:140-153, proxy :492-499).  Witnesses combine
        under the same rule as the device step (_combine_witness)."""
        verdicts = []
        parts = []
        for s in range(self.n_shards):
            clipped, rmap = self._clip_txns_for(txns, s, with_read_map=True)
            verdicts.append(
                self._mirrors[s].detect(clipped, now, new_oldest_version)
            )
            if self._witness:
                parts.append(
                    _translate_witness(self._mirrors[s].last_witness, rmap)
                )
        combined = [min(v) for v in zip(*verdicts)] if txns else []
        if self._witness:
            self.last_witness = _combine_witness(parts, combined)
        return combined

    def _serve(self, txns, pb: PackedBatch, now: int, new_oldest_version: int):
        """One short-key batch through the shard-granular serve path:
        device for every shard whose breaker allows it (stale slices
        rehydrated first), mirror for the rest — bit-identical verdicts
        either way, and only a faulting shard's breaker walks."""
        from ..flow.spans import begin_span

        S = self.n_shards
        m = self.metrics
        m.counter("batches").add()
        m.counter("transactions").add(pb.n_txn)
        allowed = [
            br.allows_device() for br in self._breakers[: self.n_shards]
        ]
        for s in range(S):
            if not allowed[s]:
                continue
            try:
                if self._stale[s]:
                    self._rehydrate_shard(s)
                self._check_fault("dispatch", s)
            except DeviceFault as e:
                self._shard_fault(s, e)
                allowed[s] = False
        do_major = 0
        if any(allowed):
            allowed = self._maybe_grow_or_rebase(now, pb.wr_cap, allowed)
        if self.tiered and any(allowed):
            do_major, allowed = self._plan_tiered_batch(pb.wr_cap, allowed)
        if any(allowed):
            if self._step_key(pb) not in self._steps:
                # A first sight of this shape compiles one program for
                # the whole mesh; the compile choke point is checked per
                # ACTIVE shard (a chip that cannot load its program slice
                # degrades alone).
                allowed = self._check_sites("compile", allowed)
        rows: list = [None] * S
        if any(allowed):
            diverged = self._device_serve(
                txns, pb, now, new_oldest_version, allowed, do_major, rows
            )
            if diverged:
                # All active shards kept pristine state (the in-core psum
                # gate); the whole batch re-decides on the mirrors — a
                # by-design CPU re-decide, not a degraded serve (the
                # single-device engine's _fallback_cpu discipline) —
                # EXCEPT for shards that were already sick this batch:
                # their slices ride the all-mirror re-decide too, and
                # that is still degraded serving (counted, flagged).
                m.counter("cpu_fallbacks").add()
                sick = [s for s in range(S) if not allowed[s]]
                if sick:
                    m.counter("degraded_shard_serves").add(len(sick))
                    self._degraded_last = True
                for s in range(S):
                    if allowed[s]:
                        self._stale[s] = True
                out = np.full((pb.txn_cap,), COMMITTED, np.int32)
                res = self._mirror_detect_all(txns, now, new_oldest_version)
                out[: len(res)] = res
                return out
        mirror_shards = [s for s in range(S) if not allowed[s]]
        mirror_wit: list = []
        if mirror_shards:
            # Degraded serving, scoped to the sick shards: each re-runs
            # ONLY its slice of the batch on its mirror (bit-identical by
            # construction) while the healthy shards' device verdicts
            # stand.  Timed on the wall clock for backend_signal()'s
            # cpu_mirror_tps (wall namespace only).
            from ..flow.metrics import wall_now

            t0 = wall_now()
            for s in mirror_shards:
                row = np.full((pb.txn_cap,), COMMITTED, np.int32)
                clipped, rmap = self._clip_txns_for(
                    txns, s, with_read_map=True
                )
                local = self._mirrors[s].detect(
                    clipped, now, new_oldest_version
                )
                row[: len(local)] = local
                rows[s] = row
                if self._witness:
                    mirror_wit.append(_translate_witness(
                        self._mirrors[s].last_witness, rmap
                    ))
            self._cpu_fallback_txns += len(txns)
            self._cpu_fallback_recent.append((len(txns), wall_now() - t0))
            m.counter("cpu_fallback_txns").add(len(txns))
            m.counter("degraded_shard_serves").add(len(mirror_shards))
            self._degraded_last = True
        device_shards = [s for s in range(S) if allowed[s]]
        if device_shards:
            with begin_span("apply", attrs={"version": now,
                                            "n_txn": pb.n_txn}):
                per = self._committed_writes_per_shard(
                    txns, rows, device_shards
                )
                for s in device_shards:
                    self._apply_shard_writes(
                        s, per[s], now, new_oldest_version
                    )
                    self._note_synced_shard(s)
        combined = np.min(np.stack(rows, axis=0), axis=0).astype(np.int32)
        if self._witness:
            # Join the device step's in-core-combined witness (covers the
            # ACTIVE shards; every row replicated — take row 0) with each
            # mirror-served shard's translated witness under the one
            # combine rule.  Pure-device batches reduce to the device
            # vector; pure-mirror batches to the host combine.
            parts = list(mirror_wit)
            if device_shards and self._last_witness_dev:
                wv, wr = self._last_witness_dev
                parts.append(decode_witness(
                    pb, combined, np.asarray(wv)[0], np.asarray(wr)[0],
                    self._base,
                ))
            self.last_witness = _combine_witness(
                parts, [int(v) for v in combined[: pb.n_txn]]
            )
        return combined

    def _device_serve(self, txns, pb, now, new_oldest_version, allowed,
                      do_major, rows) -> bool:
        """Dispatch one batch to the mesh with the active-shard mask;
        fills `rows` with each ACTIVE shard's local verdicts.  Returns
        True when the (active-combined) fixpoint diverged — every active
        shard's state then reverted in-core."""
        from ..flow.spans import begin_span
        from ..flow.trace import TraceEvent

        m = self.metrics
        step = self._step_for(pb)
        clip = lambda v: np.clip(v - self._base, FLOOR_REL + 1, 2**31 - 2)
        put = partial(jax.device_put, device=self._shardspec)
        active = put(jnp.asarray(np.asarray(allowed, bool)))
        batch_args = (
            jnp.asarray(np.ascontiguousarray(pb.r_begin.T)),
            jnp.asarray(np.ascontiguousarray(pb.r_end.T)),
            jnp.asarray(pb.r_txn),
            jnp.asarray(clip(pb.r_snap).astype(np.int32)),
            jnp.asarray(np.ascontiguousarray(pb.w_begin.T)),
            jnp.asarray(np.ascontiguousarray(pb.w_end.T)),
            jnp.asarray(pb.w_txn),
            jnp.asarray(clip(pb.t_snap).astype(np.int32)),
            jnp.asarray(pb.t_valid),
            jnp.asarray(clip(now), dtype=jnp.int32),
            jnp.asarray(clip(new_oldest_version), dtype=jnp.int32),
        )
        with begin_span("device", attrs={"version": now}):
            if self.tiered:
                out = step(
                    self._lo, self._hi, active,
                    self._hkeys, self._hvers, self._hcount, self._maxtab,
                    self._dkeys, self._dvers, self._dcount, self._oldest,
                    *batch_args, jnp.asarray(do_major, jnp.int32),
                )
                (
                    self._hkeys, self._hvers, self._hcount, self._maxtab,
                    self._dkeys, self._dvers, self._dcount, self._oldest,
                    status_s, undec_s, iters_s,
                ) = out[:11]
                self._last_witness_dev = out[11:]
            else:
                out = step(
                    self._lo, self._hi, active,
                    self._hkeys, self._hvers, self._hcount, self._oldest,
                    *batch_args,
                )
                (
                    self._hkeys, self._hvers, self._hcount, self._oldest,
                    status_s, undec_s, iters_s,
                ) = out[:7]
                self._last_witness_dev = out[7:]
            undecided = int(np.max(np.asarray(undec_s)))
            self.last_iters = int(np.max(np.asarray(iters_s)))
        m.counter("device_batches").add()
        if self.tiered:
            if do_major:
                m.counter("major_compactions").add()
                self._batches_since_major = 0
            else:
                self._batches_since_major += 1
        if undecided != 0:
            TraceEvent("ConflictFixpointDiverged", severity=30).detail(
                "n_txn", pb.n_txn
            ).detail("sharded", True).log()
            return True
        status_np = np.asarray(status_s)
        for s in range(self.n_shards):
            if allowed[s]:
                rows[s] = status_np[s]
                # The batch's verdicts are real: credit each serving
                # shard's breaker (a probing shard closes here).
                self._breakers[s].on_success()
        return False

    # -- robustness surfaces (the ConflictSet contract) -------------------
    def backend_signal(self) -> dict:
        """O(1) admission-control probe: worst shard breaker state plus
        the shard-granular detail — shards_degraded out of shards_total
        lets the ratekeeper contract the lane PROPORTIONALLY (one sick
        chip out of 8 costs ~1/8 of capacity, not a global degraded
        clamp).  cpu_mirror_tps is wall-clock-derived (0.0 = nothing
        measured) and MUST NOT feed deterministic decisions in sim."""
        order = {"ok": 0, "probing": 1, "degraded": 2}
        worst = "ok"
        degraded = 0
        for b in self._breakers[: self.n_shards]:
            if b.state != "ok":
                degraded += 1
            if order[b.state] > order[worst]:
                worst = b.state
        tps = 0.0
        wall = sum(w for _n, w in self._cpu_fallback_recent)
        if wall > 0.0:
            tps = sum(n for n, _w in self._cpu_fallback_recent) / wall
        return {
            "backend_state": worst,
            "cpu_mirror_tps": tps,
            "cpu_fallback_txns": self._cpu_fallback_txns,
            "mirror_divergence": int(
                self.metrics.counter("mirror_divergence").value
            ),
            "shards_total": self.n_shards,
            "shards_degraded": degraded,
        }

    def device_metrics(self, now=None) -> dict:
        """Registry snapshot + per-shard breaker walk — the status doc's
        tpu section for a sharded resolver.  Every per-shard key was
        pre-created at construction, so the snapshot's shape never
        depends on which shards faulted."""
        snap = self.metrics.snapshot(now=now)
        snap["h_cap"] = self.h_cap
        sig = self.backend_signal()
        snap["backend_state"] = sig["backend_state"]
        snap["shards"] = {
            "total": self.n_shards,
            "max": self.max_shards,
            "degraded": sig["shards_degraded"],
            "states": [
                b.state for b in self._breakers[: self.n_shards]
            ],
            "stale": [bool(x) for x in self._stale],
            "pinned": self._pinned,
            "split_keys": [k.hex() for k in self.split_keys],
            "occupancy": self.shard_occupancy(),
            "moves": len(self.move_log),
            "last_move": self.last_move,
        }
        snap["shard_breakers"] = {
            f"shard{s}": self._breakers[s].snapshot()
            for s in range(self.max_shards)
        }
        if self._use_kernels:
            snap["kernels"] = {
                "enabled": True,
                "interpret": bool(self._kernel_interpret),
            }
        if self.tiered:
            snap["tiers"] = {
                "mode": "tiered",
                "d_cap": self.d_cap,
                "compact_every": self.compact_every,
                "batches_since_major": self._batches_since_major,
            }
        snap["mirror"] = {
            "engine": type(self._mirrors[0]).__name__,
            "chunks": sum(m.chunk_count for m in self._mirrors),
            "boundary_count": sum(
                m.boundary_count for m in self._mirrors
            ),
            "last_check": self._last_mirror_check,
        }
        return snap

    def mirror_check(self) -> dict:
        """Per-shard consistency check (the ISSUE-9 checker made
        shard-granular): diff each SERVING shard's device slice export
        against its authoritative mirror; confirmed divergence opens ONLY
        that shard's breaker and marks only that slice stale (recovery
        rehydrates it from the mirror snapshot).  Stale / non-ok shards
        are skipped O(1) — the device is not expected to match there."""
        m = self.metrics
        shards_report: dict = {}
        if self._pinned:
            report = {"status": "skipped", "reason": "long_key_pin"}
            self._last_mirror_check = report
            return report
        hkeys = hvers = counts = olds = None
        dkeys = dvers = dcounts = None
        checked = 0
        diverged = 0
        for s in range(self.n_shards):
            if self._stale[s] or self._breakers[s].state != "ok":
                shards_report[f"shard{s}"] = {
                    "status": "skipped",
                    "reason": (
                        "stale" if self._stale[s]
                        else f"breaker_{self._breakers[s].state}"
                    ),
                }
                continue
            if hkeys is None:  # decode lazily, once, only if any shard serves
                hkeys = np.asarray(self._hkeys)
                hvers = np.asarray(self._hvers)
                counts = np.asarray(self._hcount)
                olds = np.asarray(self._oldest)
                if self.tiered:
                    dkeys = np.asarray(self._dkeys)
                    dvers = np.asarray(self._dvers)
                    dcounts = np.asarray(self._dcount)
            m.counter("mirror_checks").add()
            checked += 1
            dk, dv = self._device_shard_state(
                s, hkeys, hvers, counts, dkeys, dvers, dcounts
            )
            mk, mv = self._mirrors[s].snapshot().to_flat()
            mismatch = 0
            if self._mirrors[s].oldest_version != int(olds[s]) + self._base:
                mismatch += 1
            if mk != dk or mv != dv:
                mirror = dict(zip(mk, mv))
                device = dict(zip(dk, dv))
                for key in mirror.keys() | device.keys():
                    if mirror.get(key) != device.get(key):
                        mismatch += 1
            if mismatch:
                from ..flow.flight_recorder import maybe_trigger
                from ..flow.trace import TraceEvent

                diverged += 1
                m.counter("mirror_divergence").add()
                m.counter("mirror_mismatch_keys").add(mismatch)
                TraceEvent("MirrorDivergence", severity=40).detail(
                    "mismatch_keys", mismatch
                ).detail("shard", s).detail(
                    "mirror_boundaries", len(mk)
                ).detail("device_boundaries", len(dk)).log()
                breaker = self._breakers[s]
                breaker.on_divergence(f"mismatch_keys={mismatch}")
                maybe_trigger(
                    "mirror_divergence",
                    detail={"shard": s, "mismatch_keys": mismatch,
                            "mirror_boundaries": len(mk),
                            "device_boundaries": len(dk)},
                    transitions=lambda b=breaker: [
                        list(t) for t in b.transitions
                    ],
                    source=breaker.breaker_id,
                )
                self._stale[s] = True
                self._degraded_last = True
            shards_report[f"shard{s}"] = {
                "status": "diverged" if mismatch else "ok",
                "boundaries": len(mk),
                "device_boundaries": len(dk),
                "mismatch_keys": mismatch,
            }
        report = {
            "status": (
                "diverged" if diverged else ("ok" if checked else "skipped")
            ),
            "shards": shards_report,
        }
        self._last_mirror_check = report
        return report

    def _device_shard_state(self, s, hkeys, hvers, counts,
                            dkeys, dvers, dcounts):
        """Shard s's device slice decoded to host (keys, abs versions) —
        the merged (base+delta folded) logical view in tiered mode, via
        the ONE shared fold (engine_jax.fold_delta_over_base)."""
        def absv(rel):
            rel = int(rel)
            return FLOOR_VERSION if rel == FLOOR_REL else rel + self._base

        n = int(counts[s])
        rows = hkeys[s, :, :n].T
        bkeys = [
            keylib.decode_key(rows[i], self.key_words) for i in range(n)
        ]
        bvers = [absv(v) for v in hvers[s, :n]]
        if not self.tiered:
            return bkeys, bvers
        nd = int(dcounts[s])
        drows = dkeys[s, :, :nd].T
        dks = [
            keylib.decode_key(drows[j], self.key_words) for j in range(nd)
        ]
        return fold_delta_over_base(
            bkeys, bvers, dks, dvers[s, :nd], self._base
        )

    # -- host state exchange (resharding / recovery) ----------------------
    def _flatten_engines_to(self, engines: list, cpu) -> None:
        """Per-shard engines -> one global step function: shard 0
        contributes its full boundary list below hi_0; each later shard
        re-anchors at lo_s with its value there, then its boundaries
        strictly inside (lo_s, hi_s)."""
        from bisect import bisect_left, bisect_right

        bounds = self._shard_bounds()
        keys: list = []
        vers: list = []
        for (lo, hi), eng in zip(bounds, engines):
            if lo == b"":
                i0 = 0
            else:
                keys.append(lo)
                vers.append(eng._value_at(lo))
                i0 = bisect_right(eng.keys, lo)
            i1 = len(eng.keys) if hi is None else bisect_left(eng.keys, hi)
            keys.extend(eng.keys[i0:i1])
            vers.extend(eng.vers[i0:i1])
        cpu.keys = keys
        cpu.vers = vers
        cpu.oldest_version = min(e.oldest_version for e in engines)

    def _split_flat_to_engines(self, cpu) -> list:
        """One global step function -> per-shard engines (the inverse of
        _flatten_engines_to; the load_from path)."""
        from bisect import bisect_left, bisect_right

        bounds = self._shard_bounds()
        engines = []
        for lo, hi in bounds:
            eng = CpuConflictSet(cpu.oldest_version,
                                 key_words=self.key_words)
            i0 = bisect_right(cpu.keys, lo)
            i1 = len(cpu.keys) if hi is None else bisect_left(cpu.keys, hi)
            eng.keys = [b""] + cpu.keys[i0:i1]
            eng.vers = [cpu._value_at(lo)] + cpu.vers[i0:i1]
            engines.append(eng)
        return engines

    def store_to(self, cpu) -> None:
        """Flatten the per-shard step functions into the CPU engine's
        global one.  The mirrors ARE the authoritative per-shard state
        (updated with every batch's local verdicts — ISSUE 15), so the
        export never touches the device and is exact even mid-outage."""
        self._flatten_engines_to(self._mirrors, cpu)

    def load_from(self, cpu) -> None:
        """Adopt a global CPU state: scatter it into per-shard mirrors
        (inverse of store_to).  Device slices rehydrate lazily, each from
        its own mirror snapshot, on the next device batch — O(changed
        chunks) per shard via the per-chunk encode caches.  A state
        containing long keys installs as a mirror pin instead of raising
        at encode."""
        self._base = cpu.oldest_version
        self._mirrors = self._split_flat_to_engines(cpu)
        self._synced_stamp = [None] * self.n_shards
        self._short_streak = 0
        self._pinned = not keylib.fits(cpu.keys, self.key_words)
        self._stale = [True] * self.n_shards

    # -- live split-point migration (ISSUE 18) ----------------------------
    def shard_occupancy(self) -> list:
        """Per-shard mirror boundary counts — the balancer's occupancy
        gauge.  O(1) per shard (the mirrors maintain the count), always
        exact even mid-outage (mirrors are authoritative)."""
        return [m.boundary_count for m in self._mirrors]

    @property
    def last_move(self) -> Optional[dict]:
        """The most recent move-log entry (status/cli `shards` block)."""
        return self.move_log[-1] if self.move_log else None

    def balance_split_keys(self, n_shards: Optional[int] = None) -> list:
        """Quantile split points equalizing mirror boundary counts across
        `n_shards` (default: the current count).  Candidates are the
        ACTUAL boundary keys of the global step function (flattened per
        the store_to convention), so an unchanged quantile reproduces an
        existing split point exactly — reshard() then reuses that shard's
        mirror by identity.  Returns the CURRENT split keys when the
        history is too small to cut n ways (the balancer's no-op)."""
        from bisect import bisect_left, bisect_right

        n = self.n_shards if n_shards is None else int(n_shards)
        if not all(
            hasattr(m, "boundary_locate") for m in self._mirrors
        ):
            # Flat mirrors store bytes natively: the list path is the
            # cheap one there.
            ks_all: list = []
            for (lo, hi), eng in zip(self._shard_bounds(), self._mirrors):
                ks = eng.keys
                if lo == b"":
                    i0 = 1  # the b"" floor boundary is not a cuttable key
                else:
                    ks_all.append(lo)
                    i0 = bisect_right(ks, lo)
                i1 = len(ks) if hi is None else bisect_left(ks, hi)
                ks_all.extend(ks[i0:i1])
            if len(ks_all) < n:
                return list(self.split_keys)
            out: list = []
            for j in range(1, n):
                k = ks_all[(len(ks_all) * j) // n]
                if k != b"" and (not out or k > out[-1]):
                    out.append(k)
            if len(out) != n - 1:
                return list(self.split_keys)
            return out
        # Columnar mirrors (ISSUE 19): same candidate sequence, but as
        # per-shard (engine, offset, count) segments over the chunked
        # columns — only the n-1 selected quantile keys are ever decoded
        # to bytes, instead of materializing every boundary.
        segs: list = []  # ("key", k, 0, 1) | ("eng", eng, i0, count)
        total = 0
        for (lo, hi), eng in zip(self._shard_bounds(), self._mirrors):
            if lo == b"":
                i0 = 1  # the b"" floor boundary is not a cuttable key
            else:
                segs.append(("key", lo, 0, 1))
                total += 1
                i0 = eng.boundary_locate(lo, "right")
            i1 = (
                eng.boundary_count if hi is None
                else eng.boundary_locate(hi, "left")
            )
            c = i1 - i0
            if c > 0:
                segs.append(("eng", eng, i0, c))
                total += c
        if total < n:
            return list(self.split_keys)
        out = []
        for j in range(1, n):
            g = (total * j) // n
            k = b""
            for kind, obj, i0, c in segs:
                if g < c:
                    k = obj if kind == "key" else obj.boundary_key_at(i0 + g)
                    break
                g -= c
            if k != b"" and (not out or k > out[-1]):
                out.append(k)
        if len(out) != n - 1:
            return list(self.split_keys)
        return out

    def reshard(self, new_split_keys: Sequence[bytes],
                reason: str = "manual") -> dict:
        """Live split-point migration: re-partition the mesh along
        `new_split_keys` WITHOUT stopping the resolver, and return the
        appended move-log entry.

        The commit is a synchronous host step between batches, so every
        batch resolves against a complete, validated partition — the old
        one up to the commit, the new one after — never a torn mix (the
        multi-resolver min-combine is partition-independent, so verdicts
        and witnesses stay bit-identical to the single-set oracle across
        the move).  Mechanics:

          - one immutable ``MirrorSnapshot`` cut per old shard;
          - a new shard whose range is UNCHANGED adopts the old mirror by
            identity (encode caches, sync stamp and device slice ride
            along); a moved shard's mirror is rebuilt by CHUNK handoff
            (``engine_from_handoff``): interior chunks by reference, only
            boundary chunks at moved split points re-chunked — O(moved
            ranges), and the per-chunk encode caches survive;
          - moved shards go stale; their device slices rebuild lazily via
            the per-shard rehydrate (``_replace_slice`` by-reference
            swaps), exactly like a probe recovery;
          - a shard-count change (2→4→8 scaling, bounded by
            ``max_shards``) rebuilds the mesh and re-inits device state;
            every shard then rehydrates from its repartitioned mirror.

        Fault legality (tentpole part 4): the ``reshard`` choke point is
        checked per moved shard BEFORE any state mutates — a scripted
        fault DEFERS the whole move (the snapshot cuts are immutable and
        unadopted, so the authoritative mirrors stay exact) and replays
        byte-identically.  A moved shard with an open breaker completes
        the move degraded-on-mirror: the handoff needs no device, and the
        rebuilt shard stays mirror-served until its breaker closes."""
        from ..flow.flight_recorder import maybe_trigger
        from ..flow.spans import instant
        from ..flow.trace import TraceEvent

        new = [bytes(k) for k in new_split_keys]
        n_new = len(new) + 1
        assert all(
            new[i] < new[i + 1] for i in range(len(new) - 1)
        ) and all(k != b"" for k in new), (
            "split keys must be strictly increasing and non-empty"
        )
        assert n_new <= self.max_shards, (
            f"{n_new} shards exceed max_shards={self.max_shards} "
            "(per-shard fault domains are pre-created at construction)"
        )
        if not keylib.fits(new, self.key_words):
            raise ValueError(
                "split keys must fit the device key width "
                f"({self.key_words * 4} bytes)"
            )
        old = list(self.split_keys)
        m = self.metrics
        entry: dict = {
            "seq": len(self.move_log),
            "reason": reason,
            "from": [k.hex() for k in old],
            "to": [k.hex() for k in new],
            "shards": [len(old) + 1, n_new],
        }
        if new == old:
            entry["action"] = "noop"
            entry["moved"] = []
            self.move_log.append(entry)
            return entry
        old_bounds = self._shard_bounds()
        new_bounds = list(zip([b""] + new, new + [None]))
        scaling = n_new != self.n_shards
        moved = (
            list(range(max(self.n_shards, n_new)))
            if scaling
            else [s for s in range(n_new) if old_bounds[s] != new_bounds[s]]
        )
        entry["moved"] = moved
        # Choke point BEFORE any mutation: a fault defers the whole move.
        for s in moved:
            if s >= self.n_shards:
                continue  # not materialized yet: no device to fault
            try:
                self._check_fault("reshard", s)
            except DeviceFault as e:
                self._shard_fault(s, e)
                m.counter("reshard_deferred").add()
                entry["action"] = "deferred"
                entry["fault_shard"] = s
                self.move_log.append(entry)
                TraceEvent("ShardReshardDeferred", severity=20).detail(
                    "shard", s
                ).detail("reason", reason).log()
                return entry
        degraded = [
            s for s in moved
            if s < self.n_shards and self._breakers[s].state != "ok"
        ]
        entry["action"] = "degraded_on_mirror" if degraded else "live"
        if degraded:
            entry["degraded_shards"] = degraded
            m.counter("reshard_degraded").add()
        # Immutable cuts: nothing after this point can tear the handoff.
        snaps = [mir.snapshot() for mir in self._mirrors]
        chunk = self._mirrors[0].chunk_size
        by_bounds = {old_bounds[s]: s for s in range(self.n_shards)}
        new_mirrors: list = []
        new_stale: list = []
        new_synced: list = []
        reused = 0
        for s, (lo, hi) in enumerate(new_bounds):
            t = by_bounds.get((lo, hi))
            if t is not None:
                # Unchanged range: the mirror moves BY IDENTITY.  Its
                # device slice survives only when the index also holds
                # (same physical chip) and the mesh is not rebuilt.
                keep_dev = (not scaling) and t == s
                new_mirrors.append(self._mirrors[t])
                new_stale.append(bool(self._stale[t]) or not keep_dev)
                new_synced.append(
                    self._synced_stamp[t] if keep_dev else None
                )
                reused += 1
                continue
            parts = []
            for t2, (olo, ohi) in enumerate(old_bounds):
                if hi is not None and olo >= hi:
                    break
                if ohi is not None and ohi <= lo:
                    continue
                plo = olo if olo > lo else lo
                if ohi is None:
                    phi = hi
                elif hi is None:
                    phi = ohi
                else:
                    phi = ohi if ohi < hi else hi
                parts.append((snaps[t2], plo, phi))
            oldest = max(p[0].oldest_version for p in parts)
            new_mirrors.append(
                engine_from_handoff(parts, oldest, chunk=chunk,
                                    key_words=self.key_words)
            )
            new_stale.append(True)
            new_synced.append(None)
        # Commit: the partition flips atomically between batches.
        if scaling:
            self.n_shards = n_new
            self.mesh = Mesh(np.array(self._devices[:n_new]), (AXIS,))
            self._shardspec = NamedSharding(self.mesh, P(AXIS))
            self._steps.clear()
        self.split_keys = new
        self._mirrors = new_mirrors
        self._stale = new_stale
        self._synced_stamp = new_synced
        lo_np, hi_np = self._partition_arrays(new)
        self._lo = jax.device_put(jnp.asarray(lo_np), self._shardspec)
        self._hi = jax.device_put(jnp.asarray(hi_np), self._shardspec)
        if scaling:
            # Fresh device state at the new mesh width; every shard
            # rehydrates lazily from its repartitioned mirror.
            self._init_state(oldest_rel=0)
        m.counter("reshards").add()
        m.counter("reshard_moved_shards").add(len(moved))
        entry["reused_mirrors"] = reused
        self.move_log.append(entry)
        instant(
            "reshard",
            role="ShardedConflict",
            attrs={"seq": entry["seq"], "reason": reason,
                   "moved": len(moved), "shards": n_new},
        )
        TraceEvent("ShardReshard", severity=20).detail(
            "seq", entry["seq"]
        ).detail("reason", reason).detail("action", entry["action"]).detail(
            "moved", len(moved)
        ).detail("shards", n_new).log()
        # Flight-recorder `reshard` capture kind (ISSUE 18 satellite):
        # every COMMITTED split-point change freezes the timeseries
        # window with the move log attached, under the per-kind cooldown
        # (deferred moves are faults — the breaker path captures those).
        maybe_trigger(
            "reshard",
            detail={"seq": entry["seq"], "reason": reason,
                    "action": entry["action"], "moved": moved,
                    "shards": n_new},
            transitions=lambda: [dict(e) for e in self.move_log],
            source="resharder",
        )
        return entry


# ---------------------------------------------------------------------------
# jaxcheck entry-point registration (tools/lint/jaxir.py): the shard_map
# step is traced at a canonical 2-shard mesh on virtual CPU devices, so the
# per-shard structural invariants — no work primitive wider than ONE
# shard's history slice (a global-width op inside shard_map would show up
# as S*h_cap-sized), carried state donated, pinned shard bounds NOT
# donated, the per-batch active mask neither — hold statically before any
# multi-chip run.  ISSUE 15 extends the family to the production
# configurations: the kernelized flat step and the tiered (per-shard
# delta + shared-cadence compaction) step, each with a committed
# fingerprint.
# ---------------------------------------------------------------------------

EP_SHARDS, EP_SHARD_H, EP_SHARD_D = 2, 2048, 256


def _sharded_ep_args(tiered: bool = False):
    sds = jax.ShapeDtypeStruct
    S, kw1 = EP_SHARDS, EP_KW1
    u32, i32 = jnp.uint32, jnp.int32
    state = [
        sds((S, kw1), u32),                 # lo
        sds((S, kw1), u32),                 # hi
        sds((S,), jnp.bool_),               # active
        sds((S, kw1, EP_SHARD_H), u32),     # hkeys
        sds((S, EP_SHARD_H), i32),          # hvers
        sds((S,), i32),                     # hcount
    ]
    if tiered:
        levels = _build_max_table_np(
            np.full((EP_SHARD_H,), FLOOR_REL, np.int32)
        ).shape[0]
        state += [
            sds((S, levels, EP_SHARD_H), i32),   # maxtab
            sds((S, kw1, EP_SHARD_D), u32),      # dkeys
            sds((S, EP_SHARD_D), i32),           # dvers
            sds((S,), i32),                      # dcount
        ]
    state.append(sds((S,), i32))            # oldest
    batch = [
        sds((kw1, EP_RR), u32),             # r_begin
        sds((kw1, EP_RR), u32),             # r_end
        sds((EP_RR,), i32),                 # r_txn
        sds((EP_RR,), i32),                 # r_snap
        sds((kw1, EP_WR), u32),             # w_begin
        sds((kw1, EP_WR), u32),             # w_end
        sds((EP_WR,), i32),                 # w_txn
        sds((EP_TXN,), i32),                # t_snap
        sds((EP_TXN,), jnp.bool_),          # t_valid
        sds((), i32),                       # now_rel
        sds((), i32),                       # new_oldest_rel
    ]
    if tiered:
        batch.append(sds((), i32))          # do_major
    return tuple(state + batch)


def _sharded_ep_mesh():
    devs = jax.devices()
    if len(devs) < EP_SHARDS:
        raise RuntimeError(
            f"sharded_step entry needs >= {EP_SHARDS} devices to trace; "
            "set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "(tests/conftest.py and the jaxir CLI both do)"
        )
    return Mesh(np.array(devs[:EP_SHARDS]), (AXIS,))


def _ep_sharded_step():
    # witness=True is the canonical trace (FDB_TPU_WITNESS defaults on),
    # matching the single-device entry points.
    jitted = _make_sharded_step(
        _sharded_ep_mesh(), EP_TXN, EP_RR, EP_WR, EP_SHARD_H, witness=True
    )
    return jitted.__wrapped__, jitted, _sharded_ep_args(), {}


def _ep_sharded_step_kernels():
    """Kernelized sharded step (FDB_TPU_KERNELS): each shard's slice runs
    the fused merge-evict + streaming-search Pallas kernels.  Canonically
    traced in interpret mode (CPU analysis; on a real TPU only the
    pallas_call params differ, never the structure)."""
    jitted = _make_sharded_step(
        _sharded_ep_mesh(), EP_TXN, EP_RR, EP_WR, EP_SHARD_H,
        kernels=True, kernel_interpret=True, witness=True,
    )
    return jitted.__wrapped__, jitted, _sharded_ep_args(), {}


def _ep_sharded_step_tiered():
    """Mesh-sharded tiered step: per-shard frozen base + carried
    max-table + delta tier, one shared host-driven compaction cadence."""
    jitted = _make_sharded_step(
        _sharded_ep_mesh(), EP_TXN, EP_RR, EP_WR, EP_SHARD_H,
        tiered=True, d_cap=EP_SHARD_D, witness=True,
    )
    return jitted.__wrapped__, jitted, _sharded_ep_args(tiered=True), {}


_SHARDED_ARGS_FLAT = (
    "lo", "hi", "active", "hkeys", "hvers", "hcount", "oldest",
    "r_begin", "r_end", "r_txn", "r_snap", "w_begin", "w_end", "w_txn",
    "t_snap", "t_valid", "now_rel", "new_oldest_rel",
)

_SHARDED_ARGS_TIERED = (
    "lo", "hi", "active", "hkeys", "hvers", "hcount", "maxtab", "dkeys",
    "dvers", "dcount", "oldest",
    "r_begin", "r_end", "r_txn", "r_snap", "w_begin", "w_end", "w_txn",
    "t_snap", "t_valid", "now_rel", "new_oldest_rel", "do_major",
)

_SHARDED_BUCKETS = {
    "txn_cap": (EP_TXN, 8), "rr_cap": (EP_RR, 8), "wr_cap": (EP_WR, 8),
    "h_cap": (EP_SHARD_H, 64),
}

register_entry_point(
    "sharded_step", _ep_sharded_step,
    arg_names=_SHARDED_ARGS_FLAT,
    carried=("hkeys", "hvers", "hcount", "oldest"),
    pinned=("lo", "hi"),
    size_classes=(("H", EP_SHARD_H), ("P", 2 * (EP_RR + EP_WR)),
                  ("batch", EP_TXN)),
    h_threshold=EP_SHARD_H,
    # Per-shard width bound: the flat engine's legitimate full-width merge
    # at ONE shard's h_cap.  Anything wider means a primitive is touching
    # globally-sized (S*h_cap) data inside the shard_map body.
    work_bound=EP_SHARD_H + 4 * EP_WR,
    bucket_dims=_SHARDED_BUCKETS,
)

register_entry_point(
    "sharded_step_kernels", _ep_sharded_step_kernels,
    arg_names=_SHARDED_ARGS_FLAT,
    carried=("hkeys", "hvers", "hcount", "oldest"),
    pinned=("lo", "hi"),
    size_classes=(("H", EP_SHARD_H), ("P", 2 * (EP_RR + EP_WR)),
                  ("batch", EP_TXN)),
    h_threshold=EP_SHARD_H,
    # Same per-shard bound as the sort arm: the kernelized step keeps
    # H-sized STREAMING work but in-kernel primitives are tile-sized.
    work_bound=EP_SHARD_H + 4 * EP_WR,
    bucket_dims=_SHARDED_BUCKETS,
)

register_entry_point(
    "sharded_step_tiered", _ep_sharded_step_tiered,
    arg_names=_SHARDED_ARGS_TIERED,
    carried=("hkeys", "hvers", "hcount", "maxtab", "dkeys", "dvers",
             "dcount", "oldest"),
    pinned=("lo", "hi"),
    size_classes=(("H", EP_SHARD_H), ("P", 2 * (EP_RR + EP_WR)),
                  ("D", EP_SHARD_D), ("batch", EP_TXN)),
    h_threshold=EP_SHARD_H,
    # Steady state stays delta-bounded per shard: the same
    # compaction-gating contract as the single-device tiered step.
    compaction_gated=True,
    work_bound=EP_SHARD_H + EP_SHARD_D + 4 * EP_WR,
    bucket_dims=dict(_SHARDED_BUCKETS, d_cap=(EP_SHARD_D, 64)),
)
