"""Cluster-wide failure monitoring: CC-hosted detector, delta broadcast.

Ref: the cluster controller's failure detection
(ClusterController.actor.cpp:1257) pushes delta-compressed
SystemFailureStatus lists to every process, and
fdbclient/FailureMonitorClient.actor.cpp applies them into the local
IFailureMonitor — so clients and peers stop routing to a dead endpoint
WITHOUT first eating a per-request timeout on it.

Rebuild shape: the detector lives on the acting cluster controller (fed by
its worker ping loop); consumers long-poll `failure_monitor` with the last
version they saw and receive either the deltas since then or a full
snapshot (when the bounded history has been trimmed past them).  The
client side (`run_failure_monitor_client`) folds updates into a plain
dict consulted by loadBalance ordering.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from ..flow.asyncvar import AsyncVar
from ..flow.error import FdbError
from ..rpc.stream import RequestStream

HISTORY_LIMIT = 512
LONG_POLL_TIMEOUT = 1.0


@dataclass
class FailureMonitorReply:
    version: int = 0
    full: bool = False  # states is a complete snapshot, not a delta
    states: List[Tuple[str, bool]] = field(default_factory=list)


class FailureDetector:
    """CC-side state + the broadcast stream (delta-compressed)."""

    def __init__(self, process):
        self.process = process
        self.states: Dict[str, bool] = {}  # addr -> failed
        self.version = AsyncVar(0)
        self.history: List[Tuple[int, str, bool]] = []
        self._stream = RequestStream(
            process, "failure_monitor", well_known=True
        )
        process.spawn_observed(self._serve(), "failure_monitor_serve")

    def ref(self):
        return self._stream.ref()

    def set_state(self, addr: str, failed: bool):
        if self.states.get(addr, False) == failed:
            return
        v = self.version.get() + 1
        self.states[addr] = failed
        self.history.append((v, addr, failed))
        if len(self.history) > HISTORY_LIMIT:
            del self.history[: len(self.history) - HISTORY_LIMIT]
        self.version.set(v)

    async def _serve(self):
        from ..flow.eventloop import first_of

        loop = self.process.network.loop
        while True:
            known, reply = await self._stream.pop()
            known = known or 0
            if known >= self.version.get():
                # Long-poll: park until something changes (bounded so a
                # silent cluster still heartbeats liveness to consumers).
                waiter = self.process.spawn(
                    self._wait_change(known), "fm_wait"
                )
                await first_of(waiter, loop.delay(LONG_POLL_TIMEOUT))
                if not waiter.is_ready():
                    waiter.cancel()
            v = self.version.get()
            oldest = self.history[0][0] if self.history else v + 1
            if known + 1 >= oldest:
                deltas = [
                    (addr, failed)
                    for hv, addr, failed in self.history
                    if hv > known
                ]
                reply.send(FailureMonitorReply(version=v, states=deltas))
            else:
                # History trimmed past this consumer: full snapshot.
                reply.send(
                    FailureMonitorReply(
                        version=v,
                        full=True,
                        states=sorted(self.states.items()),
                    )
                )

    async def _wait_change(self, known: int):
        while self.version.get() <= known:
            await self.version.on_change()


async def run_failure_monitor_client(db):
    """Client/peer-side actor: keep `db.failure_states` current from the
    acting CC's detector (ref: failureMonitorClientLoop,
    FailureMonitorClient.actor.cpp).  Re-resolves the stream ref from
    ClientDBInfo each round so CC failover is transparent."""
    loop = db.process.network.loop
    known = 0
    while True:
        info = db.info_var.get() if db.info_var is not None else None
        fm = getattr(info, "failure_monitor", None) if info else None
        if fm is None:
            await loop.delay(0.25)
            continue
        try:
            rep = await fm.get_reply(db.process, known)
        except FdbError:
            # CC died: forget refs, wait for the next generation's info.
            known = 0
            await loop.delay(0.25)
            continue
        if rep.full:
            db.failure_states.clear()
        for addr, failed in rep.states:
            db.failure_states[addr] = failed
        known = rep.version
