"""Resolver role: MVCC conflict decision per version window.

Ref: Resolver.actor.cpp resolveBatch :71 — per-proxy ordering by prevVersion
(:104-115 via NotifiedVersion), ConflictBatch over the ConflictSet
(:140-153), window GC at version - MAX_WRITE_TRANSACTION_LIFE_VERSIONS
(:153), per-proxy reply cache (`outstandingBatches` :125-128, duplicate
reply :240-256) and state-transaction retention for the other proxies
(`recentStateTransactions` :170-190).  The conflict backend is pluggable
(conflict.api.ConflictSet): "cpu", "jax", "hybrid", or a mesh-sharded set
from parallel/ — the north-star swap point (BASELINE.json).

Async offload (ISSUE 11; ref: Resolver.actor.cpp's pipelined
yieldedFuture resolve loop): with a device backend and
FDB_TPU_PIPELINE_DEPTH > 1, a batch's device dispatch advances the
prevVersion chain immediately and its host-side completion (verdict
sync, mirror apply, reply) is deferred into a bounded double buffer —
while the device resolves batch N, the host applies batch N-1's
verdicts to the chunked mirror and packs/encodes batch N+1.  Verdict
streams are bit-identical to the synchronous path (depth 1): the
carried device history advances in commit order either way.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from ..conflict.api import ConflictSet
from ..conflict.types import COMMITTED
from ..flow.asyncvar import NotifiedVersion
from ..flow.hotpath import hot_path
from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream
from .interfaces import (
    ResolutionMetricsReply,
    ResolutionSplitRequest,
    ResolverSignalsReply,
    ResolveTransactionBatchReply,
    ResolveTransactionBatchRequest,
    ResolverInterface,
)

# Key-frequency sample bounds (ref: TransientStorageMetricSample iopsSample
# Resolver.actor.cpp:146-151 — a decaying sample of conflict-range begin
# keys, queried by the master's split balancing).
SAMPLE_MAX_KEYS = 2000


@dataclass
class _ProxyInfo:
    """Ref: ProxyRequestsInfo Resolver.actor.cpp — lastVersion + the
    outstanding reply cache keyed by version."""

    last_version: int = 0
    outstanding: Dict[int, ResolveTransactionBatchReply] = field(
        default_factory=dict
    )


class _ParkedResolve:
    """Resolver-side context of one batch parked in the double-buffered
    pipeline (ISSUE 11): everything the completion phase — verdict
    bookkeeping, state-txn retention, reply — needs, carried from the
    submit phase.  Completions run strictly in version order (the deque
    order), by whichever handler drives the pump."""

    __slots__ = ("entry", "req", "reply", "first_unseen", "t_enter",
                 "finished", "_promise", "span")

    def __init__(self, entry, req, reply, first_unseen: int, t_enter: float,
                 span=None):
        self.entry = entry
        self.req = req
        self.reply = reply
        self.first_unseen = first_unseen
        self.t_enter = t_enter
        self.finished = False
        self._promise = None
        self.span = span  # the batch's resolve_batch span (ISSUE 12)

    @property
    def future(self):
        """Fires when this context's resolve is FINISHED (reply sent)."""
        if self._promise is None:
            from ..flow.future import Promise

            self._promise = Promise()
            if self.finished:
                self._promise.send(None)
        return self._promise.future

    def _mark_finished(self):
        self.finished = True
        if self._promise is not None and not self._promise.is_set():
            self._promise.send(None)


class Resolver:
    def __init__(
        self,
        process: SimProcess,
        backend: str = "cpu",
        epoch_begin_version: int = 0,
        conflict_set: ConflictSet = None,
        epoch: int = 0,
        n_proxies: int = 1,
    ):
        self.process = process
        self.epoch = epoch
        self.n_proxies = n_proxies
        self.conflicts = conflict_set or ConflictSet(
            backend=backend, oldest_version=epoch_begin_version
        )
        self.version = NotifiedVersion(epoch_begin_version)
        self.total_resolved = 0
        # Committed state transactions by version, retained until every
        # proxy's lastVersion has passed them (ref :170-224).
        self._recent_state_txns: Dict[int, list] = {}
        self._proxy_info: Dict[str, _ProxyInfo] = {}
        self._epoch_begin = epoch_begin_version
        # Decaying first-key frequency sample + op counter for split
        # balancing (ref: iopsSample Resolver.actor.cpp:146-151,
        # ResolutionMetricsRequest/SplitRequest service :276-284).
        self._key_sample: Dict[bytes, int] = {}
        self._metric_ops = 0
        self._stream = RequestStream(process, "resolve", well_known=True)
        self._metrics_stream = RequestStream(
            process, "resolution_metrics", well_known=True
        )
        self._split_stream = RequestStream(
            process, "resolution_split", well_known=True
        )
        self._signals_stream = RequestStream(
            process, "resolver_signals", well_known=True
        )
        # Telemetry registry (ref: Resolver.actor.cpp's resolverCounters +
        # traceCounters): batch sizes, per-verdict counts, and the queue
        # wait the prevVersion reorder imposes.  The loop rng enables
        # histogram percentiles deterministically.
        from ..flow.metrics import MetricsRegistry, emit_metrics

        loop = process.network.loop
        self.metrics = MetricsRegistry(
            f"Resolver.{process.name}", rng=loop.rng
        )
        for _c in ("batches", "transactions", "committed", "conflicted",
                   "too_old", "cache_hits", "stale_epoch",
                   "degraded_batches", "witness_aborts",
                   "contention_spikes"):
            self.metrics.counter(_c)  # pre-create: snapshots list them all
        # Conflict-witness telemetry (ISSUE 12 satellite, the
        # observability seed of ROADMAP item 4): per-batch aborted-txn
        # counts plus a bounded top-K of the key ranges aborted
        # transactions were contending on.  Phase 1 computes the precise
        # range each loser lost to and throws it away on device; until
        # that surfaces through the reply (item 4 proper), the aborted
        # txns' own first conflict ranges are the honest host-side
        # approximation of where contention lives.
        self._witness_ranges: Dict[tuple, int] = {}
        self.metrics.gauge("conflict_witness_topk").set("[]")
        # End-to-end provenance (ISSUE 17): with FDB_TPU_WITNESS on, the
        # conflict engines report the precise (conflicting version, losing
        # read range) per abort, the reply carries it to the proxy, and
        # the contended-range sample above records the EXACT range each
        # loser lost on instead of the first-write-range approximation.
        from ..flow.knobs import g_env as _g_env

        self._witness_on = _g_env.get("FDB_TPU_WITNESS") not in ("", "0")
        # Contended-range decay advances once per
        # resolver_witness_decay_batches CONFLICT-bearing batches — a
        # batch counter, deliberately not a timer, so idle virtual time
        # never drains the top-K (pinned by test_witness_decay).
        self._witness_batches = 0
        # Per-batch abort timeline (the contention explorer's raw feed):
        # (version, n_txn, n_aborted, [[begin_hex, end_hex, count], ...]).
        from collections import deque as _deque

        self._contention_ring = _deque(
            maxlen=int(g_knobs.server.resolver_contention_ring)
        )
        # Consecutive batches at/above the spike abort fraction.
        self._contention_streak = 0
        # Set once a raw device conflict set faulted and its state was
        # exported host-side: the CPU engine then serves every later batch
        # of this role's life (see _retry_on_cpu).
        self._cpu_takeover = None
        # Admission-control signals (ISSUE 8): batches in flight or parked
        # on the prevVersion chain, and a sliding window of recent resolve
        # durations (virtual seconds, entry -> reply).  A bounded window —
        # not the cumulative histogram — so the ratekeeper's spring sees a
        # latency SPIKE instead of a lifetime-diluted reservoir.
        self._inflight = 0
        from collections import deque

        self._recent_resolve = deque(maxlen=64)
        self.metrics.gauge("queue_depth").set(0)
        # Double-buffered pipeline (ISSUE 11): contexts of batches
        # dispatched to the device whose host-side completion (verdict
        # sync, mirror apply, reply) is deferred, oldest first.  Active
        # only when the conflict set supports pipelining and
        # FDB_TPU_PIPELINE_DEPTH > 1; depth 1 keeps today's synchronous
        # path bit-for-bit.
        self._pipe_ctx = deque()
        self._flush_streak = 0  # consecutive idle-flush completions
        self._pipeline_on = (
            getattr(self.conflicts, "pipeline_depth", 1) > 1
            and callable(getattr(self.conflicts, "pipeline_submit", None))
            and getattr(self.conflicts, "_jax", None) is not None
        )
        self.metrics.gauge("pipeline_occupancy").set(0)
        for _c in ("pipeline_device_stalls", "pipeline_host_stalls"):
            self.metrics.counter(_c)  # pre-create: snapshots list them all
        self.metrics.histogram("pipeline_inflight_depth")
        # Pipeline overlap efficiency (ISSUE 12): overlapped device time
        # / total device time over completed device in-flight spans,
        # measured on the span hub's EVENT-SEQUENCE clock (deterministic:
        # virtual time does not advance during synchronous host work, so
        # seq is the clock that still shows batch N+1's dispatch running
        # inside batch N's device window).  Incremental union: device
        # spans complete in dispatch order, so one high-water mark
        # suffices.  The wall twin goes through record_wall only.
        self.metrics.gauge("pipeline_overlap_efficiency").set(0.0)
        self._dev_seq_total = 0
        self._dev_seq_union = 0
        self._dev_seq_hwm = None
        self._dev_wall_hwm = None
        # Host fraction (ISSUE 19): seq extent of the host phases
        # (encode + mirror_apply + readback, accumulated by the engine)
        # over host + device extent — the deterministic twin of the
        # wall-clock host-path share the hostpath bench arm measures.
        self.metrics.gauge("host_fraction").set(0.0)
        process.spawn_observed(self._serve(), "resolver")
        process.spawn_observed(self._serve_metrics(), "resolver_metrics")
        process.spawn_observed(self._serve_split(), "resolver_split")
        process.spawn_observed(self._serve_signals(), "resolver_signals")
        process.spawn(
            emit_metrics(self.metrics, process), "resolver_metrics_emit"
        )
        # Time-series sampler actors (ISSUE 10): bounded delta history of
        # this role's registry — and of the device engine's kernel
        # telemetry when one is live — into the global hub, the window
        # the flight recorder freezes on a trigger.
        from ..flow.timeseries import spawn_sampler

        spawn_sampler(process, self.metrics.name, self.metrics)
        dev = getattr(self.conflicts, "_jax", None)
        if dev is not None:
            spawn_sampler(
                process, f"JaxConflict.{process.name}", dev.metrics
            )
        elif getattr(self.conflicts, "metrics", None) is not None:
            # First-class raw conflict set with its own registry (the
            # mesh-sharded set, ISSUE 15): its per-shard breaker walk
            # rides the same time-series rings the flight recorder
            # freezes on a shard-breaker open.
            spawn_sampler(
                process,
                f"{self.conflicts.metrics.name}.{process.name}",
                self.conflicts.metrics,
            )
        # Mirror consistency-check actor (ISSUE 9): periodically diff a
        # live mirror snapshot against the device's exported state;
        # confirmed divergence opens the breaker (ConflictSet.mirror_check
        # counts/traces and degrades).  Deterministic: virtual-time
        # cadence, synchronous check — same seed, same transition log.
        from ..flow.knobs import g_env

        period = float(g_env.get("FDB_TPU_MIRROR_CHECK_SECONDS"))
        if period > 0 and callable(
            getattr(self.conflicts, "mirror_check", None)
        ):
            process.spawn_observed(
                self._mirror_check_loop(period), "resolver_mirror_check"
            )
        # Shard-balancer actor (ISSUE 18): periodically evaluate per-shard
        # occupancy + decayed contention skew and migrate split points
        # live (ShardedJaxConflictSet.reshard).  Deterministic: the tick
        # is virtual-time, the evaluation synchronous, the inputs
        # (occupancy, witness sample, queue-depth pressure) seed-stable —
        # same seed, byte-identical decision log.
        self.shard_balancer = None
        bal_period = float(g_env.get("FDB_TPU_SHARD_BALANCE_SECONDS"))
        if bal_period > 0 and callable(
            getattr(self.conflicts, "reshard", None)
        ):
            from .resolver_balancer import ShardBalancer

            self.shard_balancer = ShardBalancer(
                self.conflicts, load_fn=self._shard_load_sample
            )
            process.spawn_observed(
                self._shard_balance_loop(bal_period), "resolver_shard_balance"
            )

    def interface(self) -> ResolverInterface:
        return ResolverInterface(
            resolve=self._stream.ref(),
            metrics=self._metrics_stream.ref(),
            split=self._split_stream.ref(),
            signals=self._signals_stream.ref(),
        )

    @property
    def queue_depth(self) -> int:
        """Resolve batches in flight or parked on the prevVersion chain."""
        return self._inflight

    def resolve_p99_recent(self) -> float:
        """Exact p99 over the recent resolve-duration window (virtual
        seconds); 0.0 before any batch completed."""
        from ..flow.latency_chain import percentile

        return percentile(list(self._recent_resolve), 0.99) or 0.0

    def signal_snapshot(self) -> ResolverSignalsReply:
        """The admission-control probe (served by the `signals` stream and
        read directly by in-process ratekeepers).  All O(1)/O(window) —
        never O(history rows)."""
        bs = getattr(self.conflicts, "backend_signal", None)
        sig = bs() if callable(bs) else {}
        state = sig.get("backend_state", "ok")
        mirror_tps = sig.get("cpu_mirror_tps", 0.0)
        if self._cpu_takeover is not None:
            state = "degraded"  # permanent host takeover (raw device set)
        return ResolverSignalsReply(
            queue_depth=self._inflight,
            resolve_p99=self.resolve_p99_recent(),
            backend_state=state,
            cpu_mirror_tps=mirror_tps,
            degraded_batches=int(
                self.metrics.counter("degraded_batches").value
            ),
            mirror_divergence=sig.get("mirror_divergence", 0),
            # Shard-granular detail (ISSUE 15): 0/0 unless the conflict
            # set is mesh-sharded with per-shard breakers.
            shards_total=sig.get("shards_total", 0),
            shards_degraded=sig.get("shards_degraded", 0),
        )

    async def _serve_signals(self):
        while True:
            _req, reply = await self._signals_stream.pop()
            reply.send(self.signal_snapshot())

    async def _mirror_check_loop(self, period: float):
        """Run ConflictSet.mirror_check() every `period` virtual seconds.
        The check itself is synchronous (no await inside), so it can
        never observe a half-applied batch; a host-only backend returns
        None on the first call and the actor retires.  Parked pipelined
        batches are completed first (ISSUE 11): under sustained traffic
        the double buffer holds an entry almost always, and the
        divergence checker must not starve behind it — the drain just
        finishes deferred host work (replies included) a little early,
        in order, so it is always safe."""
        loop = self.process.network.loop
        while True:
            await loop.delay(period)
            if self._pipe_ctx:
                self._pipeline_pump(0, "drain")
            if self.conflicts.mirror_check() is None:
                return  # no device engine behind this conflict set

    def _shard_load_sample(self):
        """Per-shard contention load from the decayed witness-range
        sample (ISSUE 12): each contended range is charged to the shard
        owning its begin key under the CURRENT partition.  Seed-stable —
        the sample itself is deterministic and the mapping is a pure
        function of it plus split_keys."""
        from bisect import bisect_right

        cs = self.conflicts
        ks = [bytes(k) for k in cs.split_keys]
        loads = [0] * cs.n_shards
        for (begin, _end), hits in self._witness_ranges.items():
            loads[bisect_right(ks, bytes(begin))] += int(hits)
        return loads

    async def _shard_balance_loop(self, period: float):
        """Tick the ShardBalancer every `period` virtual seconds.  The
        evaluation (and any reshard it commits) is synchronous, so a
        boundary can never move under a batch mid-resolve — batches see
        the old partition or the new one, never a torn one.  Pressure is
        the queue-depth fraction of the batch-concurrency target, the
        same signal the ratekeeper throttles on."""
        loop = self.process.network.loop
        while True:
            await loop.delay(period)
            if self._pipe_ctx:
                self._pipeline_pump(0, "drain")
            pressure = min(1.0, self._inflight / 16.0)
            self.shard_balancer.evaluate(pressure=pressure)

    async def _serve(self):
        while True:
            req, reply = await self._stream.pop()
            # Owned spawn: per-request handlers can park indefinitely (the
            # prevVersion ordering wait) and MUST die with the role —
            # teardown cancels owned tasks so their held replies break
            # instead of wedging callers of a dead generation forever.
            from ..rpc.stream import spawn_owned

            spawn_owned(self, self._resolve_one(req, reply), "resolve_batch")

    def _sample(self, tr):
        for rng in tr.read_ranges:
            self._bump(rng[0])
        for rng in tr.write_ranges:
            self._bump(rng[0])
        self._metric_ops += len(tr.read_ranges) + len(tr.write_ranges)

    def _bump(self, key: bytes):
        self._key_sample[key] = self._key_sample.get(key, 0) + 1
        if len(self._key_sample) > SAMPLE_MAX_KEYS:
            # Decay: halve every count, drop the zeros (the transient-sample
            # expiry analog; keeps hot keys, sheds one-offs).  Under wide
            # uniform load halving alone may not shrink the dict (all
            # counts >= 2) — evict the coldest entries down to 3/4 capacity
            # so the rebuild amortizes to once per cap/4 inserts instead of
            # running on every insert of the hot path.
            self._key_sample = {
                k: v // 2 for k, v in self._key_sample.items() if v >= 2
            }
            target = SAMPLE_MAX_KEYS * 3 // 4
            if len(self._key_sample) > target:
                import heapq

                for k, _v in heapq.nsmallest(
                    len(self._key_sample) - target,
                    self._key_sample.items(),
                    key=lambda kv: (kv[1], kv[0]),
                ):
                    del self._key_sample[k]

    def _retry_on_cpu(self, fault, req):
        """Re-run a device-faulted batch on a host engine built from the
        conflict set's pre-batch state (injected faults raise BEFORE any
        device state mutates, so store_to exports exactly the history the
        batch must be decided against — verdicts stay bit-identical; a
        REAL XLA fault may have invalidated donated buffers, in which
        case store_to raises and the actor dies loudly — recovery then
        re-recruits, which beats deciding against corrupt history).  The
        CPU engine takes over for the rest of this role's life: handing
        state back to a faulting device mid-epoch risks a second
        interruption with no authoritative copy."""
        from ..conflict.engine_cpu import CpuConflictSet
        from ..flow.trace import TraceEvent

        store = getattr(self.conflicts, "store_to", None)
        if store is None:
            raise fault  # nothing to retry against: let the actor die loudly
        TraceEvent("ResolverDeviceFaultRetry", severity=20).detail(
            "error", type(fault).__name__
        ).detail("site", getattr(fault, "site", "")).detail(
            "version", req.version
        ).log()
        cpu = CpuConflictSet()
        store(cpu)
        self._cpu_takeover = cpu
        window = g_knobs.server.max_write_transaction_life_versions
        return cpu.detect(
            req.transactions,
            now=req.version,
            new_oldest_version=req.version - window,
        )

    async def _serve_metrics(self):
        while True:
            _req, reply = await self._metrics_stream.pop()
            reply.send(ResolutionMetricsReply(ops=self._metric_ops))
            self._metric_ops = 0

    async def _serve_split(self):
        while True:
            req, reply = await self._split_stream.pop()
            reply.send(self._split_key(req))

    def _split_key(self, req: ResolutionSplitRequest):
        """The sampled key at `fraction` of this resolver's mass within
        [begin, end); None when the sample is too thin to split."""
        keys = sorted(
            k
            for k in self._key_sample
            if k >= req.begin and (req.end is None or k < req.end)
        )
        total = sum(self._key_sample[k] for k in keys)
        if total == 0 or len(keys) < 2:
            return None
        # A boundary at key k puts the mass of every key < k on the left;
        # pick the boundary whose LEFT mass is closest to fraction*total.
        # (Crossing-key-inclusive accumulation would dump the crossing
        # key's whole mass — possibly most of the range — on the donated
        # side and overshoot wildly for skewed samples.)
        target = total * req.fraction
        acc = 0
        best_key, best_err = None, None
        for idx, k in enumerate(keys):
            if idx > 0:  # boundary at keys[0] == empty left side: no-op
                err = abs(acc - target)
                if best_err is None or err < best_err:
                    best_key, best_err = k, err
            acc += self._key_sample[k]
        return best_key

    async def _resolve_one(self, req: ResolveTransactionBatchRequest, reply):
        if req.epoch != self.epoch:
            self.metrics.counter("stale_epoch").add()
            reply.send_error("operation_failed")  # stale generation's proxy
            return
        # Queue-depth accounting (ISSUE 8): a batch counts from arrival —
        # including time parked on the prevVersion chain, which is exactly
        # where an overloaded resolver's backlog lives — until its reply.
        loop = self.process.network.loop
        t_enter = loop.now()
        self._inflight += 1
        self.metrics.gauge("queue_depth").set(self._inflight)
        try:
            await self._resolve_one_impl(req, reply, t_enter)
        finally:
            self._inflight -= 1
            self.metrics.gauge("queue_depth").set(self._inflight)

    async def _resolve_one_impl(
        self, req: ResolveTransactionBatchRequest, reply, t_enter: float
    ):
        from ..flow.buggify import buggify
        from ..flow.trace import trace_batch

        trace_batch(
            "CommitDebug", "Resolver.resolveBatch.Before", req.debug_id
        )
        if buggify("resolver_delay"):
            # BUGGIFY: batches arrive out of order — exercises the
            # prevVersion chain wait below (ref :104-115).
            loop = self.process.network.loop
            await loop.delay(loop.rng.random01() * 0.02)
        # Order batches by the sequencer's prevVersion chain: a batch may
        # arrive before its predecessor (ref :104-115).
        await self.version.when_at_least(req.prev_version)
        if self.version.get() != req.prev_version:
            # Duplicate/replayed batch (proxy retry after timeout): answer
            # from the per-proxy reply cache (ref :240-256).  The chain
            # advances at DISPATCH in pipelined mode, so the original may
            # still be parked — wait out its completion, then the cache
            # has the reply.
            pinfo = self._proxy_info.get(req.proxy_id)
            cached = pinfo.outstanding.get(req.version) if pinfo else None
            if cached is None:
                parked = next(
                    (c for c in self._pipe_ctx
                     if c.req.proxy_id == req.proxy_id
                     and c.req.version == req.version),
                    None,
                )
                if parked is not None:
                    await parked.future
                    pinfo = self._proxy_info.get(req.proxy_id)
                    cached = (
                        pinfo.outstanding.get(req.version) if pinfo else None
                    )
            if cached is not None:
                self.metrics.counter("cache_hits").add()
                reply.send(cached)
            else:
                reply.send_error("operation_failed")
            return

        pinfo = self._proxy_info.setdefault(
            req.proxy_id, _ProxyInfo(last_version=self._epoch_begin)
        )
        # The proxy has received everything through last_received_version;
        # drop those cached replies (ref :126-128).
        for v in [
            v for v in pinfo.outstanding if v <= req.last_received_version
        ]:
            del pinfo.outstanding[v]
        first_unseen = pinfo.last_version + 1
        pinfo.last_version = req.version

        for tr in req.transactions:
            self._sample(tr)
        window = g_knobs.server.max_write_transaction_life_versions
        # Batch span (ISSUE 12): arrival-ordered root of this batch's
        # stage tree (encode/dispatch/device/sync/apply/reply children).
        # Detached — it outlives awaits on the pipelined path — and ended
        # by the shared completion (_complete_resolve).
        from ..flow.spans import begin_span, use_span

        bspan = begin_span(
            "resolve_batch", role=self.metrics.name,
            attrs={"version": req.version,
                   "n_txn": len(req.transactions),
                   "pipelined": int(
                       self._pipeline_on and self._cpu_takeover is None
                   )},
        )
        if self._pipeline_on and self._cpu_takeover is None:
            # ISSUE 11: the double-buffered async offload path (ref: the
            # pipelined yieldedFuture resolve loop of Resolver.actor.cpp).
            await self._resolve_pipelined(
                req, reply, first_unseen, t_enter, window, bspan
            )
            return
        conflicts = self._cpu_takeover or self.conflicts
        batch = conflicts.new_batch() if self._cpu_takeover is None else None
        if batch is not None:
            for tr in req.transactions:
                batch.add_transaction(tr)
        degraded = False
        if batch is not None:
            from ..conflict.device_faults import DeviceFault

            try:
                with use_span(bspan):  # stage spans parent to the batch
                    statuses = batch.detect_conflicts(
                        now=req.version,
                        new_oldest_version=req.version - window,
                    )
            except DeviceFault as e:
                # Last-resort host retry, same resolve call — no error may
                # escape to the proxy (ConflictSet's breaker normally
                # absorbs faults below this; raw device sets, e.g. the
                # mesh-sharded one, surface them here).
                statuses = self._retry_on_cpu(e, req)
                degraded = True
        else:
            statuses = self._cpu_takeover.detect(
                req.transactions,
                now=req.version,
                new_oldest_version=req.version - window,
            )
            degraded = True  # permanent host takeover: still degraded
        consume = getattr(conflicts, "consume_degraded", None)
        if consume is not None and consume():
            degraded = True
        # Provenance: whichever engine actually decided the batch holds
        # its witness — the CPU takeover after a device fault (set inside
        # _retry_on_cpu), else the serving conflict set.
        witness = self._batch_witness(
            self._cpu_takeover or conflicts, len(statuses)
        )
        # version.set before the shared completion (the pipelined path
        # sets it at dispatch): NotifiedVersion wakes waiters through the
        # loop's ready queue, never synchronously, so no actor can
        # interleave before this handler's reply either way.
        self.version.set(req.version)
        self._complete_resolve(
            req, reply, statuses, degraded, first_unseen, t_enter,
            span=bspan, witness=witness,
        )

    def _batch_witness(self, engine, n: int) -> list:
        """Per-txn abort witnesses for the batch `engine` just decided
        (ISSUE 17), or [] when provenance is off or the engine predates
        it.  Length is pinned to the batch so a stale list from an
        earlier batch can never be attributed to this one."""
        if not self._witness_on:
            return []
        wit = list(getattr(engine, "last_witness", []) or [])
        return wit if len(wit) == n else []

    @hot_path(bound="batch")
    def _complete_resolve(
        self, req, reply, statuses, degraded: bool, first_unseen: int,
        t_enter: float, span=None, witness=None,
    ):
        """Post-verdict completion shared by the synchronous path and the
        pipeline's _finish_resolve — verdict accounting, state-txn
        retention + reply-cache insert, GC, trace, the latency window,
        and the reply itself live in ONE place so the two paths can
        never drift.  `span` is the batch's resolve_batch span: the
        reply child span nests under it and it is ENDED here (the one
        place both paths funnel through)."""
        from ..conflict.types import CONFLICT, TOO_OLD
        from ..flow.trace import trace_batch

        m = self.metrics
        if degraded:
            m.counter("degraded_batches").add()
            m.histogram("degraded_batch_size").add(len(req.transactions))
            trace_batch(
                "CommitDebug",
                "Resolver.resolveBatch.DegradedRetry",
                req.debug_id,
            )
        self.total_resolved += len(statuses)
        # Feed the registry: batch size + per-verdict counts (the conflict
        # rate "The Transactional Conflict Problem" trades against
        # throughput).
        n_conflicted = sum(1 for s in statuses if s == CONFLICT)
        m.counter("batches").add()
        m.counter("transactions").add(len(statuses))
        m.histogram("batch_size").add(len(statuses))
        m.counter("committed").add(sum(1 for s in statuses if s == COMMITTED))
        m.counter("conflicted").add(n_conflicted)
        m.counter("too_old").add(sum(1 for s in statuses if s == TOO_OLD))
        # Conflict-witness counters (ISSUE 12 satellite): aborted-txn
        # count per batch + the contended key ranges (see __init__).
        if n_conflicted:
            m.counter("witness_aborts").add(n_conflicted)
            m.histogram("aborted_per_batch").add(n_conflicted)
            self._witness_record(
                req.transactions, statuses, witness, req.version
            )
        # Sustained-contention black box: consecutive batches whose abort
        # fraction clears the spike ratio arm the flight recorder; one
        # sub-threshold batch disarms it.  Same cooldown/reset discipline
        # as the pipeline-stall trigger — only an ACTUAL capture resets
        # the streak, so a cooldown-suppressed attempt retries next batch.
        if statuses:
            sk = g_knobs.server
            if n_conflicted >= sk.resolver_contention_spike_ratio * len(
                statuses
            ):
                self._contention_streak += 1
                if (
                    self._contention_streak
                    >= sk.resolver_contention_spike_batches
                ):
                    from ..flow.flight_recorder import maybe_trigger

                    captured = maybe_trigger(
                        "contention_spike",
                        detail={
                            "streak": self._contention_streak,
                            "version": req.version,
                            "aborted": n_conflicted,
                            "batch": len(statuses),
                            "topk": self.conflict_witness()["topk"],
                        },
                        source=self.metrics.name,
                    )
                    if captured is not None:
                        m.counter("contention_spikes").add()
                        self._contention_streak = 0
            else:
                self._contention_streak = 0

        # Retain this batch's state transactions with their verdicts so the
        # other proxies' next batches learn them (ref :170-181).
        if req.state_txns:
            self._recent_state_txns[req.version] = [
                (statuses[t] == COMMITTED, muts) for t, muts in req.state_txns
            ]
        out = ResolveTransactionBatchReply(
            committed=statuses,
            witnesses=list(witness) if witness else [],
            degraded=degraded,
            state_mutations=[
                (v, self._recent_state_txns[v])
                for v in sorted(self._recent_state_txns)
                if first_unseen <= v < req.version
            ],
        )
        pinfo = self._proxy_info[req.proxy_id]
        pinfo.outstanding[req.version] = out

        # GC retained state txns below every proxy's lastVersion — only once
        # all proxies have checked in, else an unseen proxy could miss state
        # (ref :196-218 requiring proxyInfoMap complete).
        if len(self._proxy_info) >= self.n_proxies:
            oldest = min(p.last_version for p in self._proxy_info.values())
            # last_version advances at SUBMIT in pipelined mode, so a
            # still-parked batch may have bumped its proxy past state txns
            # its own reply (built at completion) still needs: clamp the
            # GC below the oldest parked context's first_unseen.
            # Retaining longer is always safe; _pipe_ctx is empty on the
            # synchronous path, where bump, reply build, and GC run with
            # no await between them.
            if self._pipe_ctx:
                oldest = min(
                    oldest,
                    min(c.first_unseen for c in self._pipe_ctx) - 1,
                )
            for v in [v for v in self._recent_state_txns if v <= oldest]:
                del self._recent_state_txns[v]

        from ..flow.spans import begin_span, use_span

        with use_span(span):
            with begin_span("reply", attrs={"version": req.version}):
                trace_batch(
                    "CommitDebug", "Resolver.resolveBatch.After",
                    req.debug_id,
                )
                # Resolve latency (arrival -> reply, virtual seconds):
                # the sliding window the ratekeeper's resolve_latency
                # spring reads, plus the cumulative histogram for
                # status/metrics.  Real resolves only — cache-hit/stale
                # replies never reach here and never dilute it.
                dt = self.process.network.loop.now() - t_enter
                self._recent_resolve.append(dt)
                m.histogram("resolve_seconds").add(dt)
                reply.send(out)
        if span is not None:
            span.end(attrs={"degraded": int(degraded),
                            "aborted": n_conflicted})

    WITNESS_MAX_RANGES = 512  # bounded contended-range sample (decayed)
    WITNESS_TOP_K = 8

    def _witness_record(self, txns, statuses, witness=None, version=0):
        """Bump the contended-range sample with every aborted txn's losing
        range — the PRECISE read range its witness names (ISSUE 17) when
        provenance is on, else the first-write-range approximation the
        pre-witness sample used (first-committer-wins means a loser's own
        write range is where it usually collided).  Decays like the
        split-balancer key sample so hot ranges survive and one-offs
        shed; the decay clock is REAL batches only — once per
        resolver_witness_decay_batches calls here, plus the overflow
        halving — never a timer, so a quiescent cluster's top-K holds
        byte-identical between soak phases.  Publishes the top-K as a
        canonical-JSON gauge and appends this batch's per-range abort
        counts to the contention timeline ring — both deterministic, so
        they ride snapshots/timeseries/soak reports without breaking
        byte identity."""
        from ..conflict.types import CONFLICT

        w = self._witness_ranges
        batch_ranges: Dict[tuple, int] = {}
        n_aborted = 0
        for t, (tr, s) in enumerate(zip(txns, statuses)):
            if s != CONFLICT:
                continue
            n_aborted += 1
            wtn = witness[t] if witness and t < len(witness) else None
            if wtn is not None and wtn[1] < len(tr.read_ranges):
                rng = tr.read_ranges[wtn[1]]
            else:
                ranges = tr.write_ranges or tr.read_ranges
                if not ranges:
                    continue
                rng = ranges[0]
            key = (rng[0], rng[1])
            w[key] = w.get(key, 0) + 1
            batch_ranges[key] = batch_ranges.get(key, 0) + 1
        self._witness_batches += 1
        decay_every = int(g_knobs.server.resolver_witness_decay_batches)
        if (decay_every > 0 and self._witness_batches % decay_every == 0) \
                or len(w) > self.WITNESS_MAX_RANGES:
            w = {k: v // 2 for k, v in w.items() if v >= 2}
            self._witness_ranges = w
        import json as _json

        top = sorted(w.items(), key=lambda kv: (-kv[1], kv[0]))
        top = top[: self.WITNESS_TOP_K]
        self.metrics.gauge("conflict_witness_topk").set(
            _json.dumps(
                [[b.hex(), e.hex(), n] for (b, e), n in top],
                separators=(",", ":"),
            )
        )
        self._contention_ring.append((
            int(version),
            len(statuses),
            n_aborted,
            sorted(
                [[b.hex(), e.hex(), n] for (b, e), n in batch_ranges.items()],
                key=lambda r: (-r[2], r[0], r[1]),
            ),
        ))

    def conflict_witness(self) -> dict:
        """Status/soak surface: aborted-txn total, decoded top-K contended
        ranges, and the contention block (ISSUE 17) — the per-batch abort
        timeline ring plus spike-trigger state — everything `cli
        contention` joins against the span rings."""
        import json as _json

        return {
            "aborts": int(self.metrics.counter("witness_aborts").value),
            "topk": _json.loads(
                self.metrics.gauge("conflict_witness_topk").value or "[]"
            ),
            "contention": {
                "witness_batches": self._witness_batches,
                "streak": self._contention_streak,
                "spikes": int(
                    self.metrics.counter("contention_spikes").value
                ),
                "timeline": [
                    {
                        "version": v,
                        "batch": b,
                        "aborted": a,
                        "ranges": rngs,
                    }
                    for (v, b, a, rngs) in self._contention_ring
                ],
            },
        }

    # -- double-buffered pipeline (ISSUE 11) ------------------------------
    async def _resolve_pipelined(
        self, req, reply, first_unseen: int, t_enter: float, window: int,
        bspan=None,
    ):
        """The async offload path: admit the batch into the conflict
        set's pipeline and advance the prevVersion chain at DISPATCH —
        the carried device history advances in commit order on device,
        so batch N+1's phase-1 searches already see batch N's committed
        writes while only N's host-side work (verdict sync, mirror
        apply, reply) is deferred.  Completions run strictly in version
        order: a successor's submit pushes the oldest out once the
        pipeline exceeds its depth bound (its sync overlaps OUR device
        compute, its mirror apply runs under it too), and the idle
        flush drains the tail when traffic pauses."""
        from ..flow.spans import use_span

        with use_span(bspan):
            # Synchronous section: the submit's encode/dispatch/device
            # spans (engine + ConflictSet) parent to this batch's span.
            entry = self.conflicts.pipeline_submit(
                req.transactions, req.version, req.version - window
            )
        ctx = _ParkedResolve(entry, req, reply, first_unseen, t_enter,
                             span=bspan)
        self._pipe_ctx.append(ctx)
        self.version.set(req.version)
        self.metrics.histogram("pipeline_inflight_depth").add(
            len(self._pipe_ctx)
        )
        self.metrics.gauge("pipeline_occupancy").set(len(self._pipe_ctx))
        # Submit-then-complete: the host packed/encoded THIS batch while
        # the device computed its predecessors; completing the oldest now
        # syncs it (overlapped) and applies its mirror writes under our
        # own device compute.
        self._pipeline_pump(self.conflicts.pipeline_depth - 1, "device")
        if ctx.finished:
            return
        loop = self.process.network.loop
        flush = g_knobs.server.resolver_pipeline_flush_seconds
        from ..flow.eventloop import first_of

        while not ctx.finished:
            timer = loop.delay(flush)
            await first_of(ctx.future, timer)
            loop.cancel_timer(timer)
            if not ctx.finished:
                # Idle flush: no successor pushed us out within the
                # deadline — drain (in order) through our own batch.
                self._pipeline_flush_through(ctx)

    def _pipeline_flush_through(self, ctx: _ParkedResolve):
        while not ctx.finished:
            self._pipeline_pump(len(self._pipe_ctx) - 1, "flush")

    def _pipeline_pump(self, bound: int, cause: str):
        """Finish parked resolves oldest-first until at most `bound`
        remain.  `cause` feeds the stall accounting: "device" = a
        submit's depth bound forced the completion (the host blocked on
        a device sync — the steady-state overlap), "flush" = the idle
        flush drained it (the device sat idle waiting for host/traffic)."""
        self._pipeline_sweep(cause)
        while len(self._pipe_ctx) > bound:
            # The conflict set completes its OLDEST in-flight batch (a
            # mid-pipeline fault replay may complete several at once);
            # the sweep then finishes every context whose verdicts
            # landed, preserving version order.
            self.conflicts.pipeline_complete_oldest()
            self._pipeline_sweep(cause)

    def _pipeline_sweep(self, cause: str):
        while self._pipe_ctx and self._pipe_ctx[0].entry.done:
            ctx = self._pipe_ctx.popleft()
            self._finish_resolve(ctx, cause)

    def _finish_resolve(self, ctx: _ParkedResolve, cause: str):
        """Completion phase of one pipelined resolve: the synchronous
        path's shared post-verdict bookkeeping (_complete_resolve — one
        implementation, no drift) plus the pipeline's stall accounting.
        Runs synchronously inside whichever handler drives the pump, so
        no other actor can interleave between verdict landing and reply."""
        self._complete_resolve(
            ctx.req, ctx.reply, ctx.entry.statuses, ctx.entry.degraded,
            ctx.first_unseen, ctx.t_enter, span=ctx.span,
            witness=(
                getattr(ctx.entry, "witness", None)
                if self._witness_on else None
            ),
        )
        self._note_device_span(ctx.entry)
        # Stall accounting + the wedged-pipeline black box: a pipeline
        # that is ON but only ever drains by the idle flush achieves zero
        # overlap — after a sustained streak, freeze a flight-recorder
        # artifact (cooldown-gated per resolver) so the state that led
        # here survives the incident.  Only batches that actually went
        # through the device pipeline count (ticket set): CPU-routed
        # pre-completed entries neither stalled on a device sync nor say
        # anything about overlap, so they must not inflate device_stalls
        # or break a flush streak.  "drain" completions (the mirror-check
        # barrier) are neither stall kind and leave the streak alone.
        m = self.metrics
        if ctx.entry.ticket is None:
            pass
        elif cause == "flush":
            m.counter("pipeline_host_stalls").add()
            self._flush_streak += 1
            if (
                self._flush_streak
                >= g_knobs.server.resolver_pipeline_stall_batches
            ):
                from ..flow.flight_recorder import maybe_trigger

                captured = maybe_trigger(
                    "pipeline_stall",
                    detail={
                        "streak": self._flush_streak,
                        "depth": getattr(self.conflicts, "pipeline_depth", 1),
                        "version": ctx.req.version,
                    },
                    source=self.metrics.name,
                )
                if captured is not None:
                    # Reset only on an ACTUAL capture: a cooldown-
                    # suppressed attempt must retry at the very next
                    # flush completion, not after another full streak.
                    self._flush_streak = 0
        elif cause == "device":
            m.counter("pipeline_device_stalls").add()
            self._flush_streak = 0
        m.gauge("pipeline_occupancy").set(len(self._pipe_ctx))
        ctx._mark_finished()

    def _note_device_span(self, entry) -> None:
        """Fold one completed device in-flight span into the pipeline
        overlap-efficiency gauge (ISSUE 12): overlapped device time /
        total device time, on the span hub's deterministic event-
        sequence clock.  Device spans complete in dispatch order, so the
        union is maintained with one high-water mark.  The wall-clock
        twin accumulates in the record_wall namespace only (real-mode
        tooling; never a sim-compared snapshot)."""
        sp = getattr(entry, "device_span", None)
        if sp is None or sp.seq is None or sp.end_seq is None:
            return
        if any(k in sp.attrs for k in ("fault", "replayed", "diverged")):
            # Fault/divergence paths end parked device spans at DRAIN
            # time — near-identical intervals whose mutual "overlap" is
            # mirror-replay bookkeeping, not overlapped device compute.
            # Folding them in would report high efficiency exactly when
            # the device did no useful work.
            return
        m = self.metrics
        b, e = sp.seq, sp.end_seq
        self._dev_seq_total += e - b
        hwm = self._dev_seq_hwm
        self._dev_seq_union += e - b if (hwm is None or b >= hwm) else max(
            0, e - hwm
        )
        self._dev_seq_hwm = e if hwm is None else max(hwm, e)
        if self._dev_seq_total > 0:
            m.gauge("pipeline_overlap_efficiency").set(
                round(
                    (self._dev_seq_total - self._dev_seq_union)
                    / self._dev_seq_total,
                    4,
                )
            )
        host = getattr(self.conflicts, "host_phase_seq", 0)
        if host + self._dev_seq_total > 0:
            m.gauge("host_fraction").set(
                round(host / (host + self._dev_seq_total), 4)
            )
        if sp.wall_end is not None:
            wb, we = sp.wall_start, sp.wall_end
            whwm = self._dev_wall_hwm
            covered = we - wb if (whwm is None or wb >= whwm) else max(
                0.0, we - whwm
            )
            self._dev_wall_hwm = we if whwm is None else max(whwm, we)
            m.record_wall("device_span_seconds", we - wb)
            m.record_wall("device_overlap_seconds", (we - wb) - covered)
