"""Resolver role: MVCC conflict decision per version window.

Ref: Resolver.actor.cpp resolveBatch :71 — per-proxy ordering by prevVersion
(:104-115 via NotifiedVersion), ConflictBatch over the ConflictSet
(:140-153), window GC at version - MAX_WRITE_TRANSACTION_LIFE_VERSIONS
(:153).  The conflict backend is pluggable (conflict.api.ConflictSet):
"cpu", "jax", "hybrid", or a mesh-sharded set from parallel/ — the
north-star swap point (BASELINE.json).
"""

from __future__ import annotations

from ..conflict.api import ConflictSet
from ..flow.asyncvar import NotifiedVersion
from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream
from .interfaces import (
    ResolveTransactionBatchReply,
    ResolveTransactionBatchRequest,
    ResolverInterface,
)


class Resolver:
    def __init__(
        self,
        process: SimProcess,
        backend: str = "cpu",
        epoch_begin_version: int = 0,
        conflict_set: ConflictSet = None,
        epoch: int = 0,
    ):
        self.process = process
        self.epoch = epoch
        self.conflicts = conflict_set or ConflictSet(
            backend=backend, oldest_version=epoch_begin_version
        )
        self.version = NotifiedVersion(epoch_begin_version)
        self.total_resolved = 0
        self._stream = RequestStream(process, "resolve", well_known=True)
        process.spawn(self._serve(), "resolver")

    def interface(self) -> ResolverInterface:
        return ResolverInterface(resolve=self._stream.ref())

    async def _serve(self):
        while True:
            req, reply = await self._stream.pop()
            self.process.spawn(self._resolve_one(req, reply), "resolve_batch")

    async def _resolve_one(self, req: ResolveTransactionBatchRequest, reply):
        if req.epoch != self.epoch:
            reply.send_error("operation_failed")  # stale generation's proxy
            return
        # Order batches by the sequencer's prevVersion chain: a batch may
        # arrive before its predecessor (ref :104-115).
        await self.version.when_at_least(req.prev_version)
        if req.version > self.version.get():
            batch = self.conflicts.new_batch()
            for tr in req.transactions:
                batch.add_transaction(tr)
            window = g_knobs.server.max_write_transaction_life_versions
            statuses = batch.detect_conflicts(
                now=req.version, new_oldest_version=req.version - window
            )
            self.total_resolved += len(statuses)
            self.version.set(req.version)
            reply.send(ResolveTransactionBatchReply(committed=statuses))
        else:
            # Duplicate/replayed batch (proxy retry after timeout): the
            # reference answers from its per-proxy reply cache; with a
            # single proxy a duplicate can only be a stale retry.
            reply.send_error("operation_failed")
