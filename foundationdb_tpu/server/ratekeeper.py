"""Ratekeeper: cluster-wide admission control.

Ref: fdbserver/Ratekeeper.actor.cpp — trackStorageServerQueueInfo :138 /
trackTLogQueueInfo :179 sample every log and storage server; updateRate
:251-340 computes a global transactions-per-second limit from the worst
queues (a "spring" that compresses as the lag approaches the limit); proxies
fetch the limit with their GRV loop (rateKeeper :509) and release queued
read-version requests no faster than the budget.

The rebuild's primary signal is version lag (log durable version minus
storage applied version): storage falling behind the log is exactly the
condition the reference's MVCC window protects (reads older than the window
die with transaction_too_old), so admission slows before the window is
overrun.

Overload-aware springs (ISSUE 8) extend the reference's SS/TLog-only view
to the stack's actual bottleneck, the resolver/TPU conflict path:

  resolver_queue   resolve batches in flight or parked on the prevVersion
                   chain (Resolver.queue_depth / the `signals` RPC)
  resolve_latency  recent-window resolve p99 in virtual seconds
  commit_latency   commit p99 reassembled INCREMENTALLY from the
                   latency_chain CommitDebug events (CommitChainSampler);
                   falls back to the proxies' reported sample when the
                   trace collector is file-backed (real mode)
  backend_degraded the PR-3 circuit breaker's backend_state: when verdicts
                   fall back to the CPU mirror the TPS limit contracts to
                   ratekeeper_degraded_tps_fraction of max (optionally
                   clamped to the MEASURED CPU-mirror throughput from
                   ConflictSet.backend_signal() — real mode only, the
                   measurement is wall-clock derived)

`limiting` names whichever signal set the rate; every change of the
binding signal is appended to a replayable `transitions` log (same seed =>
byte-identical), the admission-control analog of the breaker's transition
log, consumed by the soak harness's same-seed replay gate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream, RequestStreamRef


@dataclass
class RateInfo:
    tps: float = 1e9
    batch_tps: float = 1e9  # the lower-priority lane's (tighter) limit
    lag_versions: int = 0
    worst_ss_queue_bytes: int = 0
    worst_tlog_queue_bytes: int = 0
    min_free_bytes: int = 1 << 62
    # Overload-aware signals (ISSUE 8): worst across resolvers/proxies.
    resolver_queue_depth: int = 0
    resolve_p99: float = 0.0
    commit_p99: float = 0.0
    backend_state: str = "ok"  # ok | degraded | probing (worst resolver)
    grv_queue_depth: int = 0  # worst proxy-reported GRV admission queue
    mirror_divergence: int = 0  # total confirmed mirror divergences
    # Shard-granular fault domains (ISSUE 15): the BINDING degraded
    # resolver's (degraded, total) shard counts; 0/0 when nothing is
    # degraded OR the binding degraded resolver is single-device (the
    # whole-lane clamp then applies).
    shards_degraded: int = 0
    shards_total: int = 0
    limiting: str = "none"  # which signal set the rate (for status/qos)


@dataclass
class RatekeeperInterface:
    get_rate: RequestStreamRef = None


@dataclass
class Signals:
    """One sample of every spring input (see _collect_signals)."""

    lag: int = 0
    ss_queue: int = 0
    tlog_queue: int = 0
    free: int = 1 << 62
    resolver_queue: int = 0
    resolve_p99: float = 0.0
    commit_p99: float = 0.0
    backend_state: str = "ok"
    cpu_mirror_tps: float = 0.0  # measured; 0.0 = unknown
    grv_queue_depth: int = 0
    # Summed confirmed mirror/device divergences across resolvers
    # (ISSUE 9).  Informational — each one already opened that
    # resolver's breaker, so backend_state carries the spring.
    mirror_divergence: int = 0
    # Shard-granular degradation (ISSUE 15): the BINDING degraded
    # resolver's shard counts (_binding_shard_fraction) — the degraded
    # cap then contracts only the sick fraction of the keyspace instead
    # of the whole lane; 0/0 = whole-lane clamp.
    shards_degraded: int = 0
    shards_total: int = 0
    # RPC mode only: a whole commit-critical role class (every tlog, or
    # every storage) is unreachable — the cluster is mid-recovery.
    unreachable: bool = False


class CommitChainSampler:
    """Incremental latency_chain consumer: reassembles the CommitDebug
    total stage (client Before -> After) from the global IN-MEMORY trace
    collector, one pass over only the events that arrived since the last
    sample, into a sliding window whose exact p99 feeds the
    commit_latency spring.  Deterministic by construction (virtual-time
    event stamps, no reservoir).  Returns None when the collector is
    file-backed (events spooled, not retained — real mode) or nothing
    observed yet.

    OPEN chains are a signal too: a commit whose Before has no After yet
    is IN the pipeline, and during a grey failure (one-directional clog:
    the request landed, the reply is stalled) the completed-duration
    window goes quiet exactly when latency is worst.  With `now`, the age
    of the oldest open chain folds into the p99 (max-combine), so a
    wedged pipeline registers while it is wedged.  Failed attempts close
    their chain via NativeAPI.commit.Error (never entering the completed
    window), and opens older than `horizon` are pruned — an abandoned
    chain (client killed mid-commit) cannot hold the signal up forever."""

    WINDOW = 128
    FROM = "NativeAPI.commit.Before"
    TO = "NativeAPI.commit.After"
    ERR = "NativeAPI.commit.Error"

    def __init__(self):
        from collections import deque

        self._col = None
        self._cursor = 0
        self._open: dict = {}  # debug id -> Before time
        self._window = deque(maxlen=self.WINDOW)

    def sample(
        self, now: Optional[float] = None, horizon: Optional[float] = None
    ) -> Optional[float]:
        from ..flow.latency_chain import percentile
        from ..flow.trace import global_collector

        col = global_collector()
        if col.path is not None:
            return None
        if col is not self._col or len(col.events) < self._cursor:
            # New or cleared collector: restart the incremental scan.
            self._col, self._cursor = col, 0
            self._open.clear()
            self._window.clear()
        events = col.events
        for i in range(self._cursor, len(events)):
            e = events[i]
            if e.get("Type") != "CommitDebug":
                continue
            did, loc = e.get("ID"), e.get("Location")
            if did is None:
                continue
            if loc == self.FROM:
                self._open.setdefault(did, e["Time"])
            elif loc == self.TO:
                t0 = self._open.pop(did, None)
                if t0 is not None and e["Time"] >= t0:
                    self._window.append(e["Time"] - t0)
            elif loc == self.ERR:
                self._open.pop(did, None)  # attempt failed: not a wedge
        self._cursor = len(events)
        if now is not None and horizon is not None:
            for k in [
                k for k, t0 in self._open.items() if now - t0 > horizon
            ]:
                del self._open[k]
        if len(self._open) > 1024:
            # Commits that never resolved (client died mid-pipeline):
            # drop the oldest half, deterministically (insertion order).
            for k in list(self._open)[: len(self._open) - 512]:
                del self._open[k]
        p99 = percentile(list(self._window), 0.99)
        if now is not None and self._open:
            oldest_age = now - min(self._open.values())
            p99 = max(p99 or 0.0, oldest_age)
        return p99


# Construction-order ids (deterministic under the sim, unlike id()):
# the flight-recorder cooldown key for concurrent distinct generations.
import itertools

_RK_SEQ = itertools.count()


class Ratekeeper:
    def __init__(
        self,
        process: SimProcess,
        tlogs: List[object] = (),  # TLog role objects (direct metric access)
        storages: List[object] = (),
        sample_interval: float = 0.25,
        fs=None,  # SimFileSystem: enables the disk-free spring
        tlog_ifaces: List[object] = (),  # RPC mode (recruited ratekeeper):
        storage_ifaces: List[object] = (),  # polls metrics like the ref's
        # trackStorageServerQueueInfo / trackTLogQueueInfo actors.
        resolvers: List[object] = (),  # Resolver role objects (in-process)
        resolver_ifaces: List[object] = (),  # RPC mode: `signals` probes
        proxies: List[object] = (),  # Proxy role objects (in-process)
    ):
        self.process = process
        self.rk_id = next(_RK_SEQ)
        self.tlogs = list(tlogs)
        self.storages = list(storages)
        self.tlog_ifaces = list(tlog_ifaces)
        self.storage_ifaces = list(storage_ifaces)
        self.resolvers = list(resolvers)
        self.resolver_ifaces = list(resolver_ifaces)
        self.proxies = list(proxies)
        self.fs = fs
        self.sample_interval = sample_interval
        self.rate = RateInfo(tps=g_knobs.server.ratekeeper_max_tps)
        self._chain_sampler = CommitChainSampler()
        # Latest per-proxy report riding the rate fetch, stamped with its
        # arrival time: proxy_id -> (loop.now(), GetRateInfoRequest).  A
        # proxy that stops fetching (removed, dead generation) must not
        # leave a stale incident-era report driving the commit_latency
        # spring forever — reports expire after _REPORT_TTL seconds.
        self._proxy_reports: dict = {}
        # Replayable admission log: [sample_seq, from_limiting, to_limiting,
        # tps rounded] appended whenever the binding signal changes.  Same
        # seed => byte-identical (the soak harness's replay gate).  Bounded:
        # a week-scale real deployment flapping at a spring target must not
        # grow memory forever — the deque drops the oldest entries, and
        # same-seed runs cap identically so the replay gate still holds.
        from collections import deque

        self.sample_seq = 0
        self.transitions = deque(maxlen=4096)
        # Admission telemetry registry (ISSUE 10): the rate decision and
        # every spring input as gauges, sampled into the time-series ring
        # so a flight-recorder capture shows what admission was doing in
        # the window BEFORE a trigger — not just the post-incident rate.
        from ..flow.metrics import MetricsRegistry
        from ..flow.timeseries import spawn_sampler

        self.metrics = MetricsRegistry("Ratekeeper", rng=process.network.loop.rng)
        self.metrics.counter("limiting_changes")
        for _g in ("tps", "batch_tps", "lag_versions", "ss_queue_bytes",
                   "tlog_queue_bytes", "resolver_queue_depth",
                   "grv_queue_depth", "commit_p99_ms", "resolve_p99_ms"):
            self.metrics.gauge(_g)
        self._stream = RequestStream(process, "rk_get_rate", well_known=True)
        process.spawn_observed(self._update_loop(), "rk_update")
        process.spawn_observed(self._serve(), "rk_serve")
        spawn_sampler(process, "Ratekeeper", self.metrics)

    # Proxies fetch at most every 0.1s (the GRV loop's fetch throttle);
    # several missed intervals means the proxy is gone, not slow.
    _REPORT_TTL = 2.0

    def interface(self) -> RatekeeperInterface:
        return RatekeeperInterface(get_rate=self._stream.ref())

    def _live_reports(self, now: float) -> list:
        """Un-expired proxy reports; expired entries are dropped in place."""
        dead = [
            pid
            for pid, (t, _r) in self._proxy_reports.items()
            if now - t > self._REPORT_TTL
        ]
        for pid in dead:
            del self._proxy_reports[pid]
        return [r for _t, r in self._proxy_reports.values()]

    def transition_log_json(self) -> str:
        """Canonical byte form of the admission transition log — what the
        soak same-seed replay gate compares."""
        import json

        return json.dumps(list(self.transitions), separators=(",", ":"))

    @staticmethod
    def _spring(x: float, target: float, spring: float) -> float:
        """The spring: full rate up to `target`, compressing linearly to
        zero over `spring` beyond it (ref updateRate's
        (targetBytes - queueBytes) / springBytes shaping, :251-340)."""
        if x <= target:
            return 1.0
        return max(0.0, 1.0 - (x - target) / spring)

    @staticmethod
    def _free_factor(free: float, target: float, minimum: float) -> float:
        """Full rate while free space >= target, zero at <= minimum,
        linear between (ref: the MIN_FREE_SPACE clamp in updateRate)."""
        if free >= target:
            return 1.0
        if free <= minimum:
            return 0.0
        return (free - minimum) / (target - minimum)

    async def _collect_signals(self) -> Signals:
        """Every spring input in one sample, from direct role objects
        (in-process mode) and/or RPC metric probes (recruited mode — ref
        trackStorageServerQueueInfo :138 / trackTLogQueueInfo :179; the
        resolver probes use the cheap `signals` stream)."""
        from ..flow.error import FdbError
        from .interfaces import GetStorageMetricsRequest

        srv = g_knobs.server
        sig = Signals()
        log_vs = [t.durable.get() for t in self.tlogs]
        ss_vs = [s.version.get() for s in self.storages]
        ss_qs = [s.queue_bytes for s in self.storages]
        tl_qs = [getattr(t, "_mem_bytes", 0) for t in self.tlogs]
        tl_ok = 0
        for tl in self.tlog_ifaces:
            try:
                m = await tl.metrics.get_reply(self.process, None)
                log_vs.append(m.durable_version)
                tl_qs.append(m.queue_bytes)
                tl_ok += 1
            except FdbError:
                continue  # unreachable log: recovery is the real handler
        ss_ok = 0
        for ss in self.storage_ifaces:
            try:
                m = await ss.get_storage_metrics.get_reply(
                    self.process,
                    GetStorageMetricsRequest(signals_only=True),
                )
                ss_vs.append(m.version)
                ss_qs.append(m.queue_bytes)
                ss_ok += 1
            except FdbError:
                continue
        # A WHOLE commit-critical role class unreachable (every log, or
        # every storage we poll) means the cluster is mid-recovery: floor
        # admission instead of keeping the last healthy rate — the GRV
        # lane must not pile a backlog onto a generation that is being
        # replaced (the springs cannot see a stall their probes can't
        # reach).  RPC (recruited) mode only; in-process mode reads role
        # objects directly and never loses them.
        sig.unreachable = bool(
            (self.tlog_ifaces and tl_ok == 0)
            or (self.storage_ifaces and ss_ok == 0)
        )
        log_v = max(log_vs, default=0)
        ss_v = min(ss_vs, default=log_v)
        sig.lag = max(0, log_v - ss_v)
        sig.ss_queue = max(ss_qs, default=0)
        sig.tlog_queue = max(tl_qs, default=0)
        if self.fs is not None:
            used: dict = {}
            for (mid, _name), f in self.fs._files.items():
                used[mid] = used.get(mid, 0) + len(f.durable)
            # Direct-object mode knows which machines host roles; RPC mode
            # (recruited) conservatively covers every machine with files.
            roles = {
                p.process.machine.machine_id
                for p in list(self.tlogs) + list(self.storages)
            } or set(used)
            cap = srv.sim_disk_capacity_bytes
            for mid in roles:
                sig.free = min(sig.free, max(0, cap - used.get(mid, 0)))
        # Resolver signals: worst queue/latency, worst backend state,
        # SLOWEST measured CPU mirror (the binding one when degraded).
        states = {"ok": 0, "probing": 1, "degraded": 2}
        worst_state = "ok"
        mirror_tps = 0.0
        snaps = [r.signal_snapshot() for r in self.resolvers]
        for ri in self.resolver_ifaces:
            if getattr(ri, "signals", None) is None:
                continue
            try:
                snaps.append(await ri.signals.get_reply(self.process, None))
            except FdbError:
                continue  # dead resolver: recovery replaces it
        for s in snaps:
            sig.resolver_queue = max(sig.resolver_queue, s.queue_depth)
            sig.resolve_p99 = max(sig.resolve_p99, s.resolve_p99)
            sig.mirror_divergence += getattr(s, "mirror_divergence", 0)
            if states[s.backend_state] > states[worst_state]:
                worst_state = s.backend_state
            if s.backend_state != "ok" and s.cpu_mirror_tps > 0:
                mirror_tps = (
                    s.cpu_mirror_tps
                    if mirror_tps == 0.0
                    else min(mirror_tps, s.cpu_mirror_tps)
                )
        sig.backend_state = worst_state
        sig.cpu_mirror_tps = mirror_tps
        # Shard-granular detail (ISSUE 15): the BINDING degraded
        # resolver's sick fraction (see _binding_shard_fraction).
        sig.shards_degraded, sig.shards_total = (
            self._binding_shard_fraction(snaps)
        )
        # Commit latency: the incremental latency_chain reassembly when the
        # in-memory collector is live; else the proxies' passive samples
        # (direct role objects, or the reports riding their rate fetches).
        # The horizon bounds how long an open (wedged/abandoned) chain can
        # age the signal: past it the chain is pruned, so the spring
        # releases within one horizon of the stall resolving.
        loop = self.process.network.loop
        horizon = 2.0 * (
            srv.ratekeeper_target_commit_p99
            + srv.ratekeeper_spring_commit_p99
        )
        p99 = self._chain_sampler.sample(now=loop.now(), horizon=horizon)
        reports = self._live_reports(loop.now())
        if p99 is None:
            candidates = [r.commit_p99 for r in reports if r.commit_p99 > 0]
            for p in self.proxies:
                sample = getattr(p, "latency_samples", {}).get("commit")
                v = sample.percentile(0.99) if sample is not None else None
                if v:
                    candidates.append(v)
            p99 = max(candidates, default=0.0)
        sig.commit_p99 = p99 or 0.0
        sig.grv_queue_depth = max(
            (r.grv_queue_depth for r in reports), default=0
        )
        return sig

    def _limit(self, sig: Signals, target_frac: float):
        """TPS limit for one priority lane: min over every signal's spring
        at `target_frac` of the configured targets (the batch lane runs the
        same springs at tighter targets — ref the separate batch limiter)."""
        srv = g_knobs.server
        factors = {
            "ss_lag": self._spring(
                sig.lag,
                srv.ratekeeper_target_lag_versions * target_frac,
                srv.ratekeeper_spring_lag_versions * target_frac,
            ),
            "ss_queue": self._spring(
                sig.ss_queue,
                srv.ratekeeper_target_ss_queue_bytes * target_frac,
                srv.ratekeeper_spring_ss_queue_bytes * target_frac,
            ),
            "tlog_queue": self._spring(
                sig.tlog_queue,
                srv.ratekeeper_target_tlog_queue_bytes * target_frac,
                srv.ratekeeper_spring_tlog_queue_bytes * target_frac,
            ),
            # Free space springs the other way: LOW free compresses.  The
            # batch lane throttles EARLIER (at a higher free watermark).
            "disk_free": self._free_factor(
                sig.free,
                srv.ratekeeper_target_free_bytes / target_frac,
                srv.ratekeeper_min_free_bytes,
            ),
            # Resolver-path springs (ISSUE 8): queue depth in batches and
            # the recent-window resolve p99 in virtual seconds.
            "resolver_queue": self._spring(
                sig.resolver_queue,
                srv.ratekeeper_target_resolver_queue * target_frac,
                srv.ratekeeper_spring_resolver_queue * target_frac,
            ),
            "resolve_latency": self._spring(
                sig.resolve_p99,
                srv.ratekeeper_target_resolve_p99 * target_frac,
                srv.ratekeeper_spring_resolve_p99 * target_frac,
            ),
            "commit_latency": self._spring(
                sig.commit_p99,
                srv.ratekeeper_target_commit_p99 * target_frac,
                srv.ratekeeper_spring_commit_p99 * target_frac,
            ),
            "backend_degraded": self._degraded_factor(sig, target_frac),
            # Mid-recovery floor (see _collect_signals.unreachable): 0.0
            # compresses the lane to ratekeeper_min_tps until a healthy
            # generation's ratekeeper replaces this one.
            "recovering": 0.0 if sig.unreachable else 1.0,
        }
        limiting = min(factors, key=lambda k: factors[k])
        factor = factors[limiting]
        tps = max(srv.ratekeeper_min_tps, srv.ratekeeper_max_tps * factor)
        return tps, (limiting if factor < 1.0 else "none")

    @staticmethod
    def _binding_shard_fraction(snaps) -> tuple:
        """(shards_degraded, shards_total) of the BINDING degraded
        resolver — the one whose sick fraction is largest — considering
        only resolvers that are actually degraded/probing: a HEALTHY
        mesh-sharded resolver's 0/N detail must never dilute another
        resolver's clamp.  A degraded resolver WITHOUT shard detail
        (single-device) is the whole lane — returns (0, 0), which
        _degraded_factor treats as the plain whole-lane clamp, the most
        conservative, so it overrides any proportional detail."""
        best = None  # (deg, tot) of the worst sick fraction seen
        for s in snaps:
            if s.backend_state == "ok":
                continue
            tot = getattr(s, "shards_total", 0)
            deg = getattr(s, "shards_degraded", 0)
            if tot <= 0:
                return (0, 0)  # whole lane: nothing binds harder
            if best is None or deg * best[1] > best[0] * tot:
                best = (deg, tot)
        return best if best is not None else (0, 0)

    @staticmethod
    def _degraded_factor(sig: Signals, target_frac: float) -> float:
        """Not a spring but a cap: while the device circuit is open (or
        probing) and verdicts fall back to the CPU mirror, the lane's rate
        contracts to ratekeeper_degraded_tps_fraction of max — the GRV
        lane must not pile requests onto a degraded resolver.  With
        ratekeeper_use_measured_cpu_tps (real mode; the measurement is
        wall-clock derived and would break same-seed replay in sim) the
        cap additionally clamps to 80% of the measured CPU-mirror
        throughput so admission tracks what the mirror actually
        sustains.

        Shard-granular fault domains (ISSUE 15): when the degraded
        resolver is mesh-sharded, only shards_degraded of shards_total
        key ranges fell back to their mirrors — the healthy shards keep
        full device throughput — so the cap contracts PROPORTIONALLY:
        ((total - degraded) + degraded * frac) / total.  A single-device
        resolver (shards_total == 0) keeps the whole-lane clamp."""
        if sig.backend_state == "ok":
            return 1.0
        srv = g_knobs.server
        frac = srv.ratekeeper_degraded_tps_fraction
        if srv.ratekeeper_use_measured_cpu_tps and sig.cpu_mirror_tps > 0:
            frac = min(
                frac, 0.8 * sig.cpu_mirror_tps / srv.ratekeeper_max_tps
            )
        if sig.shards_total > 0:
            deg = min(sig.shards_degraded, sig.shards_total)
            frac = (
                (sig.shards_total - deg) + deg * frac
            ) / sig.shards_total
        return max(0.0, frac * target_frac)

    async def _update_loop(self):
        """Ref updateRate :251-340: springs on worst storage queue, worst
        tlog queue, version lag, free disk, and the resolver/device path;
        a separate tighter batch lane."""
        loop = self.process.network.loop
        while True:
            await loop.delay(self.sample_interval)
            sig = await self._collect_signals()
            tps, limiting = self._limit(sig, 1.0)
            batch_tps, _ = self._limit(
                sig, g_knobs.server.ratekeeper_batch_target_fraction
            )
            self.sample_seq += 1
            if limiting != self.rate.limiting:
                self.transitions.append(
                    [self.sample_seq, self.rate.limiting, limiting,
                     round(tps, 3)]
                )
                self.metrics.counter("limiting_changes").add()
                # Marker span (ISSUE 12): admission transitions on the
                # same timeline as the commit-path spans they throttle.
                from ..flow.spans import instant

                instant(
                    "ratekeeper.limiting", role="Ratekeeper",
                    attrs={"from": self.rate.limiting, "to": limiting,
                           "tps": round(tps, 3)},
                )
                # Flight-recorder trigger (ISSUE 10): the binding signal
                # changed — freeze the window that explains why.  The
                # per-kind cooldown keeps a flapping spring from churning
                # the capture ring; "-> none" (release) never triggers.
                if limiting != "none":
                    from ..flow.flight_recorder import maybe_trigger

                    maybe_trigger(
                        "ratekeeper_limiting",
                        detail={"from": self.rate.limiting, "to": limiting,
                                "tps": round(tps, 3)},
                        # Thunk: the (up to 4096-entry) log is copied only
                        # for captures the cooldown lets through.
                        transitions=lambda: [
                            list(t) for t in self.transitions
                        ],
                        source=self.rk_id,  # per-generation cooldown
                    )
            g = self.metrics.gauge
            g("tps").set(round(tps, 3))
            g("batch_tps").set(round(batch_tps, 3))
            g("lag_versions").set(sig.lag)
            g("ss_queue_bytes").set(sig.ss_queue)
            g("tlog_queue_bytes").set(sig.tlog_queue)
            g("resolver_queue_depth").set(sig.resolver_queue)
            g("grv_queue_depth").set(sig.grv_queue_depth)
            # Milliseconds rounded: a gauge sampled into the time series
            # should not carry float noise digits.
            g("commit_p99_ms").set(round(sig.commit_p99 * 1e3, 3))
            g("resolve_p99_ms").set(round(sig.resolve_p99 * 1e3, 3))
            self.rate = RateInfo(
                tps=tps,
                batch_tps=batch_tps,
                lag_versions=sig.lag,
                worst_ss_queue_bytes=sig.ss_queue,
                worst_tlog_queue_bytes=sig.tlog_queue,
                min_free_bytes=sig.free,
                resolver_queue_depth=sig.resolver_queue,
                resolve_p99=sig.resolve_p99,
                commit_p99=sig.commit_p99,
                backend_state=sig.backend_state,
                grv_queue_depth=sig.grv_queue_depth,
                mirror_divergence=sig.mirror_divergence,
                shards_degraded=sig.shards_degraded,
                shards_total=sig.shards_total,
                limiting=limiting,
            )

    async def _serve(self):
        loop = self.process.network.loop
        while True:
            req, reply = await self._stream.pop()
            if req is not None:
                # The proxy's demand report rides its fetch (ref:
                # GetRateInfoRequest.totalReleasedTransactions).
                self._proxy_reports[req.proxy_id] = (loop.now(), req)
            reply.send(self.rate)
