"""Ratekeeper: cluster-wide admission control.

Ref: fdbserver/Ratekeeper.actor.cpp — trackStorageServerQueueInfo :138 /
trackTLogQueueInfo :179 sample every log and storage server; updateRate
:251-340 computes a global transactions-per-second limit from the worst
queues (a "spring" that compresses as the lag approaches the limit); proxies
fetch the limit with their GRV loop (rateKeeper :509) and release queued
read-version requests no faster than the budget.

The rebuild's primary signal is version lag (log durable version minus
storage applied version): storage falling behind the log is exactly the
condition the reference's MVCC window protects (reads older than the window
die with transaction_too_old), so admission slows before the window is
overrun.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream, RequestStreamRef


@dataclass
class RateInfo:
    tps: float = 1e9
    lag_versions: int = 0


@dataclass
class RatekeeperInterface:
    get_rate: RequestStreamRef = None


class Ratekeeper:
    def __init__(
        self,
        process: SimProcess,
        tlogs: List[object],  # TLog role objects (sim: direct metric access)
        storages: List[object],
        sample_interval: float = 0.1,
    ):
        self.process = process
        self.tlogs = tlogs
        self.storages = storages
        self.sample_interval = sample_interval
        self.rate = RateInfo(tps=g_knobs.server.ratekeeper_max_tps)
        self._stream = RequestStream(process, "rk_get_rate", well_known=True)
        process.spawn(self._update_loop(), "rk_update")
        process.spawn(self._serve(), "rk_serve")

    def interface(self) -> RatekeeperInterface:
        return RatekeeperInterface(get_rate=self._stream.ref())

    async def _update_loop(self):
        """Ref updateRate :251-340, distilled: spring on worst version lag."""
        loop = self.process.network.loop
        srv = g_knobs.server
        while True:
            await loop.delay(self.sample_interval)
            log_v = max((t.durable.get() for t in self.tlogs), default=0)
            ss_v = min((s.version.get() for s in self.storages), default=log_v)
            lag = max(0, log_v - ss_v)
            target = srv.ratekeeper_target_lag_versions
            spring = srv.ratekeeper_spring_lag_versions
            if lag <= target:
                factor = 1.0
            else:
                factor = max(0.0, 1.0 - (lag - target) / spring)
            self.rate = RateInfo(
                tps=max(srv.ratekeeper_min_tps, srv.ratekeeper_max_tps * factor),
                lag_versions=lag,
            )

    async def _serve(self):
        while True:
            _req, reply = await self._stream.pop()
            reply.send(self.rate)
