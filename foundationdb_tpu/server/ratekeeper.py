"""Ratekeeper: cluster-wide admission control.

Ref: fdbserver/Ratekeeper.actor.cpp — trackStorageServerQueueInfo :138 /
trackTLogQueueInfo :179 sample every log and storage server; updateRate
:251-340 computes a global transactions-per-second limit from the worst
queues (a "spring" that compresses as the lag approaches the limit); proxies
fetch the limit with their GRV loop (rateKeeper :509) and release queued
read-version requests no faster than the budget.

The rebuild's primary signal is version lag (log durable version minus
storage applied version): storage falling behind the log is exactly the
condition the reference's MVCC window protects (reads older than the window
die with transaction_too_old), so admission slows before the window is
overrun.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream, RequestStreamRef


@dataclass
class RateInfo:
    tps: float = 1e9
    batch_tps: float = 1e9  # the lower-priority lane's (tighter) limit
    lag_versions: int = 0
    worst_ss_queue_bytes: int = 0
    worst_tlog_queue_bytes: int = 0
    min_free_bytes: int = 1 << 62
    limiting: str = "none"  # which signal set the rate (for status/qos)


@dataclass
class RatekeeperInterface:
    get_rate: RequestStreamRef = None


class Ratekeeper:
    def __init__(
        self,
        process: SimProcess,
        tlogs: List[object] = (),  # TLog role objects (direct metric access)
        storages: List[object] = (),
        sample_interval: float = 0.25,
        fs=None,  # SimFileSystem: enables the disk-free spring
        tlog_ifaces: List[object] = (),  # RPC mode (recruited ratekeeper):
        storage_ifaces: List[object] = (),  # polls metrics like the ref's
        # trackStorageServerQueueInfo / trackTLogQueueInfo actors.
    ):
        self.process = process
        self.tlogs = list(tlogs)
        self.storages = list(storages)
        self.tlog_ifaces = list(tlog_ifaces)
        self.storage_ifaces = list(storage_ifaces)
        self.fs = fs
        self.sample_interval = sample_interval
        self.rate = RateInfo(tps=g_knobs.server.ratekeeper_max_tps)
        self._stream = RequestStream(process, "rk_get_rate", well_known=True)
        process.spawn(self._update_loop(), "rk_update")
        process.spawn(self._serve(), "rk_serve")

    def interface(self) -> RatekeeperInterface:
        return RatekeeperInterface(get_rate=self._stream.ref())

    @staticmethod
    def _spring(x: float, target: float, spring: float) -> float:
        """The spring: full rate up to `target`, compressing linearly to
        zero over `spring` beyond it (ref updateRate's
        (targetBytes - queueBytes) / springBytes shaping, :251-340)."""
        if x <= target:
            return 1.0
        return max(0.0, 1.0 - (x - target) / spring)

    @staticmethod
    def _free_factor(free: float, target: float, minimum: float) -> float:
        """Full rate while free space >= target, zero at <= minimum,
        linear between (ref: the MIN_FREE_SPACE clamp in updateRate)."""
        if free >= target:
            return 1.0
        if free <= minimum:
            return 0.0
        return (free - minimum) / (target - minimum)

    async def _signals(self):
        """(lag, worst_ss_queue, worst_tlog_queue, min_free_bytes) from
        direct role objects (in-process mode) and/or RPC metric probes
        (recruited mode — ref trackStorageServerQueueInfo :138 /
        trackTLogQueueInfo :179)."""
        from ..flow.error import FdbError
        from .interfaces import GetStorageMetricsRequest

        srv = g_knobs.server
        log_vs = [t.durable.get() for t in self.tlogs]
        ss_vs = [s.version.get() for s in self.storages]
        ss_qs = [s.queue_bytes for s in self.storages]
        tl_qs = [getattr(t, "_mem_bytes", 0) for t in self.tlogs]
        for tl in self.tlog_ifaces:
            try:
                m = await tl.metrics.get_reply(self.process, None)
                log_vs.append(m.durable_version)
                tl_qs.append(m.queue_bytes)
            except FdbError:
                continue  # unreachable log: recovery is the real handler
        for ss in self.storage_ifaces:
            try:
                m = await ss.get_storage_metrics.get_reply(
                    self.process,
                    GetStorageMetricsRequest(signals_only=True),
                )
                ss_vs.append(m.version)
                ss_qs.append(m.queue_bytes)
            except FdbError:
                continue
        log_v = max(log_vs, default=0)
        ss_v = min(ss_vs, default=log_v)
        lag = max(0, log_v - ss_v)
        ss_q = max(ss_qs, default=0)
        tl_q = max(tl_qs, default=0)
        free = 1 << 62
        if self.fs is not None:
            used: dict = {}
            for (mid, _name), f in self.fs._files.items():
                used[mid] = used.get(mid, 0) + len(f.durable)
            # Direct-object mode knows which machines host roles; RPC mode
            # (recruited) conservatively covers every machine with files.
            roles = {
                p.process.machine.machine_id
                for p in list(self.tlogs) + list(self.storages)
            } or set(used)
            cap = srv.sim_disk_capacity_bytes
            for mid in roles:
                free = min(free, max(0, cap - used.get(mid, 0)))
        return lag, ss_q, tl_q, free

    def _limit(self, lag, ss_q, tl_q, free, target_frac: float):
        """TPS limit for one priority lane: min over every signal's spring
        at `target_frac` of the configured targets (the batch lane runs the
        same springs at tighter targets — ref the separate batch limiter)."""
        srv = g_knobs.server
        factors = {
            "ss_lag": self._spring(
                lag,
                srv.ratekeeper_target_lag_versions * target_frac,
                srv.ratekeeper_spring_lag_versions * target_frac,
            ),
            "ss_queue": self._spring(
                ss_q,
                srv.ratekeeper_target_ss_queue_bytes * target_frac,
                srv.ratekeeper_spring_ss_queue_bytes * target_frac,
            ),
            "tlog_queue": self._spring(
                tl_q,
                srv.ratekeeper_target_tlog_queue_bytes * target_frac,
                srv.ratekeeper_spring_tlog_queue_bytes * target_frac,
            ),
            # Free space springs the other way: LOW free compresses.  The
            # batch lane throttles EARLIER (at a higher free watermark).
            "disk_free": self._free_factor(
                free,
                srv.ratekeeper_target_free_bytes / target_frac,
                srv.ratekeeper_min_free_bytes,
            ),
        }
        limiting = min(factors, key=lambda k: factors[k])
        factor = factors[limiting]
        tps = max(srv.ratekeeper_min_tps, srv.ratekeeper_max_tps * factor)
        return tps, (limiting if factor < 1.0 else "none")

    async def _update_loop(self):
        """Ref updateRate :251-340: springs on worst storage queue, worst
        tlog queue, version lag, and free disk; a separate tighter batch
        lane."""
        loop = self.process.network.loop
        while True:
            await loop.delay(self.sample_interval)
            lag, ss_q, tl_q, free = await self._signals()
            tps, limiting = self._limit(lag, ss_q, tl_q, free, 1.0)
            batch_tps, _ = self._limit(
                lag, ss_q, tl_q, free,
                g_knobs.server.ratekeeper_batch_target_fraction,
            )
            self.rate = RateInfo(
                tps=tps,
                batch_tps=batch_tps,
                lag_versions=lag,
                worst_ss_queue_bytes=ss_q,
                worst_tlog_queue_bytes=tl_q,
                min_free_bytes=free,
                limiting=limiting,
            )

    async def _serve(self):
        while True:
            _req, reply = await self._stream.pop()
            reply.send(self.rate)
