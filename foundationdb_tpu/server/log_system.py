"""Tag -> tlog placement: which logs hold a tag's mutations.

Ref: TagPartitionedLogSystem.actor.cpp:63 — each tag is pushed to a
policy-selected subset of tlogs of size tLogReplicationFactor; peek-merge
cursors read a tag back from any of them.  The rebuild's policy is a stable
hash ring (locality-aware policies arrive with multi-DC): tag t lives on
rf consecutive logs starting at crc32(t) mod n.  Broadcast tags (metadata
`_all`, unsharded `_default`) live on every log so any consumer can peek
its full tag set from one log.
"""

from __future__ import annotations

import zlib
from typing import List, Optional

from ..flow.knobs import g_knobs
from .interfaces import TAG_ALL, TAG_DEFAULT


def tlogs_for_tag(tag: str, n_tlogs: int, rf: Optional[int] = None) -> List[int]:
    if tag in (TAG_ALL, TAG_DEFAULT):
        return list(range(n_tlogs))
    rf = min(rf or g_knobs.server.log_replication_factor, n_tlogs)
    h = zlib.crc32(tag.encode()) % n_tlogs
    return [(h + r) % n_tlogs for r in range(rf)]
