"""Server roles: the rebuild of fdbserver/ (one actor class per role).

Landed: Sequencer (master's version allocator), Proxy (commit pipeline +
GRV), Resolver (pluggable conflict backend incl. the TPU engines), TLog
(in-memory v1), StorageServer (MVCC reads over pulled log data), SimCluster
(single-generation wiring).  Recovery, coordination, data distribution and
the tag-partitioned log system land with the control-plane milestone
(SURVEY.md §7 step 6).
"""

from .cluster import SimCluster
from .proxy import Proxy
from .resolver import Resolver
from .sequencer import Sequencer
from .storage import StorageServer
from .tlog import TLog

__all__ = ["SimCluster", "Proxy", "Resolver", "Sequencer", "StorageServer", "TLog"]
