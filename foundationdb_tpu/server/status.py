"""Cluster status document (ref: Status.actor.cpp clusterGetStatus :1690 —
the giant JSON doc consumed by fdbcli `status` and the StatusWorkload).

The rebuild aggregates live role state into the same overall shape
(cluster/qos/data/workload sections, recovery state, process list); fields
grow as subsystems land.
"""

from __future__ import annotations

from typing import Optional


def cluster_status(cluster) -> dict:
    """Status for a SimCluster or DynamicCluster."""
    doc: dict = {
        "client": {
            "database_status": {"available": True, "healthy": True},
            "coordinators": {},
        },
        "cluster": {},
    }
    cl = doc["cluster"]
    if hasattr(cluster, "controllers"):  # DynamicCluster
        try:
            cc = cluster.acting_controller()
        except RuntimeError:
            cc = None
        doc["client"]["database_status"]["available"] = cc is not None and (
            cc.client_info.get().proxy is not None
        )
        cl["recovery_state"] = {
            "name": "fully_recovered" if cc and cc.client_info.get().proxy else "recruiting",
            "generation": cc.generation if cc else 0,
        }
        cl["cluster_controller"] = cc.process.address if cc else None
        cl["workers"] = sorted(cc.workers) if cc else []
        cl["coordinators"] = [
            c.process.address for c in cluster.coordinators
        ]
        doc["client"]["coordinators"] = {
            "quorum_reachable": sum(
                1 for c in cluster.coordinators if c.process.alive
            )
            > len(cluster.coordinators) // 2,
        }
        roles = {}
        for w in cluster.workers:
            for name, role in w.roles.items():
                roles.setdefault(name, []).append(w.process.address)
        cl["roles"] = roles
        storage = next(
            (w.roles["storage"] for w in cluster.workers if "storage" in w.roles),
            None,
        )
        tlog = next(
            (w.roles["tlog"] for w in cluster.workers if "tlog" in w.roles), None
        )
        proxy = next(
            (w.roles["proxy"] for w in cluster.workers if "proxy" in w.roles), None
        )
    else:  # SimCluster
        cl["recovery_state"] = {"name": "fully_recovered", "generation": 1}
        cl["roles"] = {
            "sequencer": [cluster.master_proc.address],
            "resolver": [p.address for p in cluster.resolver_procs],
            "tlog": [cluster.tlog_proc.address],
            "storage": [cluster.storage_proc.address],
            "proxy": [cluster.proxy_proc.address],
        }
        storage, tlog, proxy = cluster.storage, cluster.tlog, cluster.proxy

    if storage is not None:
        cl["data"] = {
            "storage_version": storage.version.get(),
            "durable_version": storage.durable_version,
            "total_keys_estimate": len(storage.store.sorted_keys)
            + (storage.kvstore.count() if storage.kvstore else 0),
        }
    if tlog is not None:
        cl["logs"] = {
            "log_version": tlog.durable.get(),
            "queue_length": len(tlog.versions),
            "popped_version": tlog.popped,
        }
    if proxy is not None:
        cl["workload"] = {
            "transactions": proxy.stats.snapshot(),
            "committed_version": proxy.committed.get(),
        }
        rk = getattr(proxy, "ratekeeper", None)
        cl["qos"] = {"ratekeeper_enabled": rk is not None}
    return doc
