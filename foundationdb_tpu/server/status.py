"""Cluster status document (ref: Status.actor.cpp clusterGetStatus :1690 —
the giant JSON doc consumed by fdbcli `status` and the StatusWorkload).

The rebuild aggregates live role state into the same overall shape
(cluster/qos/data/workload sections, recovery state, process list); fields
grow as subsystems land.
"""

from __future__ import annotations

from typing import Optional


_ROLE_PLURAL = {
    "proxy": "proxies",
    "resolver": "resolvers",
    "tlog": "tlogs",
    "storage": "storages",
}


def role_objects(cluster, name: str) -> list:
    """Live role objects of one kind across cluster flavors — the ONE
    discovery path status and the CLI share (so the two surfaces can
    never disagree about which roles exist): DynamicCluster
    current-generation worker roles on live processes, SimCluster plural
    lists, durable SimCluster singletons."""
    if hasattr(cluster, "controllers"):  # DynamicCluster
        try:
            cc = cluster.acting_controller()
        except RuntimeError:
            cc = None
        # Only THIS generation's recruited roles on live processes: a
        # spare worker can still hold a frozen role object from an
        # earlier generation (killed+rebooted, not re-recruited), which
        # would wedge min-version / queue aggregates forever.
        # _role_addrs only exists after the first recruitment completes.
        current = set(getattr(cc, "_role_addrs", {}).values() if cc else ())
        return [
            w.roles[name]
            for w in cluster.workers
            if name in w.roles
            and w.process.alive
            and (not current or w.process.address in current)
        ]
    out = list(getattr(cluster, _ROLE_PLURAL[name], None) or [])
    if not out and getattr(cluster, name, None) is not None:
        out = [getattr(cluster, name)]  # durable SimCluster singleton
    return out


def _resolver_section(resolver_roles) -> Optional[dict]:
    """The resolver/tpu status section (ISSUE 2): per-resolver registry
    snapshots plus, when a device engine is live, its kernel telemetry
    (retraces, padding occupancy, fixpoint rounds, grow/rebase).  Roles
    without the telemetry surface (older/foreign conflict sets) degrade
    to the counters they do have."""
    roles = [r for r in resolver_roles if r is not None]
    if not roles:
        return None
    sec: dict = {
        "count": len(roles),
        "total_resolved": sum(
            getattr(r, "total_resolved", 0) for r in roles
        ),
        "backends": sorted(
            {
                getattr(r.conflicts, "backend", type(r.conflicts).__name__)
                for r in roles
                if hasattr(r, "conflicts")
            }
        ),
        "resolvers": {},
    }
    tpu: dict = {}
    for r in roles:
        name = getattr(getattr(r, "process", None), "name", None) or (
            f"resolver{len(sec['resolvers'])}"
        )
        m = getattr(r, "metrics", None)
        if m is not None:
            sec["resolvers"][name] = m.snapshot()
        dm = getattr(getattr(r, "conflicts", None), "device_metrics", None)
        snap = dm() if callable(dm) else None
        if snap:
            tpu[name] = snap
    if tpu:
        sec["tpu"] = tpu
    return sec


def cluster_status(cluster) -> dict:
    """Status for a SimCluster or DynamicCluster."""
    doc: dict = {
        "client": {
            "database_status": {"available": True, "healthy": True},
            "coordinators": {},
        },
        "cluster": {},
    }
    cl = doc["cluster"]
    if hasattr(cluster, "controllers"):  # DynamicCluster
        try:
            cc = cluster.acting_controller()
        except RuntimeError:
            cc = None
        doc["client"]["database_status"]["available"] = cc is not None and (
            cc.client_info.get().proxy is not None
        )
        cl["recovery_state"] = {
            "name": "fully_recovered" if cc and cc.client_info.get().proxy else "recruiting",
            "generation": cc.generation if cc else 0,
        }
        cl["cluster_controller"] = cc.process.address if cc else None
        cl["workers"] = sorted(cc.workers) if cc else []
        cl["coordinators"] = [
            c.process.address for c in cluster.coordinators
        ]
        doc["client"]["coordinators"] = {
            "quorum_reachable": sum(
                1 for c in cluster.coordinators if c.process.alive
            )
            > len(cluster.coordinators) // 2,
        }
        roles = {}
        for w in cluster.workers:
            for name, role in w.roles.items():
                roles.setdefault(name, []).append(w.process.address)
        cl["roles"] = roles
        # Only THIS generation's recruited roles on live processes (see
        # role_objects — a spare worker can still hold a frozen role
        # object from an earlier generation, which would wedge the
        # min-version / queue aggregates forever).
        storages = role_objects(cluster, "storage")
        tlogs = role_objects(cluster, "tlog")
        storage = storages[0] if storages else None
        tlog = tlogs[0] if tlogs else None
        proxy = next(
            (w.roles["proxy"] for w in cluster.workers if "proxy" in w.roles), None
        )
        # Self-driving DD counters (ref: the data-distribution section of
        # Status.actor.cpp + the DDMetrics workload reading it).
        dd = getattr(cc, "dd_role", None) if cc else None
        if dd is not None:
            cl["data_distribution"] = {
                "moves": dd.moves_done,
                "heals": dd.heals_done,
                "splits": dd.splits_done,
                "merges": dd.merges_done,
                "queued": len(dd._queue),
                "in_flight": len(dd._inflight),
                "failed_servers": sorted(dd.failed),
            }
    else:  # SimCluster
        cl["recovery_state"] = {"name": "fully_recovered", "generation": 1}
        cl["roles"] = {
            "sequencer": [cluster.master_proc.address],
            "resolver": [p.address for p in cluster.resolver_procs],
            "tlog": [cluster.tlog_proc.address],
            "storage": [cluster.storage_proc.address],
            "proxy": [cluster.proxy_proc.address],
        }
        storages = list(getattr(cluster, "storages", []) or [cluster.storage])
        tlogs = list(getattr(cluster, "tlogs", []) or [cluster.tlog])
        storage, tlog, proxy = cluster.storage, cluster.tlog, cluster.proxy

    rsec = _resolver_section(role_objects(cluster, "resolver"))
    if rsec is not None:
        cl["resolver"] = rsec

    # Flight-recorder inventory (ISSUE 10): capture counts + the last
    # trigger, never the artifacts themselves (`cli flightrec` dumps
    # those).  Process-global, like the trace collector it spans.
    from ..flow.flight_recorder import global_flight_recorder

    cl["flight_recorder"] = global_flight_recorder().status_section()

    # Span-layer inventory (ISSUE 12): per-role ring sizes + lifetime
    # count, never the spans themselves (`cli trace-export` dumps those).
    from ..flow.spans import global_span_hub

    cl["spans"] = global_span_hub().status_section()

    if storage is not None:
        cl["data"] = {
            "storage_version": storage.version.get(),
            "durable_version": storage.durable_version,
            "total_keys_estimate": len(storage.store.sorted_keys)
            + (storage.kvstore.count() if storage.kvstore else 0),
            # Worst across replicas, like the reference's worst-queue rows.
            "storage_queue_bytes": max(
                (s.queue_bytes for s in storages), default=0
            ),
            # The LAGGING replica bounds the quiet gate, not the leader —
            # but only replicas in the SERVING set count: a spare that owns
            # no range (e.g. re-recruited after its epoch's logs were lost)
            # has nothing to catch up to and would wedge the gate forever.
            "storage_version_min": min(
                (
                    s.version.get()
                    for s in storages
                    if any(v for _b, _e, v in s.owned.items())
                    or any(a for _b, _e, a in s.adding.items())
                ),
                default=storage.version.get(),
            ),
            # Fetches in flight anywhere = data is moving (ref:
            # moving_data.in_flight_bytes).
            "moving_shards": sum(
                sum(1 for _b, _e, a in s.adding.items() if a)
                for s in storages
            ),
        }
    if proxy is not None:
        # Shard map depth (ref: data.partitions_count): the proxy's live
        # keyServers routing map.
        cl.setdefault("data", {})["partitions_count"] = len(
            list(proxy.key_servers.items())
        )
    if tlog is not None:
        cl["logs"] = {
            "log_version": max(t.durable.get() for t in tlogs),
            "queue_length": len(tlog.versions),
            "queue_bytes": max(
                (getattr(t, "_mem_bytes", 0) for t in tlogs), default=0
            ),
            "spilled_through_version": getattr(tlog, "spilled_through", 0),
            "popped_version": tlog.popped,
        }
    if proxy is not None:
        cl["workload"] = {
            "transactions": proxy.stats.snapshot(),
            "committed_version": proxy.committed.get(),
        }
        rk = getattr(proxy, "ratekeeper", None)
        qos = {"ratekeeper_enabled": rk is not None}
        info = getattr(proxy, "last_rate_info", None)
        if info is not None:
            # Ref: the qos section's transactions_per_second_limit /
            # performance_limited_by fields (Status.actor.cpp:1690).
            qos["transactions_per_second_limit"] = info.tps
            qos["batch_transactions_per_second_limit"] = getattr(
                info, "batch_tps", info.tps
            )
            qos["worst_queue_bytes_storage_server"] = getattr(
                info, "worst_ss_queue_bytes", 0
            )
            qos["worst_queue_bytes_log_server"] = getattr(
                info, "worst_tlog_queue_bytes", 0
            )
            qos["released_transactions_behind"] = info.lag_versions
            qos["performance_limited_by"] = getattr(info, "limiting", "none")
            # Overload-aware signals (ISSUE 8): the resolver/TPU-path
            # springs the reference's SS/TLog-only qos never carried.
            qos["worst_resolver_queue_depth"] = getattr(
                info, "resolver_queue_depth", 0
            )
            qos["resolve_latency_p99_seconds"] = getattr(
                info, "resolve_p99", 0.0
            )
            qos["commit_latency_p99_seconds"] = getattr(
                info, "commit_p99", 0.0
            )
            qos["conflict_backend_state"] = getattr(
                info, "backend_state", "ok"
            )
            qos["worst_grv_queue_depth"] = getattr(
                info, "grv_queue_depth", 0
            )
            # Mirror consistency (ISSUE 9): total confirmed mirror/device
            # divergences across resolvers.  Non-zero means a breaker
            # opened on corrupt device state at some point; the current
            # consequence (if any) shows in conflict_backend_state.
            qos["conflict_mirror_divergence"] = getattr(
                info, "mirror_divergence", 0
            )
            # Shard-granular fault domains (ISSUE 15): the BINDING
            # degraded resolver's (degraded, total) shard counts.  Keys
            # present only when that resolver is mesh-sharded, so
            # single-device clusters' status docs are unchanged (and a
            # whole-lane degrade shows in conflict_backend_state alone).
            if getattr(info, "shards_total", 0) > 0:
                qos["conflict_shards_total"] = info.shards_total
                qos["conflict_shards_degraded"] = info.shards_degraded
        # Conflict witnesses (ISSUE 12 satellite; ROADMAP item 4's
        # observability seed): total aborted txns + the merged top-K
        # contended key ranges across resolvers — the qos view of WHERE
        # hot-key contention is burning goodput right now.
        w_aborts = 0
        merged: dict = {}
        # Contention block (ISSUE 17): spike-trigger state + a bounded
        # tail of the per-batch abort timeline, merged across resolvers
        # in (version, resolver) order — deterministic, so same-seed
        # status docs stay byte-identical.
        contention = {"streak": 0, "spikes": 0, "timeline_batches": 0,
                      "recent": []}
        recent: list = []
        for r in role_objects(cluster, "resolver"):
            cw = getattr(r, "conflict_witness", None)
            if not callable(cw):
                continue
            w = cw()
            w_aborts += w["aborts"]
            for b, e, n in w["topk"]:
                merged[(b, e)] = merged.get((b, e), 0) + n
            block = w.get("contention")
            if block:
                contention["streak"] = max(
                    contention["streak"], block["streak"]
                )
                contention["spikes"] += block["spikes"]
                contention["timeline_batches"] += len(block["timeline"])
                recent.extend(block["timeline"])
        recent.sort(key=lambda t: t["version"])
        contention["recent"] = recent[-8:]
        # Host-phase share (ISSUE 19): worst resolver's deterministic
        # host_fraction gauge — encode + mirror_apply + readback seq
        # extent over host + device extent.  The number the columnar
        # mirror / coalesced apply work drives down.
        hf = 0.0
        for r in role_objects(cluster, "resolver"):
            m = getattr(r, "metrics", None)
            if m is not None and "host_fraction" in m.gauges:
                hf = max(hf, m.gauges["host_fraction"].value)
        qos["conflict_host_fraction"] = hf
        qos["conflict_witness_aborts"] = w_aborts
        qos["conflict_witness_topk"] = [
            [b, e, n]
            for (b, e), n in sorted(
                merged.items(), key=lambda kv: (-kv[1], kv[0])
            )[:8]
        ]
        qos["contention"] = contention
        # Shard-mesh block (ISSUE 18): split points + last reshard move
        # per mesh-sharded resolver, so an operator reads the current
        # partition (and who moved it last) straight from status.  Key
        # present only when a mesh-sharded conflict set is live.
        shards: dict = {}
        for r in role_objects(cluster, "resolver"):
            dm = getattr(getattr(r, "conflicts", None), "device_metrics",
                         None)
            if not callable(dm):
                continue
            block = (dm() or {}).get("shards")
            if block is None:
                continue
            name = getattr(getattr(r, "process", None), "name", None) or (
                f"resolver{len(shards)}"
            )
            bal = getattr(r, "shard_balancer", None)
            shards[name] = {
                "total": block["total"],
                "max": block["max"],
                "degraded": block["degraded"],
                "occupancy": block["occupancy"],
                "split_keys": block["split_keys"],
                "moves": block["moves"],
                "last_move": block.get("last_move"),
                "balancer_ticks": 0 if bal is None else len(bal.decisions),
            }
        if shards:
            qos["shards"] = shards
        cl["qos"] = qos
        # Passive latency distributions from the proxy's ContinuousSamples
        # (ref: the commit/GRV latency bands in Status.actor.cpp's qos; the
        # ACTIVE probe is the async latency_probe() below).
        samples = getattr(proxy, "latency_samples", None)
        if samples is not None:
            cl["latency"] = {
                "commit_seconds": samples["commit"].summary(),
                "grv_seconds": samples["grv"].summary(),
            }

    # Processes / machines sections (ref: the per-process and per-machine
    # maps in Status.actor.cpp:1690, fed by ProcessMetrics/MachineMetrics;
    # here read live off the fabric + each process's actor bookkeeping).
    net = getattr(cluster, "net", None)
    if net is not None and hasattr(net, "_procs"):
        role_by_addr: dict = {}
        for rname, addrs in cl.get("roles", {}).items():
            for a in addrs:
                role_by_addr.setdefault(a, []).append(rname)
        processes = {}
        machines: dict = {}
        for addr, p in sorted(net._procs.items()):
            mid = p.machine.machine_id
            processes[addr] = {
                "machine_id": mid,
                "alive": p.alive,
                "roles": sorted(role_by_addr.get(addr, [])),
                "live_actors": len(p._tasks),
                "endpoints": len(p._endpoints),
            }
            m = machines.setdefault(
                mid,
                {
                    "datacenter_id": getattr(p.machine, "dc_id", "dc0"),
                    "processes": 0,
                    "alive_processes": 0,
                },
            )
            m["processes"] += 1
            m["alive_processes"] += 1 if p.alive else 0
        cl["processes"] = processes
        cl["machines"] = machines
    return doc


async def latency_probe(db) -> dict:
    """Active end-to-end probe (ref: Status.actor.cpp's latency_probe
    section — doLatencyProbe running a real transaction): one GRV, one
    read, one commit, each timed in virtual seconds."""
    loop = db.process.network.loop
    out = {}
    tr = db.create_transaction()
    tr.options["access_system_keys"] = True
    t0 = loop.now()
    await tr.get_read_version()
    out["transaction_start_seconds"] = loop.now() - t0
    t0 = loop.now()
    await tr.get(b"\xff/status/probe")
    out["read_seconds"] = loop.now() - t0
    t0 = loop.now()
    rng = loop.rng
    k = b"\xff/status/probe/%016x" % rng.random_int(0, 1 << 62)
    tr.set(k, b"probe")
    tr.clear(k)  # net no-op; the commit round-trip is what's measured
    await tr.commit()
    out["commit_seconds"] = loop.now() - t0
    return out


async def quiet_database(
    db,
    cluster,
    timeout_vt: float = 60.0,
    max_storage_queue_bytes: int = 64 << 10,
    max_lag_versions: int = 1_000_000,
) -> None:
    """Wait until the cluster is quiescent (ref: waitForQuietDatabase,
    QuietDatabase.actor.cpp:371): every storage's queue drained below the
    bound, version lag inside the bound, and no shard move in flight.
    Chaos teardowns gate their consistency checks on this instead of fixed
    virtual-time sleeps.  Raises TimeoutError if never quiet."""
    loop = db.process.network.loop
    deadline = loop.now() + timeout_vt
    while True:
        doc = cluster_status(cluster)
        cl = doc["cluster"]
        data = cl.get("data", {})
        logs = cl.get("logs", {})
        # Sections absent (e.g. mid-recovery, roles not yet live) is NOT
        # quiet — the gate must never pass vacuously.
        quiet = (
            "storage_version_min" in data
            and "log_version" in logs
            and data.get("storage_queue_bytes", 0) <= max_storage_queue_bytes
            and data.get("moving_shards", 0) == 0
            and logs["log_version"] - data["storage_version_min"]
            <= max_lag_versions
        )
        if quiet:
            return
        if loop.now() > deadline:
            raise TimeoutError(
                f"database never became quiet: queue="
                f"{data.get('storage_queue_bytes')} moving="
                f"{data.get('moving_shards')} lag="
                f"{logs.get('log_version', 0) - data.get('storage_version_min', 0)}"
                f" sections=({sorted(data)}, {sorted(logs)})"
            )
        await loop.delay(0.25)
