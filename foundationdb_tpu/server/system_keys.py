"""System keyspace (`\xff`) encodings: the shard map lives IN the database.

Ref: fdbclient/SystemData.{h,cpp} — `keyServersKey(k) = \xff/keyServers/ + k`
whose value lists the storage servers for the shard beginning at k, and
fdbserver/ApplyMetadataMutation.h — roles learn metadata changes by watching
these keys in the mutation stream itself, so a shard handoff is serialized
with user commits at an exact version.

Values are pickled lists of storage-server ids (a "team"; replication >1
arrives with the tag-partitioned log).
"""

from __future__ import annotations

import pickle
from typing import List, Optional, Tuple

SYSTEM_PREFIX = b"\xff"
KEY_SERVERS_PREFIX = b"\xff/keyServers/"
KEY_SERVERS_END = b"\xff/keyServers0"  # '0' == '/' + 1
SERVER_LIST_PREFIX = b"\xff/serverList/"
SERVER_LIST_END = b"\xff/serverList0"


def key_servers_key(key: bytes) -> bytes:
    return KEY_SERVERS_PREFIX + key


def key_servers_begin(sys_key: bytes) -> bytes:
    assert sys_key.startswith(KEY_SERVERS_PREFIX), sys_key
    return sys_key[len(KEY_SERVERS_PREFIX):]


def encode_team(storage_ids: List[str]) -> bytes:
    return pickle.dumps(list(storage_ids), protocol=4)


def decode_team(value: Optional[bytes]) -> List[str]:
    return list(pickle.loads(value)) if value else []


def server_list_key(storage_id: str) -> bytes:
    return SERVER_LIST_PREFIX + storage_id.encode()
