"""System keyspace (`\xff`) encodings: the shard map lives IN the database.

Ref: fdbclient/SystemData.{h,cpp} — `keyServersKey(k) = \xff/keyServers/ + k`
whose value names the storage teams for the shard beginning at k, and
fdbserver/ApplyMetadataMutation.h — roles learn metadata changes by watching
these keys in the mutation stream itself, so a shard handoff is serialized
with user commits at an exact version.

Rebuild deviation from the reference encoding: each keyServers entry also
carries the shard's END key.  The reference derives extents from entry
adjacency (it reads the authoritative keyspace back); here every storage
applies metadata purely from the mutation stream, so the record must be
self-contained.  A move in flight is (src, dest, end) with dest non-empty;
a settled shard is (team, [], end).

`\xff/serverList/<id>` maps a storage id to its wire-encoded interface (ref:
serverListKeyFor SystemData.cpp), letting every role resolve ids to
endpoints passively from the stream.
"""

from __future__ import annotations

from typing import List, Tuple

from ..rpc.wire import decode_frame, encode_frame

SYSTEM_PREFIX = b"\xff"
KEY_SERVERS_PREFIX = b"\xff/keyServers/"
KEY_SERVERS_END = b"\xff/keyServers0"  # '0' == '/' + 1
SERVER_LIST_PREFIX = b"\xff/serverList/"
SERVER_LIST_END = b"\xff/serverList0"
# The resolver key-space partition (ref: the keyResolvers map the proxies
# maintain, MasterProxyServer.actor.cpp:185; split points move at an exact
# commit version via ResolutionSplitRequest, ResolverInterface.h:108-131).
RESOLVER_SPLIT_KEY = b"\xff/conf/resolverSplit"

# Database lock record (ref: databaseLockedKey fdbclient/SystemData.cpp —
# lockDatabase writes a UID here; proxies reject non-lock-aware work while
# it is non-empty).  Unlock SETS it empty rather than clearing, keeping
# parse_metadata_mutation's no-CLEAR-interpretation policy.
DB_LOCKED_KEY = b"\xff/dbLocked"

# TimeKeeper samples: wall-clock second -> commit version, written by the
# CC on a fixed cadence (ref: timeKeeperPrefixRange SystemData.cpp:411,
# the timeKeeper actor ClusterController.actor.cpp:1625).  Maps restore
# timestamps to versions (fdbbackup's timeKeeperVersionFromDatetime).
TIME_KEEPER_PREFIX = b"\xff\x02/timeKeeper/map/"
TIME_KEEPER_END = b"\xff\x02/timeKeeper/map0"
TIME_KEEPER_DISABLE_KEY = b"\xff\x02/timeKeeper/disable"


def time_keeper_key(t: int) -> bytes:
    return TIME_KEEPER_PREFIX + int(t).to_bytes(8, "big")


def time_keeper_time(sys_key: bytes) -> int:
    assert sys_key.startswith(TIME_KEEPER_PREFIX), sys_key
    return int.from_bytes(sys_key[len(TIME_KEEPER_PREFIX):], "big")


def key_servers_key(key: bytes) -> bytes:
    return KEY_SERVERS_PREFIX + key


def key_servers_begin(sys_key: bytes) -> bytes:
    assert sys_key.startswith(KEY_SERVERS_PREFIX), sys_key
    return sys_key[len(KEY_SERVERS_PREFIX):]


def encode_key_servers(
    src: List[str], dest: List[str], end: bytes
) -> bytes:
    """Shard record for [begin, end): settled on `src` when `dest` is empty,
    else a move src -> dest in flight (ref: keyServersValue's src/dest
    encoding, SystemData.cpp)."""
    return encode_frame((list(src), list(dest), end))


def decode_key_servers(value: bytes) -> Tuple[List[str], List[str], bytes]:
    src, dest, end = decode_frame(value)
    return list(src), list(dest), end


def server_list_key(storage_id: str) -> bytes:
    return SERVER_LIST_PREFIX + storage_id.encode()


def server_list_id(sys_key: bytes) -> str:
    assert sys_key.startswith(SERVER_LIST_PREFIX), sys_key
    return sys_key[len(SERVER_LIST_PREFIX):].decode()


def encode_server_entry(interface) -> bytes:
    """Wire-codec StorageInterface (refs are plain dataclasses of
    endpoint tokens, registered structs in rpc/wire.py)."""
    return encode_frame(interface)


def decode_server_entry(value: bytes):
    return decode_frame(value)


def bounds_from_split_keys(split_keys: List[bytes]) -> List[tuple]:
    """[(lo, hi_or_None)] per resolver from n-1 split points.  The proxies'
    clipping and the balancer's reconstruction of the partition MUST agree
    byte-for-byte, so this is the single definition."""
    split = list(split_keys)
    return list(zip([b""] + split, split + [None]))


def encode_resolver_split(split_keys: List[bytes]) -> bytes:
    return encode_frame(list(split_keys))


def decode_resolver_split(value: bytes) -> List[bytes]:
    return list(decode_frame(value))


def parse_metadata_mutation(m):
    """Shared ApplyMetadataMutation decoder for every role that watches the
    stream (proxy + storages must agree on the shard map byte-for-byte).

    Returns None (not metadata), ("server", id, StorageInterface),
    ("shard", begin, src, dest, end), or ("resolver_split", [split_keys]).
    CLEAR_RANGE over metadata keys is deliberately not interpreted: DD only
    ever overwrites records (clearing one would silently orphan a range —
    if shard-map compaction ever clears boundary entries, both intercept
    sites change here together)."""
    from ..client.types import MutationType

    if m.type != MutationType.SET_VALUE:
        return None
    if m.param1.startswith(SERVER_LIST_PREFIX):
        return ("server", server_list_id(m.param1), decode_server_entry(m.param2))
    if m.param1.startswith(KEY_SERVERS_PREFIX):
        src, dest, end = decode_key_servers(m.param2)
        return ("shard", key_servers_begin(m.param1), src, dest, end)
    if m.param1 == RESOLVER_SPLIT_KEY:
        return ("resolver_split", decode_resolver_split(m.param2))
    if m.param1 == DB_LOCKED_KEY:
        return ("lock", m.param2)  # empty value = unlocked
    return None
