"""Storage server role: versioned MVCC reads over pulled log data.

Ref: storageserver.actor.cpp — VersionedData :236-260 (MVCC window),
getValueQ :684 / getKeyValues :1182 read path with waitForVersion :631;
update() pulls mutations from the log via peek and applies them in version
order; atomics are applied at the storage server exactly as the client
would (shared fdbclient/Atomic.h semantics -> client/atomic.py).

v1 model: per-key version chains + a version-stamped clear-range list; one
storage process owns the whole key space (sharding arrives with
DataDistribution).  All history is retained in-memory; the durability
milestone adds the persistent engine + window trimming.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Dict, List, Optional, Tuple

from ..client.atomic import apply_atomic
from ..client.types import Mutation, MutationType
from ..flow.asyncvar import NotifiedVersion
from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream
from .interfaces import (
    GetKeyValuesReply,
    GetKeyValuesRequest,
    GetValueReply,
    GetValueRequest,
    StorageInterface,
    TLogInterface,
    TLogPeekRequest,
    TLogPopRequest,
)


class VersionedStore:
    """Per-key version chains + clear-range history (the flat-python stand-in
    for the reference's PTree VersionedMap, fdbclient/VersionedMap.h:43).

    Entries are ordered by (version, seq) where seq is the mutation's index
    within its version, so set-then-clear vs clear-then-set of the same key
    inside one commit resolve exactly as the mutation order says.
    """

    _SEQ_INF = 1 << 62

    def __init__(self):
        # key -> [(version, seq, value-or-None)]
        self.kv: Dict[bytes, List[Tuple[int, int, Optional[bytes]]]] = {}
        self.sorted_keys: List[bytes] = []
        # (version, seq, begin, end)
        self.clears: List[Tuple[int, int, bytes, bytes]] = []

    # -- reads --
    def _latest_clear_over(self, key: bytes, version: int) -> Tuple[int, int]:
        best = (-1, -1)
        for v, s, b, e in self.clears:
            if v <= version and b <= key < e and (v, s) > best:
                best = (v, s)
        return best

    def get(self, key: bytes, version: int) -> Optional[bytes]:
        chain = self.kv.get(key)
        stamp_e, val = (-1, -1), None
        if chain:
            i = bisect_right(chain, (version, self._SEQ_INF)) - 1
            if i >= 0:
                ver, seq, val = chain[i]
                stamp_e = (ver, seq)
        if self._latest_clear_over(key, version) > stamp_e:
            return None
        return val

    def get_range(
        self,
        begin: bytes,
        end: bytes,
        version: int,
        limit: int,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        i = bisect_left(self.sorted_keys, begin)
        j = bisect_left(self.sorted_keys, end)
        keys = self.sorted_keys[i:j]
        if reverse:
            keys = reversed(keys)
        out = []
        for k in keys:
            v = self.get(k, version)
            if v is not None:
                out.append((k, v))
                if len(out) >= limit:
                    break
        return out

    # -- writes (applied in (version, seq) order by the update loop) --
    def set(self, key: bytes, value: bytes, version: int, seq: int = 0):
        chain = self.kv.get(key)
        if chain is None:
            self.kv[key] = [(version, seq, value)]
            insort(self.sorted_keys, key)
        else:
            chain.append((version, seq, value))

    def clear_range(self, begin: bytes, end: bytes, version: int, seq: int = 0):
        self.clears.append((version, seq, begin, end))


class StorageServer:
    def __init__(
        self,
        process: SimProcess,
        tlog: TLogInterface,
        epoch_begin_version: int = 0,
    ):
        self.process = process
        self.tlog = tlog
        self.store = VersionedStore()
        self.version = NotifiedVersion(epoch_begin_version)
        self._gv_stream = RequestStream(process, "get_value")
        self._gkv_stream = RequestStream(process, "get_key_values")
        self._ver_stream = RequestStream(process, "get_version")
        process.spawn(self._update_loop(), "ss_update")
        process.spawn(self._serve_get_value(), "ss_get_value")
        process.spawn(self._serve_get_key_values(), "ss_get_key_values")
        process.spawn(self._serve_get_version(), "ss_get_version")

    def interface(self) -> StorageInterface:
        return StorageInterface(
            get_value=self._gv_stream.ref(),
            get_key_values=self._gkv_stream.ref(),
            get_version=self._ver_stream.ref(),
        )

    # -- write path: pull from the log (ref: storageserver update()) --
    async def _update_loop(self):
        from ..rpc.stream import retry_get_reply

        loop = self.process.network.loop
        while True:
            reply = await retry_get_reply(
                self.tlog.peek,
                self.process,
                TLogPeekRequest(begin_version=self.version.get()),
            )
            for version, mutations in reply.entries:
                if version <= self.version.get():
                    continue
                self._apply(version, mutations)
                self.version.set(version)
            # In-memory engine: applied == durable, pop eagerly (ref: tLogPop
            # once storage has made data durable).
            self.tlog.pop.send(
                self.process, TLogPopRequest(version=self.version.get())
            )
            if not reply.has_more:
                await loop.delay(0.001)  # poll; push-based peek comes later

    def _apply(self, version: int, mutations: List[Mutation]):
        for seq, m in enumerate(mutations):
            if m.type == MutationType.SET_VALUE:
                self.store.set(m.param1, m.param2, version, seq)
            elif m.type == MutationType.CLEAR_RANGE:
                self.store.clear_range(m.param1, m.param2, version, seq)
            elif m.type in (MutationType.NO_OP, MutationType.DEBUG_KEY):
                pass
            else:
                existing = self.store.get(m.param1, version)
                self.store.set(
                    m.param1, apply_atomic(m.type, existing, m.param2), version, seq
                )

    # -- read path --
    async def _wait_for_version(self, version: int):
        """Ref: waitForVersion storageserver.actor.cpp:631."""
        if version > self.version.get() + g_knobs.server.max_versions_in_flight:
            from ..flow.error import FdbError

            raise FdbError("future_version")
        await self.version.when_at_least(version)

    async def _serve_get_value(self):
        while True:
            req, reply = await self._gv_stream.pop()
            self.process.spawn(self._get_value_one(req, reply), "ss_gv")

    async def _get_value_one(self, req: GetValueRequest, reply):
        try:
            await self._wait_for_version(req.version)
        except Exception as e:  # noqa: BLE001
            reply.send_error(getattr(e, "name", "internal_error"))
            return
        reply.send(
            GetValueReply(value=self.store.get(req.key, req.version), version=req.version)
        )

    async def _serve_get_key_values(self):
        while True:
            req, reply = await self._gkv_stream.pop()
            self.process.spawn(self._get_key_values_one(req, reply), "ss_gkv")

    async def _get_key_values_one(self, req: GetKeyValuesRequest, reply):
        try:
            await self._wait_for_version(req.version)
        except Exception as e:  # noqa: BLE001
            reply.send_error(getattr(e, "name", "internal_error"))
            return
        data = self.store.get_range(
            req.begin, req.end, req.version, req.limit + 1, req.reverse
        )
        more = len(data) > req.limit
        reply.send(
            GetKeyValuesReply(data=data[: req.limit], more=more, version=req.version)
        )

    async def _serve_get_version(self):
        while True:
            _req, reply = await self._ver_stream.pop()
            reply.send(self.version.get())
